"""Checkpoint / resume.

The reference has NO model checkpointing (SURVEY §5.4) — only strategy files
persist (strategy.cc) and weights can be moved via set/get_tensor. The TPU
build makes checkpointing first-class: orbax saves the sharded params /
optimizer state / batch-norm stats / step counter (each chip writes its own
shard — no host gather), and the strategy table is saved alongside in the
reference text schema so a resumed job re-shards identically.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import numpy as np

from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                            save_strategies_to_file)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _opt_layout(model) -> str:
    """Optimizer-state pytree layout: the fused wrappers store state as
    flat per-dtype vectors, so a checkpoint written under one layout
    cannot restore into another (the tree structures differ). Recorded in
    meta.json; restore refuses a mismatch with a clear error instead of
    an opaque tree-structure failure."""
    from flexflow_tpu.runtime.optimizer import (FusedUpdate,
                                                ShardedFusedUpdate)

    opt = model.optimizer
    if isinstance(opt, ShardedFusedUpdate):
        return "sharded_fused"
    if isinstance(opt, FusedUpdate):
        return "fused"
    return "per_leaf"


def _sharded_fused_shardings(model):
    """The sharded-fused flat vector's element order is a pure function
    of (tree structure, leaf shardings, mesh) — record all three so a
    restore onto a DIFFERENT topology is refused instead of silently
    scrambling the moments (same per-dtype length, different
    (leaf, element) mapping)."""
    return {op: {w: str(spec) for w, spec in ws.items()}
            for op, ws in model.optimizer.specs.items()}


def _is_multihost() -> bool:
    return jax.process_count() > 1


def save_checkpoint(model, directory: str, step: Optional[int] = None) -> str:
    """Save model state. Returns the checkpoint path.

    Single-controller: arrays are gathered to host numpy before writing, so
    checkpoints are topology-free — a restore re-shards onto whatever mesh
    the restoring model compiled with.

    Multi-controller (jax.process_count() > 1): arrays are handed to orbax
    as sharded jax.Arrays and EVERY process participates in the save — each
    host writes only its addressable shards (no host gather; a vocab-sharded
    embedding never materializes on one host). All processes must call this
    collectively. Saving the same step twice overwrites (idempotent)."""
    import shutil

    directory = os.path.abspath(directory)
    step = step if step is not None else model._step_count
    path = os.path.join(directory, f"step_{step}")
    multihost = _is_multihost()
    if not multihost or jax.process_index() == 0:
        os.makedirs(directory, exist_ok=True)
        if os.path.exists(path):
            # orbax refuses to overwrite; make saves idempotent
            shutil.rmtree(path)
    if multihost:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ff_ckpt_clean")

    if multihost:
        prep = _strip_none  # keep sharded jax.Arrays; orbax writes per host
    else:
        prep = lambda tree: jax.tree_util.tree_map(
            lambda a: np.asarray(a), _strip_none(tree))
    state = {"params": prep(model.params)}
    if model.opt_state is not None:
        state["opt_state"] = prep(model.opt_state)
    if model.bn_state:
        state["bn_state"] = prep(model.bn_state)
    _checkpointer().save(path, state)

    if not multihost or jax.process_index() == 0:
        meta = {"step": int(step),
                "mesh_shape": model.config.mesh_shape,
                "multihost": multihost,
                "loss_type": model.loss_type.name if model.loss_type else None}
        if "opt_state" in state:  # layout only meaningful when state saved
            meta["opt_layout"] = _opt_layout(model)
            if meta["opt_layout"] == "sharded_fused":
                meta["opt_state_shardings"] = _sharded_fused_shardings(model)
        with open(os.path.join(directory, "meta.json"), "w") as f:
            json.dump(meta, f)
        save_strategies_to_file(os.path.join(directory, "strategy.txt"),
                                model.config.strategies)
    if multihost:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ff_ckpt_done")
    return path


def restore_checkpoint(model, directory: str, step: Optional[int] = None):
    """Restore into a compiled model. Single-controller checkpoints are
    stored as host numpy (see save_checkpoint), so restore re-shards onto
    the restoring model's own mesh regardless of the topology that saved
    them. Under multi-controller, every process calls this collectively and
    orbax restores each array directly into the model's current sharding
    (each host reads only its shards)."""
    directory = os.path.abspath(directory)
    with open(os.path.join(directory, "meta.json")) as f:
        meta = json.load(f)
    step = step if step is not None else meta["step"]
    path = os.path.join(directory, f"step_{step}")

    # absent on pre-r5 and params-only checkpoints (no opt state to
    # mismatch — a weights-export -> fine-tune restore must not be blocked)
    saved_layout = meta.get("opt_layout")
    if saved_layout is not None and model.optimizer is not None:
        if saved_layout != _opt_layout(model):
            raise ValueError(
                f"checkpoint at {directory} stores optimizer state in the "
                f"{saved_layout!r} layout but this model uses "
                f"{_opt_layout(model)!r} (FFConfig.fused_optimizer and the "
                f"sharding strategy determine the layout). Re-compile with "
                f"a matching fused_optimizer setting to restore.")
        if saved_layout == "sharded_fused":
            # same layout kind is not enough: the flat vector's element
            # order depends on (mesh, leaf shardings) — a cross-topology
            # restore would silently scramble the moments
            saved_sh = meta.get("opt_state_shardings")
            cur_sh = _sharded_fused_shardings(model)
            # ordered compare: the flat layout follows mesh AXIS ORDER
            # (P(tuple(axis_names))), so {'data':2,'model':2} and
            # {'model':2,'data':2} are different layouts even though the
            # dicts compare equal (JSON preserves key order)
            mesh_saved = list((meta.get("mesh_shape") or {}).items())
            mesh_cur = list(model.config.mesh_shape.items())
            if (mesh_saved != mesh_cur
                    or (saved_sh is not None and saved_sh != cur_sh)):
                raise ValueError(
                    f"checkpoint at {directory} stores sharded-fused "
                    f"optimizer state for mesh {meta.get('mesh_shape')} "
                    f"with different parameter shardings — the flat state "
                    f"layout is topology-dependent. Re-compile with the "
                    f"saved mesh/strategy, or restore weights only "
                    f"(optimizer=None) and start the optimizer fresh.")

    if _is_multihost():
        import orbax.checkpoint as ocp

        template = {"params": model.params}
        if model.opt_state is not None:
            template["opt_state"] = _strip_none(model.opt_state)
        if model.bn_state:
            template["bn_state"] = model.bn_state
        restore_args = ocp.checkpoint_utils.construct_restore_args(template)
        restored = _checkpointer().restore(path, restore_args=restore_args)
        model.params = restored["params"]
        if "opt_state" in restored and model.optimizer is not None:
            fresh = model.optimizer.init_state(model.params)
            model.opt_state = _merge_sharded(fresh, restored["opt_state"])
        if "bn_state" in restored:
            model.bn_state = restored["bn_state"]
        model._step_count = step
        return step

    restored = _checkpointer().restore(path)
    shardings = model.executor.param_shardings()

    def put(tree, shard_map_):
        out = {}
        for op_name, ws in tree.items():
            out[op_name] = {
                name: jax.device_put(np.asarray(v),
                                     shard_map_.get(op_name, {}).get(name))
                if shard_map_.get(op_name, {}).get(name) is not None
                else jax.device_put(np.asarray(v))
                for name, v in ws.items()}
        return out

    model.params = put(restored["params"], shardings)
    if "opt_state" in restored and model.optimizer is not None:
        fresh = model.optimizer.init_state(model.params)
        model.opt_state = _merge_restored(fresh, restored["opt_state"])
    if "bn_state" in restored:
        model.bn_state = {k: {n: jax.device_put(np.asarray(v))
                              for n, v in s.items()}
                          for k, s in restored["bn_state"].items()}
    model._step_count = step
    # NOTE: the checkpointed strategy file is NOT silently applied — sharding
    # was already resolved in compile(). To resume with the checkpointed
    # strategy, pass import_strategy_file=<dir>/strategy.txt in FFConfig
    # BEFORE compile(). We only warn on divergence here.
    try:
        saved = load_strategies_from_file(
            os.path.join(directory, "strategy.txt"))
        current = model.config.strategies
        def differs(a, b):
            if a.dims != b.dims:
                return True
            # dims alone miss CONTRACT/STAGE divergence (they shard
            # weights, not the output) — compare axis maps when both known
            if a.axis_map is not None and b.axis_map is not None:
                na = {k: v for k, v in a.axis_map.items() if v is not None}
                nb = {k: v for k, v in b.axis_map.items() if v is not None}
                return na != nb
            return False

        diff = [k for k in saved
                if k in current and differs(saved[k], current[k])]
        if diff:
            import sys

            print(f"[checkpoint] WARNING: strategy mismatch vs checkpoint for "
                  f"ops {diff[:5]}{'...' if len(diff) > 5 else ''}; set "
                  f"import_strategy_file before compile() to resume with the "
                  f"saved strategy", file=sys.stderr)
    except FileNotFoundError:
        pass
    return step


def latest_step(directory: str) -> Optional[int]:
    try:
        with open(os.path.join(directory, "meta.json")) as f:
            return json.load(f)["step"]
    except (FileNotFoundError, KeyError):
        return None


def _strip_none(tree):
    if isinstance(tree, dict):
        return {k: _strip_none(v) for k, v in tree.items() if v is not None}
    return tree


def _merge_sharded(fresh, restored):
    """Refill None leaves stripped before a sharded save (restored arrays
    already carry the model's shardings via construct_restore_args)."""
    if isinstance(fresh, dict):
        return {k: _merge_sharded(v, restored[k]) if k in restored else v
                for k, v in fresh.items()}
    if fresh is None:
        return None
    return restored


def _merge_restored(fresh, restored):
    from jax.sharding import NamedSharding

    if isinstance(fresh, dict):
        return {k: _merge_restored(v, restored[k]) if k in restored else v
                for k, v in fresh.items()}
    if fresh is None:
        return None
    arr = np.asarray(restored).astype(np.asarray(fresh).dtype)
    sh = getattr(fresh, "sharding", None)
    if isinstance(sh, NamedSharding):
        return jax.device_put(arr, sh)
    # uncommitted: let jit place it alongside the mesh-sharded params
    import jax.numpy as jnp

    return jnp.asarray(arr)


def auto_resume(model, directory: str) -> int:
    """Slice-preemption recovery (the capability gap SURVEY §5.3 notes in the
    reference: a failed node kills the job with no recovery). Call after
    compile(): restores the newest checkpoint in `directory` when one exists
    and returns its step; returns 0 on a fresh start."""
    step = latest_step(directory)
    if step is None:
        return 0
    restore_checkpoint(model, directory, step=step)
    return step
