"""Checkpoint / resume.

The reference has NO model checkpointing (SURVEY §5.4) — only strategy files
persist (strategy.cc) and weights can be moved via set/get_tensor. The TPU
build makes checkpointing first-class: orbax saves the sharded params /
optimizer state / batch-norm stats / step counter (each chip writes its own
shard — no host gather), and the strategy table is saved alongside in the
reference text schema so a resumed job re-shards identically.

Crash consistency (the preemption story, runtime/resilience.py): each save
lands in ``<dir>/.tmp-step_N`` and becomes ``<dir>/step_N`` via one
``os.replace`` — a kill mid-save leaves only an ignored tmp dir, never a
half-written checkpoint. ``ff_meta.json`` (step, layout guards, supervisor
extras: RNG key, dataloader cursors) is written INSIDE the step dir before
the rename, so a renamed checkpoint is always self-contained; the top-level
``meta.json``/``strategy.txt`` mirror the newest step for older readers.
``latest_step`` scans the ``step_*`` dirs (tmp dirs skipped), and orbax
save/load run under ``resilience.retry`` with ``io_fail`` fault-injection
hooks (FF_FAULT) so the retry path is tier-1-testable.
"""

from __future__ import annotations

import json
import re
import os
from typing import Optional

import jax
import numpy as np

from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                            save_strategies_to_file)
from flexflow_tpu.runtime import faultinject
from flexflow_tpu.runtime.resilience import retry


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _opt_layout(model) -> str:
    """Optimizer-state pytree layout: the fused wrappers store state as
    flat per-dtype vectors, so a checkpoint written under one layout
    cannot restore into another (the tree structures differ). Recorded in
    meta.json; restore refuses a mismatch with a clear error instead of
    an opaque tree-structure failure."""
    from flexflow_tpu.runtime.optimizer import (FusedUpdate,
                                                ShardedFusedUpdate)

    opt = model.optimizer
    if isinstance(opt, ShardedFusedUpdate):
        return "sharded_fused"
    if isinstance(opt, FusedUpdate):
        return "fused"
    return "per_leaf"


def _sharded_fused_shardings(model):
    """The sharded-fused flat vector's element order is a pure function
    of (tree structure, leaf shardings, mesh) — record all three so a
    restore onto a DIFFERENT topology is refused instead of silently
    scrambling the moments (same per-dtype length, different
    (leaf, element) mapping)."""
    return {op: {w: str(spec) for w, spec in ws.items()}
            for op, ws in model.optimizer.specs.items()}


def _is_multihost() -> bool:
    return jax.process_count() > 1


def save_checkpoint(model, directory: str, step: Optional[int] = None,
                    extra_meta: Optional[dict] = None,
                    keep: Optional[int] = None) -> str:
    """Save model state. Returns the checkpoint path.

    Atomic: orbax writes into ``<directory>/.tmp-step_N``; meta + strategy
    land inside it; ONE ``os.replace`` publishes ``step_N``. A kill at any
    point leaves either the previous checkpoints intact plus a stale tmp
    dir (ignored by latest_step and cleaned on the next save of that
    step), or the complete new checkpoint — never a torn one.

    ``extra_meta`` merges into the per-step ``ff_meta.json`` (the
    supervisor records RNG key + dataloader cursors there); ``keep``
    prunes all but the newest ``keep`` step dirs after a successful
    publish.

    Single-controller: arrays are gathered to host numpy before writing, so
    checkpoints are topology-free — a restore re-shards onto whatever mesh
    the restoring model compiled with.

    Multi-controller (jax.process_count() > 1): arrays are handed to orbax
    as sharded jax.Arrays and EVERY process participates in the save — each
    host writes only its addressable shards (no host gather; a vocab-sharded
    embedding never materializes on one host). All processes must call this
    collectively; process 0 does the rename/prune between the barriers.
    Saving the same step twice overwrites (idempotent)."""
    import shutil

    directory = os.path.abspath(directory)
    step = step if step is not None else model._step_count
    path = os.path.join(directory, f"step_{step}")
    tmp = os.path.join(directory, f".tmp-step_{step}")
    multihost = _is_multihost()
    is_writer = not multihost or jax.process_index() == 0
    if is_writer:
        os.makedirs(directory, exist_ok=True)
        # only the TMP dir is cleared up front (orbax refuses to
        # overwrite); a pre-existing published step_N stays live until the
        # new one is ready — clearing it here would lose the checkpoint if
        # the process dies during the orbax write
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
    if multihost:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ff_ckpt_clean")

    if multihost:
        prep = _strip_none  # keep sharded jax.Arrays; orbax writes per host
    else:
        prep = lambda tree: jax.tree_util.tree_map(
            lambda a: np.asarray(a), _strip_none(tree))
    state = {"params": prep(model.params)}
    if model.opt_state is not None:
        state["opt_state"] = prep(model.opt_state)
    if model.bn_state:
        state["bn_state"] = prep(model.bn_state)

    def _save():
        faultinject.maybe_fail("io_fail", "save")
        if is_writer and os.path.exists(tmp):
            shutil.rmtree(tmp)  # half-written tmp from a failed attempt
        _checkpointer().save(tmp, state)

    if multihost:
        # the orbax save is COLLECTIVE: a per-host retry would re-enter
        # it on one process only (different op counts per host -> the
        # job deadlocks at orbax's internal syncs, or the writer rmtrees
        # shards peers just wrote). A failed collective save must be
        # retried collectively by the caller on every host.
        _save()
    else:
        retry(attempts=3, base_delay=0.05, retryable=(OSError,),
              name="orbax save")(_save)()

    if is_writer:
        meta = {"step": int(step),
                "mesh_shape": model.config.mesh_shape,
                "multihost": multihost,
                "loss_type": model.loss_type.name if model.loss_type else None}
        if "opt_state" in state:  # layout only meaningful when state saved
            meta["opt_layout"] = _opt_layout(model)
            if meta["opt_layout"] == "sharded_fused":
                meta["opt_state_shardings"] = _sharded_fused_shardings(model)
        if extra_meta:
            meta.update(extra_meta)
        with open(os.path.join(tmp, "ff_meta.json"), "w") as f:
            json.dump(meta, f)
        save_strategies_to_file(os.path.join(tmp, "strategy.txt"),
                                model.config.strategies)
        if os.path.exists(path):
            # same-step overwrite: the old dir must vanish for the rename
            # (os.replace cannot clobber a non-empty dir). The unprotected
            # window shrinks to this instant — the complete replacement is
            # already on disk in tmp, so a kill here leaves tmp salvageable
            # rather than nothing mid-write
            shutil.rmtree(path)
        os.replace(tmp, path)  # the publish point
        # top-level mirrors (older readers + import_strategy_file): written
        # atomically too, AFTER the step dir is live
        mtmp = os.path.join(directory, f".meta.json.tmp-{os.getpid()}")
        with open(mtmp, "w") as f:
            json.dump(meta, f)
        os.replace(mtmp, os.path.join(directory, "meta.json"))
        stmp = os.path.join(directory, f".strategy.txt.tmp-{os.getpid()}")
        save_strategies_to_file(stmp, model.config.strategies)
        os.replace(stmp, os.path.join(directory, "strategy.txt"))
        if keep is not None and keep > 0:
            for old in sorted(_step_dirs(directory))[:-keep]:
                shutil.rmtree(os.path.join(directory, f"step_{old}"),
                              ignore_errors=True)
    if multihost:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ff_ckpt_done")
    return path


def restore_checkpoint(model, directory: str, step: Optional[int] = None):
    """Restore into a compiled model. Single-controller checkpoints are
    stored as host numpy (see save_checkpoint), so restore re-shards onto
    the restoring model's own mesh regardless of the topology that saved
    them. Under multi-controller, every process calls this collectively and
    orbax restores each array directly into the model's current sharding
    (each host reads only its shards)."""
    directory = os.path.abspath(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found in {directory}")
    meta = load_meta(directory, step)
    path = os.path.join(directory, f"step_{step}")

    # absent on pre-r5 and params-only checkpoints (no opt state to
    # mismatch — a weights-export -> fine-tune restore must not be blocked)
    saved_layout = meta.get("opt_layout")
    if saved_layout is not None and model.optimizer is not None:
        if saved_layout != _opt_layout(model):
            raise ValueError(
                f"checkpoint at {directory} stores optimizer state in the "
                f"{saved_layout!r} layout but this model uses "
                f"{_opt_layout(model)!r} (FFConfig.fused_optimizer and the "
                f"sharding strategy determine the layout). Re-compile with "
                f"a matching fused_optimizer setting to restore.")
        if saved_layout == "sharded_fused":
            # same layout kind is not enough: the flat vector's element
            # order depends on (mesh, leaf shardings) — a cross-topology
            # restore would silently scramble the moments
            saved_sh = meta.get("opt_state_shardings")
            cur_sh = _sharded_fused_shardings(model)
            # ordered compare: the flat layout follows mesh AXIS ORDER
            # (P(tuple(axis_names))), so {'data':2,'model':2} and
            # {'model':2,'data':2} are different layouts even though the
            # dicts compare equal (JSON preserves key order)
            mesh_saved = list((meta.get("mesh_shape") or {}).items())
            mesh_cur = list(model.config.mesh_shape.items())
            if (mesh_saved != mesh_cur
                    or (saved_sh is not None and saved_sh != cur_sh)):
                raise ValueError(
                    f"checkpoint at {directory} stores sharded-fused "
                    f"optimizer state for mesh {meta.get('mesh_shape')} "
                    f"with different parameter shardings — the flat state "
                    f"layout is topology-dependent. Re-compile with the "
                    f"saved mesh/strategy, or restore weights only "
                    f"(optimizer=None) and start the optimizer fresh.")

    if _is_multihost():
        import orbax.checkpoint as ocp

        template = {"params": model.params}
        if model.opt_state is not None:
            template["opt_state"] = _strip_none(model.opt_state)
        if model.bn_state:
            template["bn_state"] = model.bn_state
        restore_args = ocp.checkpoint_utils.construct_restore_args(template)
        # no per-host retry around the COLLECTIVE restore (see _save):
        # one host re-entering it would desync the participants
        faultinject.maybe_fail("io_fail", "load")
        restored = _checkpointer().restore(path, restore_args=restore_args)
        model.params = restored["params"]
        if "opt_state" in restored and model.optimizer is not None:
            fresh = model.optimizer.init_state(model.params)
            model.opt_state = _merge_sharded(fresh, restored["opt_state"])
        if "bn_state" in restored:
            model.bn_state = restored["bn_state"]
        model._step_count = step
        return step

    restored = _orbax_restore(path)
    shardings = model.executor.param_shardings()

    def put(tree, shard_map_):
        out = {}
        for op_name, ws in tree.items():
            out[op_name] = {
                name: jax.device_put(np.asarray(v),
                                     shard_map_.get(op_name, {}).get(name))
                if shard_map_.get(op_name, {}).get(name) is not None
                else jax.device_put(np.asarray(v))
                for name, v in ws.items()}
        return out

    model.params = put(restored["params"], shardings)
    if "opt_state" in restored and model.optimizer is not None:
        fresh = model.optimizer.init_state(model.params)
        model.opt_state = _merge_restored(fresh, restored["opt_state"])
    if "bn_state" in restored:
        model.bn_state = {k: {n: jax.device_put(np.asarray(v))
                              for n, v in s.items()}
                          for k, s in restored["bn_state"].items()}
    model._step_count = step
    # NOTE: the checkpointed strategy file is NOT silently applied — sharding
    # was already resolved in compile(). To resume with the checkpointed
    # strategy, pass import_strategy_file=<dir>/strategy.txt in FFConfig
    # BEFORE compile(). We only warn on divergence here.
    try:
        per_step = os.path.join(path, "strategy.txt")
        saved = load_strategies_from_file(
            per_step if os.path.exists(per_step)
            else os.path.join(directory, "strategy.txt"))
        current = model.config.strategies
        def differs(a, b):
            if a.dims != b.dims:
                return True
            # dims alone miss CONTRACT/STAGE divergence (they shard
            # weights, not the output) — compare axis maps when both known
            if a.axis_map is not None and b.axis_map is not None:
                na = {k: v for k, v in a.axis_map.items() if v is not None}
                nb = {k: v for k, v in b.axis_map.items() if v is not None}
                return na != nb
            return False

        diff = [k for k in saved
                if k in current and differs(saved[k], current[k])]
        if diff:
            import sys

            print(f"[checkpoint] WARNING: strategy mismatch vs checkpoint for "
                  f"ops {diff[:5]}{'...' if len(diff) > 5 else ''}; set "
                  f"import_strategy_file before compile() to resume with the "
                  f"saved strategy", file=sys.stderr)
    except FileNotFoundError:
        pass
    return step


@retry(attempts=3, base_delay=0.05, retryable=(OSError,), name="orbax load")
def _orbax_restore(path, **kw):
    faultinject.maybe_fail("io_fail", "load")
    return _checkpointer().restore(path, **kw)


def _step_dirs(directory: str):
    """Published checkpoint step numbers in `directory` (tmp dirs from an
    interrupted save are skipped — they never became checkpoints)."""
    try:
        names = os.listdir(directory)
    except (FileNotFoundError, NotADirectoryError):
        return []
    out = []
    for n in names:
        m = re.fullmatch(r"step_(\d+)", n)
        if m and os.path.isdir(os.path.join(directory, n)):
            out.append(int(m.group(1)))
    return out


def load_meta(directory: str, step: Optional[int] = None) -> dict:
    """Checkpoint metadata: the per-step ``step_N/ff_meta.json`` when
    present (self-contained checkpoints), else the top-level ``meta.json``
    (pre-atomic-write layout)."""
    directory = os.path.abspath(directory)
    if step is not None:
        per_step = os.path.join(directory, f"step_{step}", "ff_meta.json")
        if os.path.exists(per_step):
            with open(per_step) as f:
                return json.load(f)
    with open(os.path.join(directory, "meta.json")) as f:
        return json.load(f)


def latest_step(directory: str) -> Optional[int]:
    """Newest published checkpoint step in `directory`, or None. Scans the
    ``step_*`` dirs ONLY: trusting ``meta.json`` would return steps whose
    dir is gone (a kill inside the same-step overwrite window, retention
    pruning) and turn auto-resume into a restore-of-nothing crash loop —
    no dir means fresh start. ``.tmp-*`` leftovers from an interrupted
    save are ignored."""
    steps = _step_dirs(directory)
    return max(steps) if steps else None


def _strip_none(tree):
    if isinstance(tree, dict):
        return {k: _strip_none(v) for k, v in tree.items() if v is not None}
    return tree


def _merge_sharded(fresh, restored):
    """Refill None leaves stripped before a sharded save (restored arrays
    already carry the model's shardings via construct_restore_args)."""
    if isinstance(fresh, dict):
        return {k: _merge_sharded(v, restored[k]) if k in restored else v
                for k, v in fresh.items()}
    if fresh is None:
        return None
    return restored


def _merge_restored(fresh, restored):
    from jax.sharding import NamedSharding

    if isinstance(fresh, dict):
        return {k: _merge_restored(v, restored[k]) if k in restored else v
                for k, v in fresh.items()}
    if fresh is None:
        return None
    arr = np.asarray(restored).astype(np.asarray(fresh).dtype)
    sh = getattr(fresh, "sharding", None)
    if isinstance(sh, NamedSharding):
        return jax.device_put(arr, sh)
    # uncommitted: let jit place it alongside the mesh-sharded params
    import jax.numpy as jnp

    return jnp.asarray(arr)


def auto_resume(model, directory: str) -> int:
    """Slice-preemption recovery (the capability gap SURVEY §5.3 notes in the
    reference: a failed node kills the job with no recovery). Call after
    compile(): restores the newest checkpoint in `directory` when one exists
    and returns its step; returns 0 on a fresh start."""
    step = latest_step(directory)
    if step is None:
        return 0
    restore_checkpoint(model, directory, step=step)
    return step
