"""Weight initializers.

Reference: include/initializer.h:28-101 + src/runtime/initializer_kernel.cu
(curand Glorot-uniform, zero, uniform, normal, constant — each a Legion task
over the weight partition). Here each is a pure function of a PRNG key; under
GSPMD the init computation itself is sharded like the weight, so large
embedding tables initialize without ever materializing unsharded.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from flexflow_tpu.ops.base import WeightSpec


class Initializer:
    def __call__(self, key, shape, dtype=jnp.float32):
        raise NotImplementedError


class GlorotUniformInitializer(Initializer):
    def __init__(self, seed: int = 0):
        self.seed = seed

    def __call__(self, key, shape, dtype=jnp.float32,
                 fan: Optional[Tuple[int, int]] = None):
        if fan is None:
            fan_in = shape[0] if len(shape) > 1 else shape[0]
            fan_out = shape[-1] if len(shape) > 1 else shape[0]
        else:
            fan_in, fan_out = fan
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class ZeroInitializer(Initializer):
    def __call__(self, key, shape, dtype=jnp.float32, **kw):
        return jnp.zeros(shape, dtype)


class OneInitializer(Initializer):
    def __call__(self, key, shape, dtype=jnp.float32, **kw):
        return jnp.ones(shape, dtype)


class UniformInitializer(Initializer):
    def __init__(self, seed: int = 0, low: float = -0.05, high: float = 0.05):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype=jnp.float32, **kw):
        return jax.random.uniform(key, shape, dtype, self.low, self.high)


class NormInitializer(Initializer):
    def __init__(self, seed: int = 0, mean: float = 0.0, stddev: float = 1.0):
        self.mean, self.stddev = mean, stddev

    def __call__(self, key, shape, dtype=jnp.float32, **kw):
        return self.mean + self.stddev * jax.random.normal(key, shape, dtype)


class ConstantInitializer(Initializer):
    def __init__(self, value: float):
        self.value = value

    def __call__(self, key, shape, dtype=jnp.float32, **kw):
        return jnp.full(shape, self.value, dtype)


def init_weight(spec: WeightSpec, key, dtype=jnp.float32):
    """Initialize one weight from its spec (used when no user initializer is
    attached — reference attaches GlorotUniform/Zero defaults in create_weights,
    e.g. linear.cu:74-122)."""
    kind = spec.init
    if kind == "glorot":
        return GlorotUniformInitializer()(key, spec.shape, dtype, fan=spec.fan)
    if kind == "zero":
        return jnp.zeros(spec.shape, dtype)
    if kind == "one":
        return jnp.ones(spec.shape, dtype)
    if kind == "uniform":
        low, high = spec.init_args if spec.init_args else (-0.05, 0.05)
        return jax.random.uniform(key, spec.shape, dtype, low, high)
    if kind == "normal":
        mean, std = spec.init_args if spec.init_args else (0.0, 1.0)
        return mean + std * jax.random.normal(key, spec.shape, dtype)
    if kind == "constant":
        (v,) = spec.init_args
        return jnp.full(spec.shape, v, dtype)
    raise ValueError(f"unknown init kind {kind}")
