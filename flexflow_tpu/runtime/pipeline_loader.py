"""Host-overlap input pipeline: bounded background prefetch + ahead-of-time
committed sharding.

The reference hides data movement behind compute with Legion's deferred
execution (every `next_batch` is an index-launch the runtime overlaps with
whatever compute is outstanding); the TensorFlow-paper input pipeline gets
the same effect with an explicit prefetch queue. Our synchronous `fit()`
loop had neither: each step pulled a batch on the host, `device_put` it,
and only then dispatched — TPU idle during host work, host idle during
device work.

``PipelineLoader`` closes that gap: a daemon worker thread pulls batches
from any source (the model's ``SingleDataLoader``s, or a
``NativeBatchLoader``), shards each one to its cached ``NamedSharding``
with a **committed** ``jax.device_put`` (committed placement matters: an
uncommitted batch gives the warm step program a different pjit signature
and silently retraces it — the PR-3 serving-pool lesson), and parks up to
``depth`` ready batches in a bounded buffer. The training loop's
``get()`` then returns an already-device-resident batch, so the hot path
does no host slicing and no H2D wait.

Exactness contracts (what makes overlap safe to turn on by default):

  * **Order**: batches are pulled, sharded, and buffered strictly in
    source order by ONE worker; ``get()`` pops FIFO — the overlap loop
    trains the exact batch sequence the synchronous loop would.
  * **Cursor accounting**: the worker advances the source's cursor
    (``dl.next_index``) ahead of training. ``consumed_cursors()`` always
    reports the position as of the last batch actually HANDED to the
    training loop, and every quiesce (epoch break, stop) rewinds the
    source cursors to that consumed position — so a checkpoint taken at
    any step boundary records exactly the synchronous loop's cursor and
    resume stays bitwise-identical (runtime/resilience.py reads cursors
    through this when a pipeline is active).
  * **Fault semantics**: the pull runs inside ``resilience.retry`` with
    ``faultinject.maybe_fail("io_fail", "loader")`` checked BEFORE the
    cursor advances, so an injected ``FF_FAULT=io_fail@loader:n`` retries
    the same batch — no reorder, no skip, no deadlock. A worker error
    that exhausts retries is parked and re-raised from ``get()`` on the
    training thread.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Optional

from flexflow_tpu.logger import fflogger
from flexflow_tpu.runtime import faultinject, locks, telemetry
from flexflow_tpu.runtime.resilience import retry


class PipelineLoader:
    """Bounded background prefetch queue over a batch source.

    ``pull() -> batch dict | None`` (None = end of epoch, native loader
    semantics), ``shard(batch) -> device batch`` (the executor's cached
    committed sharding), optional ``cursors()/restore(snapshot)`` for
    sources with seekable cursors (the deterministic loaders)."""

    def __init__(self, pull: Callable[[], Optional[Dict]],
                 shard: Callable[[Dict], Dict], *, depth: int = 2,
                 cursors: Optional[Callable[[], Dict]] = None,
                 restore: Optional[Callable[[Dict], None]] = None,
                 telemetry_on: bool = True):
        if depth < 1:
            raise ValueError(f"PipelineLoader depth must be >= 1, got {depth}")
        # FFConfig.telemetry="off" reaches the worker through the model
        # constructors below — the off contract covers the loader track
        self._tm_on = bool(telemetry_on)
        self._shard = shard
        self._cursors = cursors
        self._restore = restore
        self.depth = depth
        self._cv = locks.make_condition("pipeline-loader")
        self._buf: collections.deque = collections.deque()
        self._paused = False
        self._stopped = False
        self._pulling = False
        self._eos = False
        self._gen = 0  # bumped at every quiesce; stale pulls must not buffer
        self._exc: Optional[BaseException] = None
        self._consumed = cursors() if cursors is not None else None
        # h2d_s accumulates INSIDE the worker — time the training thread
        # never sees (that is the point of the pipeline); pulls/retries
        # are visible through resilience.COUNTERS as usual
        self.stats = {"h2d_s": 0.0, "pull_s": 0.0, "batches": 0}
        # maybe_fail runs BEFORE the underlying pull so a scheduled
        # io_fail@loader fires without advancing any cursor; the retry
        # then re-pulls the SAME batch
        @retry(attempts=3, base_delay=0.05, retryable=(OSError,),
               name="prefetch pull")
        def _pull_retry():
            faultinject.maybe_fail("io_fail", "loader")
            return pull()

        self._pull = _pull_retry
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ff-prefetch")
        self._started = False

    # ---- constructors ------------------------------------------------------

    @classmethod
    def from_loaders(cls, model, depth: int = 2) -> "PipelineLoader":
        """Prefetch from the model's attached SingleDataLoaders (seekable
        cursors -> exact quiesce/checkpoint accounting)."""
        dls = list(model._dataloaders)

        def pull():
            return {dl.name: dl.next_batch() for dl in dls}

        def cursors():
            return {dl.name: int(dl.next_index) for dl in dls}

        def restore(snap):
            for dl in dls:
                if dl.name in snap:
                    dl.next_index = int(snap[dl.name])

        return cls(pull, model.executor.shard_batch, depth=depth,
                   cursors=cursors, restore=restore,
                   telemetry_on=getattr(model.config, "telemetry",
                                        "on") != "off")

    @classmethod
    def from_native(cls, native_dl, model, depth: int = 2) -> "PipelineLoader":
        """Prefetch-shard on top of the native threaded loader (it already
        overlaps host batch ASSEMBLY; this adds the H2D put). Its shuffled
        cursor cannot seek, so there is no cursor contract — resume under
        the native loader replays batches by count, exactly as before."""
        return cls(native_dl.next_batch, model.executor.shard_batch,
                   depth=depth,
                   telemetry_on=getattr(model.config, "telemetry",
                                        "on") != "off")

    # ---- worker ------------------------------------------------------------

    def _run(self):
        while True:
            with self._cv:
                while not self._stopped and (
                        self._paused or self._eos
                        or len(self._buf) >= self.depth):
                    self._cv.wait()
                if self._stopped:
                    return
                self._pulling = True
                gen = self._gen
            try:
                t0 = time.perf_counter()
                batch = self._pull()
                t1 = time.perf_counter()
                if batch is None:  # end of epoch (native loader)
                    with self._cv:
                        self._pulling = False
                        self._eos = True
                        self._cv.notify_all()
                    continue
                sharded = self._shard(batch)
                t2 = time.perf_counter()
                snap = self._cursors() if self._cursors is not None else None
            except BaseException as e:  # noqa: BLE001 — parked for get()
                with self._cv:
                    self._pulling = False
                    self._exc = e
                    self._cv.notify_all()
                return
            with self._cv:
                self._pulling = False
                # a quiesce that raced this pull rewinds the cursor past
                # it — the batch must be dropped, not buffered stale (the
                # generation check also covers a pull the quiesce gave up
                # waiting on, which completes only after resume)
                if not (self._paused or self._stopped) and gen == self._gen:
                    self._buf.append((sharded, snap))
                    self.stats["pull_s"] += t1 - t0
                    self.stats["h2d_s"] += t2 - t1
                    self.stats["batches"] += 1
                    # telemetry: the worker's overlapped phases on their
                    # own "loader" track — the exported trace shows
                    # prefetch running UNDER the train steps (that is
                    # the overlap schedule, end to end)
                    if self._tm_on:
                        telemetry.tracer().complete(
                            "prefetch_pull", t0, t1 - t0, track="loader")
                        telemetry.tracer().complete(
                            "prefetch_h2d", t1, t2 - t1, track="loader")
                self._cv.notify_all()

    # ---- training-thread API ----------------------------------------------

    def start(self):
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def get(self, timeout: Optional[float] = None) -> Dict:
        """Next sharded batch, FIFO. Blocks until the worker delivers;
        re-raises a worker error here (the training thread) instead of
        deadlocking on an empty queue."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                if self._buf:
                    sharded, snap = self._buf.popleft()
                    if snap is not None:
                        self._consumed = snap
                    self._cv.notify_all()
                    return sharded
                if self._exc is not None:
                    raise RuntimeError(
                        "prefetch worker died") from self._exc
                if self._eos:
                    raise RuntimeError(
                        "prefetch source exhausted mid-epoch (loader "
                        "num_batches disagrees with the training loop)")
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"prefetch get() timed out after {timeout}s")
                self._cv.wait(timeout=0.5)

    def consumed_cursors(self) -> Optional[Dict]:
        """Source cursor position as of the last batch handed to the
        training loop (None for unseekable sources). This — not the
        source's own pulled-ahead cursor — is what a checkpoint must
        record."""
        with self._cv:
            return dict(self._consumed) if self._consumed is not None else None

    def reset_stats(self):
        """Zero the accumulated counters under the worker's lock (the
        worker read-modify-writes them under the same lock mid-prefetch,
        so an unlocked reset could be lost)."""
        with self._cv:
            for k in self.stats:
                self.stats[k] = 0 if k == "batches" else 0.0

    def _quiesce_locked(self, timeout: float = 10.0):
        self._paused = True
        self._cv.notify_all()
        deadline = time.monotonic() + timeout
        while self._pulling:
            remaining = deadline - time.monotonic()
            if remaining <= 0:  # pragma: no cover — diagnostics
                # a pull stuck on a dead source would otherwise hang this
                # quiesce forever — and a SIGTERM stop() would never reach
                # its timed join or the preemption checkpoint. Abandon the
                # daemon worker; the generation bump guarantees its batch
                # is dropped if it ever completes.
                fflogger.warning(
                    "prefetch worker still mid-pull after %.0fs quiesce "
                    "wait — abandoning it (source may be hung)", timeout)
                break
            self._cv.wait(timeout=min(remaining, 0.5))
        self._gen += 1
        self._buf.clear()
        self._eos = False
        if self._restore is not None and self._consumed is not None:
            # rewind the source to the consumed position: prefetched-but-
            # untrained batches are discarded and will be re-pulled
            self._restore(self._consumed)

    def epoch_break(self, reset: Optional[Callable[[], None]] = None):
        """Epoch boundary: pause the worker, discard prefetched batches,
        rewind cursors to consumed, run the loader ``reset`` with the
        worker idle, re-snapshot, resume. Leaves source state exactly
        where the synchronous loop's epoch boundary would."""
        with self._cv:
            self._quiesce_locked()
            if reset is not None:
                reset()
            if self._cursors is not None:
                self._consumed = self._cursors()
            self._paused = False
            self._cv.notify_all()

    def stop(self):
        """Terminate the worker and rewind cursors to the consumed
        position (so ``dl.next_index`` after fit equals the synchronous
        loop's). Idempotent; never raises — a parked worker error has
        already surfaced (or will be moot) on the training thread."""
        with self._cv:
            if self._stopped:
                return
            try:
                self._quiesce_locked()
            finally:
                self._stopped = True
                self._cv.notify_all()
        if self._started:
            self._thread.join(timeout=10.0)
            if self._thread.is_alive():  # pragma: no cover — diagnostics
                fflogger.warning(
                    "prefetch worker did not exit within 10s at stop()")
