"""ctypes bridge to the native threaded dataloader (csrc/dataloader.cc).

Groups the model's `SingleDataLoader`s into ONE native loader so the sample
permutation stays consistent across input and label arrays (the reference
shares one `SampleIdxs` argmap across its loaders —
flexflow_dataloader.h:88-141). Worker threads gather shuffled batch slices
into a ring of prefetch slots, overlapping host-side batch assembly with
device compute.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flexflow_tpu.runtime import locks

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_LIB_PATH = os.path.join(_CSRC, "libffdl.so")
_lib = None
_lib_lock = locks.make_lock("native-loader")


def load_lib():
    """Compile (if stale) and load libffdl.so; returns None when no g++.
    Failures are cached (sentinel False) so fit() doesn't re-spawn g++ every
    call; the build goes to a temp file + os.rename so concurrent processes
    sharing the package dir never dlopen a half-written .so."""
    global _lib
    with _lib_lock:
        if _lib is False:
            return None
        if _lib is not None:
            return _lib
        src = os.path.join(_CSRC, "dataloader.cc")
        from flexflow_tpu.runtime.resilience import retry

        # a concurrent process can race the build (dlopen of a just-
        # replaced .so, transient fs errors) — retry once before giving
        # up; "no g++ at all" (FileNotFoundError) is permanent, not
        # retryable, and must fall through to the Python loader fast
        @retry(attempts=2, base_delay=0.1,
               retryable=lambda e: isinstance(
                   e, (OSError, subprocess.CalledProcessError))
               and not isinstance(e, FileNotFoundError),
               name="native dataloader build")
        def _build_and_open():
            if (not os.path.exists(_LIB_PATH)
                    or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)):
                tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-fPIC", "-Wall", "-pthread",
                     "-shared", "-o", tmp, src],
                    check=True, capture_output=True)
                os.rename(tmp, _LIB_PATH)
            return ctypes.CDLL(_LIB_PATH)

        try:
            lib = _build_and_open()
        except (OSError, subprocess.CalledProcessError):
            _lib = False
            return None
        lib.ffdl_create.restype = ctypes.c_void_p
        lib.ffdl_create.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
        lib.ffdl_num_batches.restype = ctypes.c_int64
        lib.ffdl_num_batches.argtypes = [ctypes.c_void_p]
        lib.ffdl_next.restype = ctypes.c_int
        lib.ffdl_next.argtypes = [ctypes.c_void_p]
        lib.ffdl_buffer.restype = ctypes.c_void_p
        lib.ffdl_buffer.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.ffdl_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ffdl_reset.argtypes = [ctypes.c_void_p]
        lib.ffdl_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class NativeBatchLoader:
    """One prefetching loader over N parallel (name, array) streams."""

    def __init__(self, arrays: Sequence[Tuple[str, np.ndarray]],
                 batch_size: int, shuffle: bool = False, seed: int = 0,
                 prefetch_slots: int = 3, num_threads: int = 2):
        lib = load_lib()
        if lib is None:
            raise RuntimeError("native dataloader unavailable (no g++?)")
        self._lib = lib
        self.names = [n for n, _ in arrays]
        # keep C-contiguous copies alive for the lifetime of the loader — the
        # C++ side reads them directly
        self.arrays = [np.ascontiguousarray(a) for _, a in arrays]
        ns = {a.shape[0] for a in self.arrays}
        if len(ns) != 1:
            raise ValueError(f"arrays disagree on num_samples: {ns}")
        self.num_samples = ns.pop()
        self.batch_size = batch_size
        self.sample_shapes = [a.shape[1:] for a in self.arrays]
        self.dtypes = [a.dtype for a in self.arrays]

        n = len(self.arrays)
        ptrs = (ctypes.c_void_p * n)(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in self.arrays])
        sbytes = (ctypes.c_int64 * n)(
            *[int(np.prod(s, dtype=np.int64)) * d.itemsize
              for s, d in zip(self.sample_shapes, self.dtypes)])
        self._h = lib.ffdl_create(
            n, ptrs, sbytes, self.num_samples, batch_size,
            1 if shuffle else 0, seed, prefetch_slots, num_threads)
        if not self._h:
            raise RuntimeError("ffdl_create failed (batch_size > num_samples?)")
        self.num_batches = int(lib.ffdl_num_batches(self._h))
        self._served = 0

    def reset(self):
        self._lib.ffdl_reset(self._h)
        self._served = 0

    def next_batch(self) -> Optional[Dict[str, np.ndarray]]:
        """Next prefetched batch as {name: array}; None at end of epoch.
        Arrays are copies — safe to hand to jax.device_put on any backend
        (the CPU backend may alias numpy buffers)."""
        if self._h is None:
            raise RuntimeError("loader destroyed")
        slot = self._lib.ffdl_next(self._h)
        if slot < 0:
            return None
        out = {}
        for i, name in enumerate(self.names):
            ptr = self._lib.ffdl_buffer(self._h, slot, i)
            nbytes = (self.batch_size
                      * int(np.prod(self.sample_shapes[i], dtype=np.int64))
                      * self.dtypes[i].itemsize)
            buf = (ctypes.c_char * nbytes).from_address(ptr)
            arr = np.frombuffer(buf, dtype=self.dtypes[i]).reshape(
                (self.batch_size,) + tuple(self.sample_shapes[i])).copy()
            out[name] = arr
        self._lib.ffdl_release(self._h, slot)
        self._served += 1
        return out

    def close(self):
        if self._h is not None:
            self._lib.ffdl_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def group_loader_for(model) -> Optional[NativeBatchLoader]:
    """Build one NativeBatchLoader over the model's attached dataloaders, or
    None when unavailable / heterogeneous."""
    cfg = model.config
    if not getattr(cfg, "native_dataloader", False) or not model._dataloaders:
        return None
    sizes = {dl.batch_size for dl in model._dataloaders}
    ns = {dl.num_samples for dl in model._dataloaders}
    if len(sizes) != 1 or len(ns) != 1:
        return None
    try:
        return NativeBatchLoader(
            [(dl.name, dl.data[:dl.num_samples]) for dl in model._dataloaders],
            batch_size=sizes.pop(),
            shuffle=getattr(cfg, "dataloader_shuffle", False),
            seed=getattr(cfg, "seed", 0),
            prefetch_slots=getattr(cfg, "dataloader_prefetch_slots", 3),
            num_threads=getattr(cfg, "dataloader_threads", 2))
    except (RuntimeError, ValueError):
        return None
