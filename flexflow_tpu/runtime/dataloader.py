"""SingleDataLoader: full-dataset-resident batch slicer.

Reference semantics (python/flexflow_dataloader.{h,cc,cu}): the entire dataset
is attached once into zero-copy memory; `next_batch` is an index launch that
copies each shard's sample slice to its device. TPU version: when the dataset
fits the configured budget it is device_put ONCE (sharded over the 'data'
mesh axis) and `next_batch` is a jitted on-device dynamic_slice producing the
batch already under the training sharding — no per-step host->device
transfer, exactly the reference's resident-dataset design. Datasets over
budget stay in host RAM as numpy and are device_put per batch (each host
feeds its addressable shard — multi-host ready).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class SingleDataLoader:
    def __init__(self, model, tensor, full_array: np.ndarray,
                 num_samples: Optional[int] = None, batch_size: Optional[int] = None):
        self.model = model
        self.tensor = tensor
        self.name = tensor.name.split(":")[0] if tensor.name else "input"
        self.data = np.asarray(full_array)
        self.num_samples = num_samples or self.data.shape[0]
        self.batch_size = batch_size or model.config.batch_size
        self.next_index = 0
        self._dev_data = None
        self._dev_slice = None
        self._dev_failed = False
        self._staged_bs = None
        if model is not None:
            model._dataloaders.append(self)

    @property
    def num_batches(self) -> int:
        return self.num_samples // self.batch_size

    def reset(self):
        self.next_index = 0

    def unstage(self):
        """Drop the device-resident copy (frees HBM) and pin this loader to
        the host path — next_batch must not silently re-upload what fit()
        just evicted."""
        self._dev_data = self._dev_slice = None
        self._dev_failed = True

    # ---- device-resident path ------------------------------------------------

    def device_eligible(self) -> bool:
        """Cheap check (no upload): may this dataset live on device?
        Shuffling stays on the host prefetch loader, which reshuffles per
        epoch (native_loader.py)."""
        model = self.model
        cfg = getattr(model, "config", None)
        executor = getattr(model, "executor", None)
        return (cfg is not None and executor is not None
                and not self._dev_failed
                and getattr(cfg, "device_resident_data", True)
                and not getattr(cfg, "dataloader_shuffle", False)
                and not getattr(executor, "jits_per_group", False)
                and self.data.nbytes <= getattr(
                    cfg, "device_data_budget_bytes", 2 << 30))

    def _try_stage_on_device(self) -> bool:
        """Upload the dataset once, batch-sharded over 'data'. Returns True
        when the device-resident path is usable."""
        if self._dev_data is not None:
            if self._staged_bs == self.batch_size:
                return True
            self._dev_data = self._dev_slice = None  # batch size changed
        if not self.device_eligible():
            self._dev_failed = True
            return False
        try:
            import jax
            from jax import lax
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = self.model.executor.input_sharding(self.tensor)
            b = self.batch_size
            nb = self.num_batches
            # stage PRE-BATCHED: (num_batches, batch, ...) with the batch
            # dim sharded and the leading batch-index dim replicated, so
            # next_batch is a purely local index — no collective per slice
            # (slicing a sample-sharded flat array would all-gather across
            # shard boundaries on every batch)
            data = self.data[:nb * b].reshape((nb, b) + self.data.shape[1:])
            staged_spec = PartitionSpec(None, *sharding.spec)
            staged_sharding = NamedSharding(sharding.mesh, staged_spec)
            self._dev_data = jax.device_put(data, staged_sharding)
            self._dev_slice = jax.jit(
                lambda d, i: lax.dynamic_index_in_dim(d, i, 0,
                                                      keepdims=False),
                out_shardings=sharding)
            self._staged_bs = b
        except Exception:
            self._dev_failed = True
            return False
        return True

    def next_batch(self) -> np.ndarray:
        b = self.batch_size
        start = self.next_index
        if start + b > self.num_samples:
            start = 0
            self.next_index = 0
        self.next_index = start + b
        if self._try_stage_on_device():
            # same wrap policy as the host path: past the end -> batch 0
            bi = (start // b) % self._dev_data.shape[0]
            return self._dev_slice(self._dev_data, bi)
        return self.data[start:start + b]


def attach_training_data(ffmodel, input_tensors, x, y, loss_type):
    """Shared keras-style fit() plumbing (keras + keras_exp frontends):
    reset dataloaders, attach one loader per graph input, reshape 1-D
    sparse-CE labels to the (N, 1) the label tensor expects, attach the
    label loader."""
    from flexflow_tpu.ffconst import LossType

    xs = x if isinstance(x, (list, tuple)) else [x]
    assert len(xs) == len(input_tensors), \
        f"{len(xs)} input arrays for {len(input_tensors)} graph inputs"
    ffmodel._dataloaders = []
    for t, arr in zip(input_tensors, xs):
        SingleDataLoader(ffmodel, t, np.asarray(arr))
    y = np.asarray(y)
    if loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY \
            and y.ndim == 1:
        y = y.reshape(-1, 1)
    SingleDataLoader(ffmodel, ffmodel.label_tensor, y)
