"""SingleDataLoader: full-dataset-resident batch slicer.

Reference semantics (python/flexflow_dataloader.{h,cc,cu}): the entire dataset
is attached once into zero-copy memory; `next_batch` is an index launch that
copies each shard's sample slice to its device. TPU version: the dataset stays
in host RAM as numpy; `next_batch` returns the next batch slice, and the
executor device_puts it under the batch NamedSharding (each host feeds its
addressable shard — multi-host ready).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class SingleDataLoader:
    def __init__(self, model, tensor, full_array: np.ndarray,
                 num_samples: Optional[int] = None, batch_size: Optional[int] = None):
        self.model = model
        self.tensor = tensor
        self.name = tensor.name.split(":")[0] if tensor.name else "input"
        self.data = np.asarray(full_array)
        self.num_samples = num_samples or self.data.shape[0]
        self.batch_size = batch_size or model.config.batch_size
        self.next_index = 0
        if model is not None:
            model._dataloaders.append(self)

    @property
    def num_batches(self) -> int:
        return self.num_samples // self.batch_size

    def reset(self):
        self.next_index = 0

    def next_batch(self) -> np.ndarray:
        b = self.batch_size
        start = self.next_index
        if start + b > self.num_samples:
            start = 0
            self.next_index = 0
        out = self.data[start:start + b]
        self.next_index = start + b
        return out
