"""Fault-tolerant training: preemption-safe supervisor, divergence guards,
retry/backoff, and a step watchdog.

The reference FlexFlow has no checkpointing or failure story (SURVEY §5.4)
— a lost node kills the run. The ROADMAP north-star is a production system
on preemptible TPU pools, where interruption is the COMMON case, so the
runtime owns recovery (the TensorFlow-paper position: periodic consistent
checkpointing + automatic resume is a first-class runtime responsibility):

  * ``TrainSupervisor`` wraps the train loop with periodic + SIGTERM-
    triggered atomic checkpoints (runtime/checkpoint.py: tmp-dir +
    os.replace, last-K retention) and automatic resume-from-latest —
    step counter, RNG key, and dataloader cursors restore so the resumed
    loss trajectory is bitwise identical to an uninterrupted run.
  * Divergence guard: a per-step finite-loss/grad-norm check compiled INTO
    the jitted step (executor.make_guarded_train_step — one jnp.isfinite
    reduction, skip/keep selected in-graph by jnp.where, no device→host
    round trip before the update). The supervisor counts consecutive bad
    steps on the host and rewinds to the last checkpoint after N.
  * ``retry(attempts, base_delay, retryable=...)``: timeout/backoff
    decorator applied to jax.distributed.initialize (launcher.py), orbax
    save/load (checkpoint.py), and the native dataloader build
    (native_loader.py).
  * ``Watchdog``: wall-clock step timeout that dumps every thread's stack
    (faulthandler) before aborting a stuck collective.
  * Elastic recovery (runtime/elastic.py): resume() restores the newest
    *intact* checkpoint (content-hash manifest verification, falling back
    past corrupted steps) and tolerates a CHANGED topology — the compile-
    time policy hook refit the mesh, and the restore re-shards the saved
    state onto it (``on_topology_change`` = resume_resharded | research |
    abort).

Every path is deterministically testable on CPU via runtime/faultinject.py
(``FF_FAULT=nan_loss@step:7,sigterm@step:12,io_fail@save:1``).
"""

from __future__ import annotations

import collections
import contextlib
import functools
import os
import signal
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from flexflow_tpu.logger import fflogger
from flexflow_tpu.runtime import faultinject, flightrec, locks, telemetry

# process-wide resilience counters (skipped steps / restarts / retries …);
# read via counters(), cleared via reset_counters()
COUNTERS: collections.Counter = collections.Counter()


def counters() -> Dict[str, int]:
    return dict(COUNTERS)


def reset_counters():
    COUNTERS.clear()


def install_sigterm(handler):
    """Install ``handler`` as the SIGTERM disposition — the ONE
    preemption-notice entry point (ISSUE 20): TrainSupervisor (checkpoint
    then exit) and ServingRouter.install_preempt_handler (evacuate a
    replica against a deadline) both route the cloud's preemption signal
    through here. Returns ``(installed, previous_disposition)`` —
    ``(False, None)`` off the main thread (the signal module's rule),
    where the owner must call its programmatic ``request_preempt()``
    instead."""
    try:
        return True, signal.signal(signal.SIGTERM, handler)
    except ValueError:
        return False, None


# --------------------------------------------------------------- retry


def retry(attempts: int = 3, base_delay: float = 0.1, max_delay: float = 5.0,
          retryable=(OSError,), name: Optional[str] = None,
          sleep: Callable[[float], None] = time.sleep):
    """Exponential-backoff retry decorator for flaky IO/RPC boundaries
    (orbax save/load, jax.distributed.initialize, native loader build).

    ``retryable`` is an exception class / tuple of classes, or a predicate
    ``exc -> bool``. Non-retryable and final-attempt failures re-raise
    unchanged. Each retry increments COUNTERS["retries"] and logs the
    failure — a silent retry hides a degrading storage layer."""
    if attempts < 1:
        # a typo'd knob (FF_INIT_ATTEMPTS=0) must fail loudly, not make
        # the wrapper silently skip the call and return None
        raise ValueError(f"retry: attempts must be >= 1, got {attempts}")
    if isinstance(retryable, (type, tuple)):
        classes = retryable

        def pred(e):
            return isinstance(e, classes)
    else:
        pred = retryable

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            delay = base_delay
            for i in range(attempts):
                try:
                    return fn(*args, **kwargs)
                except Exception as e:
                    if i == attempts - 1 or not pred(e):
                        raise
                    COUNTERS["retries"] += 1
                    fflogger.warning(
                        "retry %s: attempt %d/%d failed (%s: %s); "
                        "retrying in %.2fs",
                        name or getattr(fn, "__name__", "?"), i + 1,
                        attempts, type(e).__name__, e, delay)
                    sleep(min(delay, max_delay))
                    delay *= 2
        return wrapper
    return deco


# ------------------------------------------------------------- watchdog


class Watchdog:
    """Wall-clock timeout around a blocking section (the host fetch that
    waits on a step's device work). A hung collective — one host dropped
    out of a rendezvous — blocks forever with no exception; the watchdog
    dumps every thread's stack first (the post-mortem that distinguishes
    'stuck in all-reduce' from 'stuck in the dataloader') and then aborts
    via ``on_timeout`` (default: KeyboardInterrupt in the main thread).

    ``timeout_s <= 0`` disarms. One Watchdog is reusable across steps."""

    def __init__(self, timeout_s: float, on_timeout: Optional[Callable] = None,
                 dump_path: Optional[str] = None):
        self.timeout_s = float(timeout_s)
        self.on_timeout = on_timeout
        self.dump_path = dump_path
        self.fired = False
        # the owning supervisor clears this under FFConfig.telemetry=off
        self.telemetry_on = True

    def _dump(self, label: str, timeout_s: float):
        import faulthandler

        msg = (f"\n[resilience] watchdog: {label!r} exceeded "
               f"{timeout_s:.1f}s wall clock; thread stacks follow\n")
        if self.dump_path:
            with open(self.dump_path, "a") as f:
                f.write(msg)
                faulthandler.dump_traceback(file=f)
        else:
            sys.stderr.write(msg)
            faulthandler.dump_traceback(file=sys.stderr)

    def _profiler_snapshot(self):
        """Best-effort device profiler snapshot alongside the stacks
        (pprof heap profile — which buffers were live when the step
        wedged). Runs AFTER the abort is signalled: it can be slow, and a
        fully hung runtime may never answer."""
        if not self.dump_path:
            return
        try:
            import jax

            jax.profiler.save_device_memory_profile(
                self.dump_path + ".memprof")
        except Exception:
            pass

    @contextlib.contextmanager
    def arm(self, label: str = "step", scale: float = 1.0):
        """``scale`` stretches the timeout for syncs that wait on more
        than one step's async work (fit's epoch-end conversion blocks on
        every step dispatched since the last sync)."""
        if self.timeout_s <= 0:
            yield
            return
        timeout_s = self.timeout_s * max(scale, 1.0)

        grace: List[threading.Timer] = []
        lock = locks.make_lock("watchdog")
        state = {"active": True}

        def hard_exit():
            with lock:
                if not state["active"]:
                    return  # section completed; interrupt was serviced
            os._exit(70)

        def fire():
            # the lock is held through dump + grace registration +
            # interrupt: arm()'s finally blocks on it, so it can never
            # observe a half-registered grace timer. A section completing
            # in the same instant the timer fires reads as "step took
            # >= timeout" — which is what the watchdog reports.
            with lock:
                if not state["active"]:
                    return  # completed before we fired: healthy run
                self.fired = True
                COUNTERS["watchdog_fires"] += 1
                if self.telemetry_on:
                    telemetry.tracer().instant(
                        "watchdog_fire", track="train", label=label,
                        timeout_s=timeout_s)
                    # the post-mortem trigger: capture the last N
                    # seconds of spans/metrics/logs before the abort
                    # path tears the process down (the write happens on
                    # the recorder's own daemon timer — this only
                    # schedules)
                    flightrec.trip("watchdog_fire", label=label,
                                   timeout_s=timeout_s)
                self._dump(label, timeout_s)  # stacks first, while they
                # still show the hang; the slow profiler snapshot trails
                if self.telemetry_on:
                    # TERMINAL trigger: the abort below may end the
                    # process before the debounce timer fires, so the
                    # bundle must be written synchronously NOW — the
                    # whole point is evidence that survives the death
                    flightrec.recorder().flush(timeout=15.0)
                if self.on_timeout is not None:
                    self.on_timeout(label)
                else:
                    import _thread

                    # interrupt_main only raises at the next Python
                    # bytecode boundary — a main thread wedged inside a
                    # native device fetch never reaches one. Hard-exit
                    # backstop: if the interrupt isn't serviced, the
                    # process is unrecoverable; exit so the launcher /
                    # scheduler can restart it (auto-resume picks up the
                    # last checkpoint). hard_exit re-checks liveness, so
                    # a serviced interrupt always defuses it.
                    g = threading.Timer(max(timeout_s, 10.0), hard_exit)
                    g.daemon = True
                    grace.append(g)
                    g.start()
                    _thread.interrupt_main()
            self._profiler_snapshot()

        t = threading.Timer(timeout_s, fire)
        t.daemon = True
        t.start()
        t_arm = time.perf_counter()
        try:
            yield
        finally:
            # telemetry: the armed window as a span — how long each
            # guarded device fetch actually blocked, fire or no fire
            if self.telemetry_on:
                telemetry.tracer().complete(
                    "watchdog_armed", t_arm, time.perf_counter() - t_arm,
                    track="train", label=label, fired=self.fired)
            t.cancel()
            with lock:  # blocks until an in-flight fire() finishes, so
                # the grace list is complete before we cancel
                state["active"] = False
            for g in grace:
                g.cancel()


# ---------------------------------------------------------- guard state


def init_guard_state(loss_scale: float = 1.0):
    """Device-resident divergence-guard carry for the guarded train step
    (executor.make_guarded_train_step): consecutive-bad-step streak,
    loss-scale, cumulative skip count. Lives on device so the guard makes
    no host round trip; the supervisor mirrors the streak on host from
    the step's returned metrics."""
    import jax.numpy as jnp

    return {"bad_streak": jnp.zeros((), jnp.int32),
            "good_streak": jnp.zeros((), jnp.int32),
            "loss_scale": jnp.asarray(loss_scale, jnp.float32),
            "skipped": jnp.zeros((), jnp.int32)}


# ------------------------------------------------------------ supervisor


class TrainSupervisor:
    """Drives a training loop with checkpoint/resume, preemption handling,
    divergence rewind, and hang detection.

    Lifecycle::

        cfg = FFConfig(checkpoint_dir="ckpt", checkpoint_every=50,
                       on_nonfinite="skip", nonfinite_rewind_after=3)
        model.compile(opt, ...)                # builds the guarded step
        sup = TrainSupervisor(model)
        status = sup.run(num_steps=1000)       # "completed" | "preempted"

    ``run`` resumes from the newest checkpoint in the directory (fresh
    start when none), installs a SIGTERM handler (preemption notice →
    checkpoint at the next step boundary, then stop), checkpoints every
    ``checkpoint_every`` steps, and — when the divergence guard is
    compiled in — skips non-finite steps in-graph and rewinds to the last
    checkpoint after ``rewind_after`` consecutive bad steps.

    ``model.fit`` drives the same machinery through ``install``/
    ``resume``/``after_step``/``finalize`` when FFConfig.checkpoint_dir
    is set.

    Multihost: every controller must construct the supervisor and call
    run() collectively (checkpoint save/restore are collective); SIGTERM
    must be delivered to all controllers (the typical preemption notice
    is). See docs/resilience.md for the caveats."""

    def __init__(self, model, directory: Optional[str] = None, *,
                 checkpoint_every: Optional[int] = None,
                 keep: Optional[int] = None,
                 rewind_after: Optional[int] = None,
                 step_timeout_s: Optional[float] = None,
                 max_rewinds: int = 3,
                 faults: Optional[faultinject.FaultPlan] = None,
                 verbose: bool = False):
        cfg = model.config
        self.model = model
        self.directory = directory or getattr(cfg, "checkpoint_dir", "")
        if not self.directory:
            raise ValueError(
                "TrainSupervisor needs a checkpoint directory: pass one or "
                "set FFConfig.checkpoint_dir")
        self.checkpoint_every = (checkpoint_every
                                 if checkpoint_every is not None
                                 else getattr(cfg, "checkpoint_every", 0))
        self.keep = keep if keep is not None else getattr(
            cfg, "keep_checkpoints", 3)
        self.rewind_after = (rewind_after if rewind_after is not None
                             else getattr(cfg, "nonfinite_rewind_after", 0))
        # async checkpointing (runtime/checkpoint.py): periodic saves
        # publish on the background thread; preempt/final/initial saves
        # stay synchronous (the caller is about to stop or to read the
        # directory), and rewind/finalize quiesce pending publishes first
        self.async_saves = bool(getattr(cfg, "async_checkpointing", False))
        # FFConfig.telemetry="off" silences the supervisor's spans and
        # histograms too (the "off short-circuits every emit" contract)
        self._tm_on = getattr(cfg, "telemetry", "on") != "off"
        # unconditional: flight recorder + SLO monitor adopt this run's
        # knobs INCLUDING telemetry="off" — configure() is how the off
        # state reaches the recorder's own gate. (Watchdog fires,
        # nonfinite rewinds and SIGTERM preempts are trigger sites; the
        # train step-time / checkpoint-stall SLOs window the histograms
        # the saves/steps already feed.)
        flightrec.configure(cfg)
        self.watchdog = Watchdog(step_timeout_s if step_timeout_s is not None
                                 else getattr(cfg, "step_timeout_s", 0.0))
        self.watchdog.telemetry_on = self._tm_on
        self.faults = faults  # None -> the FF_FAULT env plan, read lazily
        self.verbose = verbose
        # poll the guard's per-step nonfinite flag on the host? True for
        # the step-driven run() loop (it syncs the loss anyway); fit()
        # turns it off unless rewind_after needs prompt streak tracking,
        # keeping its dispatch async (skips reconcile from the device
        # counter at finalize)
        self.poll_nonfinite = True
        self.losses: List[float] = []
        self._loss_base = model._step_count  # step number of losses[0] - 1
        self._bad_streak = 0
        self._skips_counted = 0  # host-observed skips (vs device counter)
        self._fault_mark = model._step_count  # last step-fault boundary
        # livelock guard: rewinding to the SAME checkpoint restores the
        # same params/RNG/cursors, so a deterministic NaN (bad data, not
        # a transient) replays identically — cap repeats and abort loudly
        self.max_rewinds = max_rewinds
        self._last_rewind_step: Optional[int] = None
        self._same_rewinds = 0
        self._last_saved_step: Optional[int] = None
        self._resumed: Optional[int] = None
        self._preempted = threading.Event()
        self._prev_sigterm = None
        self._installed = False

    # ---- signal handling -------------------------------------------------

    def install(self):
        """Install the SIGTERM handler (preemption notice). Main thread
        only; idempotent. The handler just sets a flag — the checkpoint
        happens at the next step boundary, where params are consistent."""
        if self._installed:
            return
        ok, prev = install_sigterm(self._on_sigterm)
        if ok:
            self._prev_sigterm = prev
            self._installed = True
        else:
            # not the main thread: preemption must then be signalled by
            # calling request_preempt() from whoever owns the signal
            fflogger.warning(
                "TrainSupervisor: cannot install SIGTERM handler outside "
                "the main thread; call request_preempt() instead")

    def close(self):
        """Restore the previous SIGTERM disposition."""
        if self._installed:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._installed = False

    def _on_sigterm(self, signum, frame):
        self._preempted.set()

    def request_preempt(self):
        """Programmatic preemption notice (same effect as SIGTERM)."""
        self._preempted.set()

    @property
    def preempted(self) -> bool:
        return self._preempted.is_set()

    # ---- checkpoint / resume ---------------------------------------------

    def _fault_plan(self) -> faultinject.FaultPlan:
        return self.faults if self.faults is not None \
            else faultinject.active_plan()

    def _extra_meta(self) -> dict:
        # with the host-overlap pipeline active, dl.next_index has been
        # advanced by the prefetch worker PAST the last trained batch —
        # the checkpoint must record the CONSUMED cursor (the position
        # the synchronous loop would be at), which the pipeline tracks
        # per handed-out batch (runtime/pipeline_loader.py)
        pipe = getattr(self.model, "_pipeline", None)
        cursors = pipe.consumed_cursors() if pipe is not None else None
        if cursors is None:
            cursors = {dl.name: int(dl.next_index)
                       for dl in self.model._dataloaders}
        meta = {
            "rng_key": np.asarray(self.model._rng).tolist(),
            "dataloaders": cursors,
        }
        gs = getattr(self.model, "_guard_state", None)
        if gs is not None:
            meta["loss_scale"] = float(np.asarray(gs["loss_scale"]))
        return meta

    def save(self, reason: str = "periodic") -> Optional[str]:
        """Atomic checkpoint of params/opt/bn + step + RNG + dataloader
        cursors. Skips when the current step is already saved (a preempt
        right after a periodic save must not write twice). With
        async_checkpointing, ONLY periodic saves publish asynchronously —
        a preempt/final/initial save must be durable when this returns,
        so it quiesces pending publishes and writes synchronously."""
        from flexflow_tpu.runtime.checkpoint import (save_checkpoint,
                                                     wait_pending_saves)

        async_ok = self.async_saves and reason == "periodic"
        if self.async_saves and not async_ok:
            # a preempt/final/initial save must leave the directory
            # DURABLE when this returns — quiesce pending publishes even
            # when the step itself was already (asynchronously) saved,
            # and never let the synchronous save below race an older
            # step's pending publish into the same directory
            wait_pending_saves(self.directory)
        step = self.model._step_count
        if self._last_saved_step == step:
            return None
        extra = self._extra_meta()
        extra["reason"] = reason
        t0 = time.perf_counter()
        path = save_checkpoint(self.model, self.directory, step=step,
                               extra_meta=extra, keep=self.keep,
                               async_save=async_ok)
        stall = time.perf_counter() - t0
        # telemetry: the STALL this save cost the training loop (an
        # async publish returns after the in-step snapshot; the
        # background IO is invisible here — which is the point), as a
        # span on the train track + the checkpoint-stall SLO histogram
        if self._tm_on:
            telemetry.tracer().complete(
                "checkpoint_save", t0, stall, track="train", step=step,
                reason=reason, published_async=async_ok)
            telemetry.registry().histogram(
                "ff_checkpoint_stall_seconds",
                "training-loop stall per checkpoint save (async "
                "publishes cost only the in-step snapshot)").observe(
                    stall)
        self._last_saved_step = step
        COUNTERS["checkpoints_saved"] += 1
        if self.verbose:
            fflogger.info("supervisor: checkpoint step %d (%s) -> %s",
                          step, reason, path)
        return path

    def _restore(self, step: int):
        from flexflow_tpu.runtime.checkpoint import (load_meta,
                                                     restore_checkpoint)

        # both callers (resume's lazy scan, rewind via latest_intact_step)
        # verified this step's manifest moments ago — don't hash the
        # payload a second time
        restore_checkpoint(self.model, self.directory, step=step,
                           verify=False)
        meta = load_meta(self.directory, step)
        rng = meta.get("rng_key")
        if rng is not None:
            import jax.numpy as jnp

            self.model._rng = jnp.asarray(np.asarray(rng, np.uint32))
        cursors = meta.get("dataloaders") or {}
        for dl in self.model._dataloaders:
            if dl.name in cursors:
                dl.next_index = int(cursors[dl.name])
        if getattr(self.model, "_guard_state", None) is not None:
            # fresh streaks; keep the backed-off loss scale — restoring a
            # pre-divergence scale would walk straight back into the
            # cliff. A checkpoint without a recorded scale (pre-supervisor
            # or unguarded-run save) falls back to the CONFIGURED scale,
            # not 1.0
            self.model._guard_state = init_guard_state(
                meta.get("loss_scale",
                         getattr(self.model.config, "loss_scale", 1.0)))
        self._bad_streak = 0
        self._skips_counted = 0  # device skip counter was re-initialized
        self._last_saved_step = step

    def _check_topology(self, step: int):
        """Elastic policy safety net at resume time: the compile-time hook
        (runtime/elastic.py) normally refit the mesh already, but a
        supervisor pointed at a directory the config did not name skips
        that path — so enforce 'abort' here too, and log every cross-
        topology resume (the restore itself re-shards regardless)."""
        from flexflow_tpu.runtime.checkpoint import load_meta

        saved = {k: int(v) for k, v in
                 (load_meta(self.directory, step).get("mesh_shape")
                  or {}).items()}
        current = {k: int(v) for k, v in
                   (self.model.config.mesh_shape or {}).items()}
        if not saved or saved == current:
            return
        policy = getattr(self.model.config, "on_topology_change",
                         "resume_resharded")
        if policy == "abort":
            from flexflow_tpu.runtime.elastic import TopologyChangedError

            raise TopologyChangedError(
                f"checkpoint step {step} in {self.directory} was saved on "
                f"mesh {saved} but this model compiled mesh {current} and "
                f"on_topology_change='abort'")
        COUNTERS["elastic_resumes"] += 1
        fflogger.warning(
            "supervisor: resuming across a topology change — checkpoint "
            "mesh %s -> current mesh %s (params/opt-state re-shard onto "
            "the new placement; policy=%s)", saved, current, policy)

    def resume(self) -> int:
        """Restore the newest INTACT checkpoint in the directory (0 =
        fresh start). A corrupted or unreadable newer step is skipped
        with a warning (lazy manifest verification, one payload hash per
        step actually examined); when every existing step fails, the
        corruption error propagates — silently starting fresh over
        damaged checkpoints would destroy the evidence. On a fresh start
        with rewind enabled, takes an initial step-0 checkpoint so a
        rewind target always exists."""
        from flexflow_tpu.runtime.checkpoint import scan_and_restore

        def _count_skip(_s):
            COUNTERS["corrupt_checkpoints_skipped"] += 1

        def _restore_cand(cand):
            self._check_topology(cand)
            self._restore(cand)

        # checkpoint.scan_and_restore is the ONE newest-intact-first
        # resume policy (auto_resume rides the same one): lazy, so the
        # normal resume pays one hash pass over one checkpoint — and none
        # at all for the step the compile-time elastic hook just verified
        step = scan_and_restore(self.model, self.directory,
                                restore=_restore_cand, on_skip=_count_skip,
                                who="supervisor")
        if step is None:
            self._resumed = 0
            if self.rewind_after:
                self.save(reason="initial")
            return 0
        self.losses.clear()
        self._loss_base = step
        self._fault_mark = step
        self._resumed = step
        COUNTERS["resumes"] += 1
        fflogger.info("supervisor: resumed from step %d in %s", step,
                      self.directory)
        return step

    def rewind(self):
        """Divergence recovery: back to the last checkpoint (params, opt
        state, step counter, RNG, dataloader cursors)."""
        from flexflow_tpu.runtime.checkpoint import (latest_intact_step,
                                                     wait_pending_saves)

        if self.async_saves:
            # the rewind target may still be mid-publish on the background
            # thread — the intact scan must see it published. A STALE
            # publish failure surfacing here must not abort the recovery
            # (the failed step is simply absent; the scan below falls back
            # to the newest step that actually published intact)
            try:
                wait_pending_saves(self.directory)
            except RuntimeError as e:
                fflogger.warning(
                    "rewind: a pending async checkpoint save had failed "
                    "(%s) — rewinding to the newest intact step instead",
                    e)
        step = latest_intact_step(
            self.directory,
            verify=bool(getattr(self.model.config, "verify_checkpoints",
                                True)))
        if step is None:
            raise RuntimeError(
                f"rewind requested but no checkpoint (passing integrity "
                f"verification) exists in {self.directory}")
        if step == self._last_rewind_step:
            self._same_rewinds += 1
        else:
            self._last_rewind_step = step
            self._same_rewinds = 1
        if self._same_rewinds > self.max_rewinds:
            raise RuntimeError(
                f"supervisor: rewound to checkpoint step {step} "
                f"{self._same_rewinds} times with no progress — a rewind "
                f"replays identical params/RNG/batches, so this "
                f"non-finite condition is deterministic (bad data or a "
                f"diverged config), not transient; aborting instead of "
                f"livelocking")
        fflogger.warning(
            "supervisor: %d consecutive non-finite steps at step %d — "
            "rewinding to checkpoint step %d", self._bad_streak,
            self.model._step_count, step)
        # losses[i] is the loss of step _loss_base + i + 1: truncate the
        # steps being discarded (index relative to the resume offset)
        if self._tm_on:
            telemetry.tracer().instant(
                "rewind", track="train",
                from_step=self.model._step_count,
                to_step=step, bad_streak=self._bad_streak)
            flightrec.trip("nonfinite_rewind",
                           from_step=self.model._step_count,
                           to_step=step, bad_streak=self._bad_streak)
        del self.losses[max(step - self._loss_base, 0):]
        self._restore(step)
        COUNTERS["rewinds"] += 1

    # ---- stepping ---------------------------------------------------------

    def _deliver_step_faults(self, step_no: int):
        # range match, not equality: fit's scanned program advances the
        # step counter scan_steps at a time, and an event landing inside
        # a chunk must still fire at the next boundary
        plan = self._fault_plan()
        lo = min(self._fault_mark, step_no)
        self._fault_mark = step_no
        if plan.in_step_range("sigterm", lo, step_no):
            os.kill(os.getpid(), signal.SIGTERM)
            # signal delivery is asynchronous; give the interpreter a
            # moment to run the handler before the boundary check
            self._preempted.wait(timeout=1.0)

    def after_step(self, nonfinite: Optional[bool] = None) -> bool:
        """Step-boundary bookkeeping shared by run() and model.fit():
        divergence streak/rewind, injected + real preemption, periodic
        checkpointing. Returns True when the caller must stop (a
        preemption checkpoint was written)."""
        step_no = self.model._step_count
        if nonfinite is None and self.poll_nonfinite \
                and self.model._guard_state is not None:
            lm = getattr(self.model, "_last_metrics", None) or {}
            if "nonfinite" in lm:
                # this fetch blocks on the step's device work — the spot
                # where a hung collective surfaces on the fit path
                with self.watchdog.arm(f"step {step_no} guard poll"):
                    nonfinite = bool(int(np.asarray(lm["nonfinite"])))
        if nonfinite:
            self._bad_streak += 1
            self._skips_counted += 1
            COUNTERS["steps_skipped"] += 1
            if self.rewind_after and self._bad_streak >= self.rewind_after:
                self.rewind()
                return False
        elif nonfinite is not None:
            self._bad_streak = 0
        self._deliver_step_faults(step_no)
        if self._preempted.is_set():
            self.save(reason="preempt")
            COUNTERS["preempt_stops"] += 1
            if self._tm_on:
                # TERMINAL trigger: the caller stops (and typically
                # exits) after the preempt checkpoint — write the
                # bundle synchronously, don't leave it on a daemon
                # debounce timer the interpreter teardown would kill
                flightrec.trip("sigterm_preempt",
                               step=self.model._step_count)
                flightrec.recorder().flush(timeout=15.0)
            fflogger.warning(
                "supervisor: preemption notice — checkpointed step %d, "
                "stopping", self.model._step_count)
            return True
        if (self.checkpoint_every
                and (self._last_saved_step is None
                     or step_no - self._last_saved_step
                     >= self.checkpoint_every)):
            self.save(reason="periodic")
        if self._tm_on:
            # the train-side SLO tick (step-time / checkpoint-stall
            # budgets): one predicate + one time compare until a full
            # window has elapsed
            flightrec.slo_monitor().maybe_evaluate()
        return False

    def nan_due(self) -> bool:
        """Is a nan_loss fault scheduled for the step about to run? Used
        by both run() and fit() so the injection path is identical."""
        due = self._fault_plan().at_step("nan_loss",
                                         self.model._step_count + 1)
        if due and self.model._guard_state is None:
            raise RuntimeError(
                "FF_FAULT nan_loss injection requires the divergence guard "
                "(set FFConfig.on_nonfinite='skip' or 'backoff' before "
                "compile())")
        return due

    def step(self) -> float:
        """One supervised training step on the next staged batch: injects
        scheduled NaNs in-graph, arms the watchdog around the blocking
        loss fetch, records the loss."""
        model = self.model
        step_no = model._step_count + 1  # 1-based index of this step
        inject = self.nan_due()
        hang = self._fault_plan().at_step("hang", step_no)
        batch = model._stage_batch()
        loss, _ = model._run_train_step(batch, inject_nan=inject)
        with self.watchdog.arm(f"train step {step_no}"):
            if hang and self.watchdog.timeout_s > 0:
                # simulate a stuck collective: block well past the
                # watchdog so its dump+abort path runs
                time.sleep(self.watchdog.timeout_s * 3)
            loss_f = float(loss)
        self.losses.append(loss_f)
        return loss_f

    def run(self, num_steps: int) -> str:
        """Supervised loop until ``model._step_count == num_steps``.
        Resumes from the newest checkpoint first (no-op when fresh).
        Returns "completed" or "preempted" (after writing the preemption
        checkpoint — process exit is the caller's call, so tests can
        resume in-process)."""
        assert self.model._train_step is not None or \
            self.model._guard_state is not None, \
            "compile() with an optimizer first"
        assert self.model._dataloaders, "attach SingleDataLoader(s) first"
        self.install()
        try:
            if self._resumed is None:
                self.resume()
            while self.model._step_count < num_steps:
                self.step()
                if self.after_step():
                    return "preempted"
            self.save(reason="final")
            return "completed"
        finally:
            self.close()

    def finalize(self):
        """End-of-fit hook: final checkpoint (checkpoint_dir being set IS
        the request to persist — checkpoint_every == 0 just means no
        periodic saves in between, matching run()'s final save), skip-
        counter reconciliation, handler restore, counter report."""
        try:
            # after a watchdog abort the runtime is wedged — a final save
            # would block forever on the same hung device work (and the
            # hard-exit backstop is gone once the interrupt is serviced);
            # the last periodic/preempt checkpoint stands instead
            if not self.watchdog.fired:
                self.save(reason="final")
            if self.async_saves:
                # drain the publisher even when the final save was skipped
                # (watchdog abort): pending publishes are pure host-side
                # IO of already-snapshotted state, safe to wait on — and a
                # failed one must surface here, not vanish with the thread
                from flexflow_tpu.runtime.checkpoint import \
                    wait_pending_saves

                wait_pending_saves(self.directory)
        finally:
            self.close()
        gs = getattr(self.model, "_guard_state", None)
        if gs is not None:
            # when per-step polling was off (async fit), the device-side
            # skip counter still has the truth — fold in what the host
            # didn't observe
            skipped = int(np.asarray(gs["skipped"]))
            if skipped > self._skips_counted:
                COUNTERS["steps_skipped"] += skipped - self._skips_counted
                self._skips_counted = skipped
        snap = {k: v for k, v in COUNTERS.items() if v}
        if snap:
            fflogger.info("resilience counters: %s", snap)
