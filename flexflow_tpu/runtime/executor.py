"""Graph executor: lowers the op graph + strategy table into jitted,
GSPMD-sharded XLA programs.

This replaces the reference's entire launch machinery — per-op IndexLaunchers,
the FFMapper's tag->ParallelConfig->device resolution (src/mapper/mapper.cc:
346-424), and Legion's implicit region copies — with ONE traced program per
(train step | inference step): each op's output gets a
`with_sharding_constraint` from its ParallelConfig (the "mapper tag"), and XLA
GSPMD inserts all resharding/halo/collective traffic over ICI. The jit cache
plays the role of Legion tracing (flexflow_cbinding.py:394-397).
"""

from __future__ import annotations

import functools
import itertools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flexflow_tpu.ffconst import CompMode, LossType, MetricsType, dtype_to_np
from flexflow_tpu.ops.base import InputOp, Op
from flexflow_tpu.parallel.mesh import mesh_shape_dict
from flexflow_tpu.parallel.pconfig import ParallelConfig
from flexflow_tpu.runtime.initializer import init_weight
from flexflow_tpu.runtime.loss import compute_loss
from flexflow_tpu.runtime.metrics import batch_metrics


def resolve_axis_map(pc: ParallelConfig, mesh_shape: Dict[str, int],
                     ndims: int) -> Dict[str, Optional[int]]:
    """Fill in pc.axis_map from degrees when a strategy came from a file
    (degrees only). Greedy: each partitioned dim takes unused mesh axes whose
    sizes multiply to its degree; sample dim prefers 'data'."""
    from flexflow_tpu.parallel.pconfig import CONTRACT, EXPERT, STAGE

    if pc.axis_map is not None:
        # explicit axis_map (search output, or a file's @axismap record):
        # validate against THIS mesh — a file written on a differently-
        # named mesh must fail here with the axis named, not deep inside
        # JAX; a same-name different-SIZE mesh silently changes degrees,
        # so check the recorded dims still match
        missing = [ax for ax, d in pc.axis_map.items()
                   if d is not None and ax not in mesh_shape]
        if missing:
            raise ValueError(
                f"strategy axis_map references mesh axes {missing} absent "
                f"from this mesh {mesh_shape} — the strategy was "
                f"produced for a different mesh; regenerate it or rename "
                f"the mesh axes")
        # dim indices must be valid for THIS op's rank: a hand-edited /
        # corrupt @axismap record would otherwise surface as a bare
        # IndexError inside from_axis_map rather than a diagnosis
        bad = {ax: d for ax, d in pc.axis_map.items()
               if d is not None and d not in (CONTRACT, STAGE, EXPERT)
               and not (0 <= d < ndims)}
        if bad:
            raise ValueError(
                f"strategy axis_map entries {bad} map mesh axes to tensor "
                f"dims outside this op's rank {ndims} (valid: 0..{ndims - 1} "
                f"or the CONTRACT/STAGE/EXPERT sentinels) — the @axismap "
                f"record is corrupt or was written for a different operator")
        if pc.dims:
            # re-derive degrees exactly the way the serializer did
            # (from_axis_map: CONTRACT appends a trailing degree, STAGE
            # contributes none) so a correct unchanged-mesh strategy
            # never trips the drift warning
            from flexflow_tpu.parallel.pconfig import ParallelConfig as _PC

            expect = _PC.from_axis_map(ndims, mesh_shape, pc.axis_map).dims
            if tuple(expect) != tuple(pc.dims):
                from flexflow_tpu.logger import fflogger

                fflogger.warning(
                    "strategy axis_map on this mesh gives degrees %s but "
                    "the strategy recorded %s — the mesh axis sizes "
                    "changed since it was written; executing at the NEW "
                    "degrees", tuple(expect), tuple(pc.dims))
        return pc.axis_map
    remaining = dict(mesh_shape)
    axis_map: Dict[str, Optional[int]] = {}
    # a degree list one longer than the output rank carries a trailing
    # CONTRACT (row-parallel) degree — the reference's replica-dim
    # convention (linear.cu:171-192); resolved like any other dim but
    # mapped to the CONTRACT sentinel
    targets = list(range(min(ndims, len(pc.dims))))
    if len(pc.dims) == ndims + 1 and pc.dims[ndims] > 1:
        targets.append(ndims)
    order = sorted(targets, key=lambda d: (d != 0,))  # sample dim first
    for d in order:
        deg = pc.dims[d]
        logical = CONTRACT if d == ndims else d
        if deg == 1:
            continue
        # prefer canonical axis for the dim role
        prefs = (["data"] if d == 0 else []) + list(remaining.keys())
        single = [ax for ax in prefs if remaining.get(ax) == deg]
        if single:
            axis_map[single[0]] = logical
            del remaining[single[0]]
            continue
        # general case: smallest subset of remaining axes whose sizes
        # multiply to the degree (covers 3+-axis factorizations)
        found = None
        axes = list(remaining.keys())
        for r in range(2, len(axes) + 1):
            for combo in itertools.combinations(axes, r):
                prod = 1
                for ax in combo:
                    prod *= remaining[ax]
                if prod == deg:
                    found = combo
                    break
            if found:
                break
        if not found:
            raise ValueError(
                f"strategy degree {deg} on dim {d} not expressible as a "
                f"product of unused mesh axes (mesh {mesh_shape}, "
                f"remaining {remaining})")
        for ax in found:
            axis_map[ax] = logical
            del remaining[ax]
    return axis_map


class GraphExecutor:
    def __init__(self, model):
        self.model = model
        self.mesh: Mesh = model.mesh
        self.mesh_shape = mesh_shape_dict(self.mesh)
        self._op_axis_maps: Dict[str, Dict[str, Optional[int]]] = {}
        self._batch_sharding_cache: Dict[Tuple[str, int], NamedSharding] = {}
        self._resolve_strategies()

    # ---- strategy resolution ------------------------------------------------

    def _resolve_strategies(self):
        strategies = self.model.config.strategies
        for op in self.model.ops:
            if isinstance(op, InputOp):
                continue
            pc = strategies.get(op.name)
            nd = op.outputs[0].num_dims
            if pc is None:
                pc = ParallelConfig.data_parallel(
                    nd, self.mesh_shape.get("data", 1))
                if "data" not in self.mesh_shape:
                    pc = ParallelConfig.replicated(nd)
            am = resolve_axis_map(pc, self.mesh_shape, nd)
            self._op_axis_maps[op.name] = am

    def op_output_sharding(self, op: Op) -> NamedSharding:
        am = self._op_axis_maps.get(op.name, {})
        pspec = ParallelConfig(axis_map=am).to_partition_spec(
            op.outputs[0].num_dims, list(self.mesh.axis_names))
        return NamedSharding(self.mesh, pspec)

    def input_sharding(self, tensor) -> NamedSharding:
        # batch-shard graph inputs on 'data' if present
        entries = [None] * tensor.num_dims
        if "data" in self.mesh_shape and self.mesh_shape["data"] > 1:
            entries[0] = "data"
        return NamedSharding(self.mesh, P(*entries))

    def param_shardings(self) -> Dict[str, Dict[str, NamedSharding]]:
        fsdp = getattr(self.model.config, "fsdp_axis", "")
        if fsdp and fsdp not in self.mesh_shape:
            raise ValueError(
                f"fsdp_axis={fsdp!r} is not a mesh axis "
                f"(mesh {self.mesh_shape})")
        out: Dict[str, Dict[str, NamedSharding]] = {}
        for op in self.model.ops:
            specs = op.weight_specs()
            if not specs:
                continue
            am = self._op_axis_maps.get(op.name, {})
            wp = op.weight_partition(am)
            shapes = {w.name: w.shape for w in specs}
            out[op.name] = {
                name: NamedSharding(
                    self.mesh,
                    _with_fsdp(ps, shapes.get(name), fsdp,
                               self.mesh_shape.get(fsdp, 1)) if fsdp else ps)
                for name, ps in wp.items()}
        return out

    def grad_scatter_shardings(self) -> Dict[str, Dict[str, NamedSharding]]:
        """ZeRO-1 / bucketed-grad-sync layout (FFConfig.overlap_grad_sync):
        each weight's strategy(+FSDP) sharding with its largest
        still-unsharded divisible dim ADDITIONALLY split over the data
        axis — the per-op "bucket" the accumulation scan reduce-scatters
        gradients into, and the layout the ZeRO-1 optimizer update runs
        in. A weight the data axis cannot divide (or that FSDP already
        shards over it, the ZeRO-3 case) keeps its param sharding and
        rides the plain all-reduce path. Returns {} when the mesh has no
        data axis > 1 — nothing to scatter over."""
        n = self.mesh_shape.get("data", 0)
        if n <= 1:
            return {}
        base = self.param_shardings()
        out: Dict[str, Dict[str, NamedSharding]] = {}
        for op in self.model.ops:
            specs = op.weight_specs()
            if not specs:
                continue
            per = {}
            for spec in specs:
                ns = base.get(op.name, {}).get(spec.name)
                if ns is None:
                    continue
                per[spec.name] = NamedSharding(
                    self.mesh, _with_fsdp(ns.spec, spec.shape, "data", n))
            if per:
                out[op.name] = per
        return out

    # ---- parameter / state initialization -----------------------------------

    def init_params(self, rng_key) -> Dict[str, Dict[str, jnp.ndarray]]:
        """Sharded param init: each weight's init runs jitted with its target
        sharding as out_sharding, so a vocab-sharded embedding table never
        materializes replicated. Deliberately one tiny jit per weight (NOT
        one batched program per model): the key is a traced argument, so
        same-shape inits share a jaxpr and jax's lowering/compilation
        caches dedupe them across ops, models, and tests in a process — a
        per-model batched program bakes the per-op key constants into a
        unique HLO and recompiles for every model built."""
        shardings = self.param_shardings()
        params: Dict[str, Dict[str, jnp.ndarray]] = {}
        for op in self.model.ops:
            specs = op.weight_specs()
            if not specs:
                continue
            op_params = {}
            master_bf16 = self.model.config.master_dtype == "bfloat16"
            tied = getattr(self.model, "_tied", {})
            for i, spec in enumerate(specs):
                if (op.name, spec.name) in tied:
                    continue  # storage lives with the tie source
                key = jax.random.fold_in(
                    jax.random.fold_in(rng_key, _stable_hash(op.name)), i)
                sharding = shardings[op.name].get(spec.name)
                init_fn = functools.partial(init_weight, spec)
                dtype = dtype_to_np(spec.dtype)

                def _init(k, f=init_fn, d=dtype):
                    w = f(k, dtype=d)
                    # bf16 master weights: storage halves, init stays f32
                    if master_bf16 and w.dtype == jnp.float32:
                        w = w.astype(jnp.bfloat16)
                    return w

                op_params[spec.name] = jax.jit(
                    _init, out_shardings=sharding)(key)
            params[op.name] = op_params
        return params

    def init_state(self) -> Dict[str, Dict[str, jnp.ndarray]]:
        state = {}
        for op in self.model.ops:
            if op.stateful:
                s = op.init_state()
                state[op.name] = {k: jnp.asarray(v) for k, v in s.items()}
        return state

    # ---- forward interpretation ---------------------------------------------

    def apply_graph(self, params, state, input_values: Dict[Any, jnp.ndarray],
                    *, training: bool, rng) -> Tuple[Dict[Any, jnp.ndarray], Dict]:
        """Interpret the graph in topo order. Returns (tensor->value map,
        new_state)."""
        vals: Dict[Any, jnp.ndarray] = dict(input_values)
        new_state: Dict[str, Dict] = {}
        # mixed precision: master params stay f32; compute runs in bf16 on the
        # MXU when config.compute_dtype == "bfloat16" (autodiff through the
        # casts yields f32 grads)
        bf16 = self.model.config.compute_dtype == "bfloat16"

        def to_compute(a):
            return a.astype(jnp.bfloat16) if (bf16 and a.dtype == jnp.float32) else a

        vals = {k: to_compute(v) for k, v in vals.items()}
        for idx, op in enumerate(self.model.ops):
            if isinstance(op, InputOp):
                t = op.outputs[0]
                if t not in vals:
                    raise ValueError(f"missing input value for {op.name}")
                continue
            xs = [vals[t] for t in op.inputs]
            op_rng = None
            if op.needs_rng and rng is not None:
                op_rng = jax.random.fold_in(rng, idx)
                seed = getattr(op, "seed", 0)
                if seed:
                    op_rng = jax.random.fold_in(op_rng, seed)
            p = resolve_tied_params(self.model, params, op.name,
                                    params.get(op.name, {}))
            if bf16:
                p = {k: to_compute(v) for k, v in p.items()}
            kwargs = {}
            if getattr(op, "wants_shard_ctx", False):
                kwargs["shard_ctx"] = {
                    "mesh": self.mesh,
                    "axis_map": self._op_axis_maps.get(op.name, {}),
                    "sp_mode": getattr(self.model.config, "sp_mode", "ring"),
                }
            # named_scope stamps the op name into the HLO metadata of every
            # instruction it traces, so xla_trace/Perfetto spans of the
            # PRODUCTION jitted program attribute back to graph ops — the
            # in-situ analog of the reference's --profiling per-op events
            # (linear.cu:526-553); profiler.profile_step stays the unfused
            # wall-timer variant
            with jax.named_scope(op.name):
                if op.stateful:
                    outs, ns = op.forward_stateful(
                        p, state.get(op.name, {}), xs,
                        training=training, rng=op_rng)
                    new_state[op.name] = ns
                else:
                    outs = op.forward(p, xs, training=training, rng=op_rng,
                                      **kwargs)
            sharding = self.op_output_sharding(op)
            for i, t in enumerate(op.outputs):
                v = outs[i]
                if v.ndim == t.num_dims and _spec_rank_ok(sharding.spec, v.ndim):
                    v = jax.lax.with_sharding_constraint(v, sharding)
                elif i == 0 and v.ndim == t.num_dims:
                    # the strategy's axis map targets the primary output; a
                    # rank mismatch there is a bad strategy entry, not a
                    # condition to silently skip (secondary outputs of other
                    # ranks — e.g. MoE's scalar aux loss — stay unconstrained)
                    raise ValueError(
                        f"sharding constraint for {op.name!r} has rank "
                        f"{len(sharding.spec)} but its output is rank "
                        f"{v.ndim} — the strategy entry does not match this "
                        f"op's output; fix or regenerate the strategy file")
                vals[t] = v
        for k, v in state.items():
            if k not in new_state:
                new_state[k] = v
        return vals, new_state

    # ---- compiled steps -----------------------------------------------------

    def _make_loss_fn(self, loss_type: LossType,
                      metric_types: List[MetricsType], final_tensor,
                      label_key="label"):
        """loss_fn(p, state, batch, rng) -> (loss, (new_state, mets)) —
        shared by the plain, scanned, and divergence-guarded step
        builders."""
        input_ops = [op for op in self.model.ops if isinstance(op, InputOp)]
        aux_tensors = list(getattr(self.model, "_aux_tensors", ()))

        def loss_fn(p, st, batch, rng):
            input_values = {op.outputs[0]: batch[op.name] for op in input_ops}
            vals, new_state = self.apply_graph(
                p, st, input_values, training=True, rng=rng)
            logits = vals[final_tensor]
            loss = compute_loss(loss_type, logits, batch[label_key])
            for t in aux_tensors:  # e.g. MoE load-balancing losses
                loss = loss + vals[t]
            mets = batch_metrics(
                loss_type, metric_types, logits, batch[label_key],
                ignore_index=getattr(self.model.config,
                                     "metrics_ignore_index", None))
            return loss, (new_state, mets)

        return loss_fn

    def _train_step_body(self, optimizer, loss_type: LossType,
                         metric_types: List[MetricsType], final_tensor,
                         label_key="label"):
        """The un-jitted fused fwd+bwd+update body shared by the per-step
        program and the scanned multi-step program."""
        accum = max(1, int(getattr(self.model.config, "grad_accum_steps", 1)))
        loss_fn = self._make_loss_fn(loss_type, metric_types, final_tensor,
                                     label_key)

        def step(params, opt_state, state, batch, rng):
            (loss, (new_state, mets)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, state, batch, rng)
            new_params, new_opt_state = optimizer.update(params, grads, opt_state)
            return new_params, new_opt_state, new_state, loss, mets

        # in-graph grad-sync overlap (FFConfig.overlap_grad_sync): carry
        # the accumulated grads through the scan in the data-scattered
        # ZeRO-1 bucket layout instead of the full (replicated /
        # all-reduced) tree — GSPMD then lowers each microbatch's
        # cross-data-shard grad reduction to a reduce-scatter whose
        # collective overlaps the NEXT microbatch's backward, and the
        # scan epilogue shrinks to the final bucket + the sharded update
        overlap = (bool(getattr(self.model.config, "overlap_grad_sync",
                                False))
                   and self.mesh_shape.get("data", 1) > 1)
        scatter = self.grad_scatter_shardings() if overlap else {}

        def accum_step(params, opt_state, state, batch, rng):
            # gradient accumulation: the global batch splits into `accum`
            # equal microbatches scanned through fwd+bwd with summed grads
            # and ONE optimizer update — numerically the full-batch step
            # (all losses are batch means, so mean-of-means is exact),
            # with activation memory of a microbatch. Net-new vs the
            # reference (its global batch is always one wave of shards).
            for k, v in batch.items():
                if v.shape[0] % accum:
                    raise ValueError(
                        f"batch dim {v.shape[0]} of {k!r} not divisible by "
                        f"grad_accum_steps={accum}")
            micro = {k: v.reshape(accum, v.shape[0] // accum, *v.shape[1:])
                     for k, v in batch.items()}

            def constrain(tree):
                if not scatter:
                    return tree
                from flexflow_tpu.runtime.optimizer import \
                    apply_tree_shardings

                return apply_tree_shardings(
                    tree, scatter, jax.lax.with_sharding_constraint)

            def accum_zero(p):
                # low-precision grads accumulate in f32: summing `accum`
                # bf16 microbatch grads in bf16 drops low bits on every
                # add (the scan used to sum in the grad dtype); the f32
                # carry only lives for the scan's duration
                dt = jnp.float32 if p.dtype in (jnp.bfloat16,
                                                jnp.float16) else p.dtype
                return jnp.zeros(p.shape, dt)

            def body(carry, mb_i):
                g_acc, st = carry
                mb, i = mb_i
                (loss, (st, mets)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(
                        params, st, mb, jax.random.fold_in(rng, i))
                g_acc = jax.tree.map(
                    lambda a, g: a + g.astype(a.dtype), g_acc, grads)
                return (constrain(g_acc), st), (loss, mets)

            zeros = constrain(jax.tree.map(accum_zero, params))
            (g_sum, new_state), (losses, mets) = jax.lax.scan(
                body, (zeros, state),
                (micro, jnp.arange(accum, dtype=jnp.int32)))
            grads = jax.tree.map(lambda g: g / accum, g_sum)
            loss = jnp.mean(losses)
            # counts and totals (accuracy_count/_total) sum across
            # microbatches; mean metrics average (equal sizes -> exact)
            mets = {k: (jnp.sum(v) if k.endswith(("_count", "_total"))
                        else jnp.mean(v))
                    for k, v in mets.items()}
            new_params, new_opt_state = optimizer.update(params, grads,
                                                         opt_state)
            return new_params, new_opt_state, new_state, loss, mets

        return accum_step if accum > 1 else step

    def make_train_step(self, optimizer, loss_type: LossType,
                        metric_types: List[MetricsType], final_tensor,
                        label_key="label"):
        step = self._train_step_body(optimizer, loss_type, metric_types,
                                     final_tensor, label_key)
        return jax.jit(step, donate_argnums=(0, 1, 2))

    def make_guarded_train_step(self, optimizer, loss_type: LossType,
                                metric_types: List[MetricsType], final_tensor,
                                guard_cfg: Dict, label_key="label"):
        """Divergence-guarded train step (runtime/resilience.py): the
        finite-loss/grad-norm check and the skip/keep selection are
        compiled INTO the step — one jnp.isfinite reduction over the loss
        plus the global grad-norm (f32), a jnp.where per state leaf — so
        the happy path makes NO device→host round trip the plain step
        doesn't. With loss_scale == 1.0 and every step finite, the
        trajectory is bitwise identical to make_train_step's.

        Signature:
            fn(params, opt_state, state, batch, rng, guard_state,
               inject_nan)
              -> (params, opt_state, state, loss, mets, guard_state)
        guard_state: resilience.init_guard_state() pytree (device-resident
        streaks / loss scale / skip counter). inject_nan: traced bool —
        the FF_FAULT nan_loss hook adds NaN to the loss in-graph, so
        injection reuses the one compiled program.

        Returned mets add: nonfinite (0/1 this step), grad_norm,
        loss_scale, skipped_total."""
        mode = guard_cfg.get("on_nonfinite", "skip")
        backoff = float(guard_cfg.get("backoff", 2.0))
        growth_interval = int(guard_cfg.get("growth_interval", 200))
        min_scale = float(guard_cfg.get("min_loss_scale", 2.0 ** -14))
        max_scale = float(guard_cfg.get("max_loss_scale", 2.0 ** 15))
        loss_fn = self._make_loss_fn(loss_type, metric_types, final_tensor,
                                     label_key)

        def gstep(params, opt_state, state, batch, rng, gstate, inject_nan):
            scale = gstate["loss_scale"]

            def scaled(p, st, b, r):
                loss, aux = loss_fn(p, st, b, r)
                loss = loss + jnp.where(inject_nan, jnp.nan, 0.0
                                        ).astype(loss.dtype)
                return loss * scale.astype(loss.dtype), (loss, aux)

            (_, (raw_loss, (new_state, mets))), grads = jax.value_and_grad(
                scaled, has_aux=True)(params, state, batch, rng)
            inv = (1.0 / scale)
            grads = jax.tree_util.tree_map(
                lambda g: (g * inv.astype(g.dtype)), grads)
            leaves = jax.tree_util.tree_leaves(grads)
            gnorm_sq = jnp.float32(0.0)
            for g in leaves:
                gnorm_sq = gnorm_sq + jnp.sum(
                    jnp.square(g.astype(jnp.float32)))
            finite = jnp.isfinite(raw_loss) & jnp.isfinite(gnorm_sq)
            new_params, new_opt_state = optimizer.update(params, grads,
                                                         opt_state)

            def sel(new, old):
                return jax.tree_util.tree_map(
                    lambda n, o: jnp.where(finite, n, o), new, old)

            params_out = sel(new_params, params)
            opt_out = sel(new_opt_state, opt_state)
            state_out = sel(new_state, state)
            bad = ~finite
            streak = jnp.where(bad, gstate["bad_streak"] + 1, 0)
            good = jnp.where(bad, 0, gstate["good_streak"] + 1)
            if mode == "backoff":
                down = jnp.maximum(scale / backoff, min_scale)
                grow = good >= growth_interval
                up = jnp.where(grow, jnp.minimum(scale * backoff, max_scale),
                               scale)
                new_scale = jnp.where(bad, down, up)
                good = jnp.where(grow & ~bad, 0, good)
            else:
                new_scale = scale
            new_gstate = {"bad_streak": streak, "good_streak": good,
                          "loss_scale": new_scale,
                          "skipped": gstate["skipped"]
                          + bad.astype(jnp.int32)}
            mets = dict(mets)
            mets["nonfinite"] = bad.astype(jnp.int32)
            mets["grad_norm"] = jnp.sqrt(gnorm_sq)
            mets["loss_scale"] = new_scale
            mets["skipped_total"] = new_gstate["skipped"]
            return (params_out, opt_out, state_out, raw_loss, mets,
                    new_gstate)

        return jax.jit(gstep, donate_argnums=(0, 1, 2, 5))

    def make_train_scan(self, optimizer, loss_type: LossType,
                        metric_types: List[MetricsType], final_tensor,
                        label_key="label"):
        """Multi-step trainer: ONE device program runs `n_steps` training
        steps via lax.scan over the pre-batched device-resident dataset
        (dataloader staging shape (num_batches, batch, ...)).

        This is the TPU-native analog of the reference's Legion tracing
        around each training iteration (flexflow_cbinding.py:394-397,
        base_model.py:408-418): where Legion records the task launch
        pattern once and replays it without re-analysis, here the whole
        step sequence is a single compiled XLA program, so per-step host
        dispatch (batch slice + rng split + step launch) disappears
        entirely — which matters whenever host->device latency is
        non-trivial relative to step time.

        Returned fn signature:
            fn(params, opt_state, state, staged, rng, start, n_steps)
        with `staged` a dict name -> (num_batches, batch, ...) device
        array, `start` the starting batch index (wraps mod num_batches),
        and `n_steps` STATIC. Returns (params, opt_state, state, losses,
        mets) with per-step losses stacked shape (n_steps,) and each
        metric stacked likewise.
        """
        step = self._train_step_body(optimizer, loss_type, metric_types,
                                     final_tensor, label_key)

        def scan_fn(params, opt_state, state, staged, rng, start, n_steps):
            # min across datasets: loaders may stage unequal sample counts
            # (model.py's cursor math uses the same modulus)
            nb = min(v.shape[0] for v in staged.values())

            def body(carry, i):
                params, opt_state, state = carry
                bi = jax.lax.rem(start + i, nb)
                batch = {k: jax.lax.dynamic_index_in_dim(v, bi, 0,
                                                         keepdims=False)
                         for k, v in staged.items()}
                step_rng = jax.random.fold_in(rng, i)
                params, opt_state, state, loss, mets = step(
                    params, opt_state, state, batch, step_rng)
                return (params, opt_state, state), (loss, mets)

            (params, opt_state, state), (losses, mets) = jax.lax.scan(
                body, (params, opt_state, state),
                jnp.arange(n_steps, dtype=jnp.int32))
            return params, opt_state, state, losses, mets

        return jax.jit(scan_fn, static_argnums=(6,), donate_argnums=(0, 1, 2))

    def make_eval_step(self, loss_type: LossType,
                       metric_types: List[MetricsType], final_tensor,
                       label_key="label"):
        input_ops = [op for op in self.model.ops if isinstance(op, InputOp)]

        def step(params, state, batch):
            input_values = {op.outputs[0]: batch[op.name] for op in input_ops}
            vals, _ = self.apply_graph(params, state, input_values,
                                       training=False, rng=None)
            logits = vals[final_tensor]
            loss = compute_loss(loss_type, logits, batch[label_key])
            mets = batch_metrics(
                loss_type, metric_types, logits, batch[label_key],
                ignore_index=getattr(self.model.config,
                                     "metrics_ignore_index", None))
            return loss, mets, logits

        return jax.jit(step)

    def make_forward(self, final_tensors=None, training: bool = False):
        """Plain forward fn over graph inputs (used by __graft_entry__ and
        inference)."""
        input_ops = [op for op in self.model.ops if isinstance(op, InputOp)]
        finals = final_tensors or [self.model.ops[-1].outputs[0]]

        def fwd(params, state, batch, rng=None):
            input_values = {op.outputs[0]: batch[op.name] for op in input_ops}
            vals, _ = self.apply_graph(params, state, input_values,
                                       training=training, rng=rng)
            return [vals[t] for t in finals]

        return fwd

    def batch_sharding(self, name: str, ndim: int) -> NamedSharding:
        """The committed placement for one batch entry, CACHED per
        (name, ndim) — building a fresh NamedSharding (and walking the op
        list) every step was pure hot-path overhead, and the prefetch
        pipeline (runtime/pipeline_loader.py) needs the same object so
        ahead-of-time puts and in-step puts agree exactly."""
        key = (name, ndim)
        sh = self._batch_sharding_cache.get(key)
        if sh is None:
            input_by_name = {op.name: op.outputs[0]
                             for op in self.model.ops
                             if isinstance(op, InputOp)}
            if name in input_by_name:
                sh = self.input_sharding(input_by_name[name])
            else:
                entries = [None] * ndim
                if "data" in self.mesh_shape and self.mesh_shape["data"] > 1:
                    entries[0] = "data"
                sh = NamedSharding(self.mesh, P(*entries))
            self._batch_sharding_cache[key] = sh
        return sh

    def reshard_params(self, host_tree):
        """Place a host (numpy) params tree onto THIS executor's mesh —
        the restore half of topology-free checkpoints: the saved arrays
        are placement-less bytes, so whatever mesh the restoring process
        compiled with (same, differently shaped, or a different device
        count entirely — the elastic path) determines the layout here,
        not the mesh that saved them."""
        return reshard_tree(host_tree, self.param_shardings())

    def shard_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, jnp.ndarray]:
        """Commit every batch entry to its cached NamedSharding. Entries
        that are ALREADY committed to the right sharding (a prefetched
        batch, or the device-resident loader's jitted slice) pass through
        untouched — the put is skipped, so calling this on a pre-sharded
        batch is a dict walk, not a transfer. Committed (not just
        correctly-placed) matters: an uncommitted array changes the warm
        step program's pjit signature and silently retraces it."""
        out = {}
        for k, v in batch.items():
            if not hasattr(v, "ndim"):  # plain list/scalar callers
                v = np.asarray(v)
            sh = self.batch_sharding(k, v.ndim)
            if (isinstance(v, jax.Array)
                    and getattr(v, "committed", False)
                    and v.sharding.is_equivalent_to(sh, v.ndim)):
                out[k] = v
            else:
                out[k] = jax.device_put(v, sh)
        return out


def reshard_tree(host_tree, shardings):
    """device_put a {op: {weight: array}} host tree leaf-by-leaf onto the
    given ``param_shardings()``-style placement map (leaves without an
    entry get default placement). Shared by GraphExecutor and
    PlacementExecutor so every restore path re-shards identically."""
    out = {}
    for op_name, ws in host_tree.items():
        per_op = shardings.get(op_name, {})
        out[op_name] = {
            name: jax.device_put(np.asarray(v), per_op.get(name))
            if per_op.get(name) is not None
            # ffsan: allow(uncommitted-device-put) — ops without a
            # recorded sharding deliberately take default placement
            # (restore-time, before any program is warm)
            else jax.device_put(np.asarray(v))
            for name, v in ws.items()}
    return out


def _with_fsdp(ps, shape, axis: str, axis_size: int):
    """FSDP post-process of a weight's PartitionSpec (FFConfig.fsdp_axis):
    shard its LARGEST still-unsharded, divisible dim over `axis` (on top
    of any strategy sharding, e.g. TP — 2D weight sharding). The training
    strategy stays activation-side; GSPMD inserts the all-gather at use
    and the gradient reduce-scatter, so param + optimizer-state HBM
    divide by the axis size — the ZeRO-3 design, spelled as shardings."""
    if shape is None or axis_size <= 1:
        return ps
    entries = list(ps) + [None] * (len(shape) - len(ps))
    used = set()
    for e in entries:
        for a in (e if isinstance(e, tuple) else (e,)):
            if a is not None:
                used.add(a)
    if axis in used:
        return ps  # strategy already spent this axis on the weight
    best = None
    for d, e in enumerate(entries):
        if e is None and shape[d] % axis_size == 0:
            if best is None or shape[d] > shape[best]:
                best = d
    if best is None:
        return ps  # nothing divisible: weight stays as the strategy left it
    entries[best] = axis
    return P(*entries)


def tie_transform(w, tf: str):
    """The single definition of tie transforms (FFModel.tie_weights);
    every params consumer (full-precision and quantized walks) resolves
    through here so a new transform cannot silently diverge."""
    return w.T if tf == "transpose" else w


def resolve_tied_params(model, params, op_name, p, leaf=None):
    """Materialize tied weights (FFModel.tie_weights) for `op_name` from
    their source op's storage. Runs inside the traced step, so autodiff
    accumulates both ops' gradients into the single source array. `leaf`
    optionally maps the raw stored leaf before the transform (the int8
    decode path dequantizes here)."""
    tied = getattr(model, "_tied", None)
    if not tied:
        return p
    out = None
    for (dst_op, dst_w), (src_op, src_w, tf) in tied.items():
        if dst_op != op_name:
            continue
        if out is None:
            out = dict(p)
        w = params[src_op][src_w]
        if leaf is not None:
            w = leaf(w)
        out[dst_w] = tie_transform(w, tf)
    return p if out is None else out


def _spec_rank_ok(spec, ndim) -> bool:
    return len(spec) <= ndim


def _stable_hash(s: str) -> int:
    h = 0
    for ch in s:
        h = (h * 31 + ord(ch)) % (2 ** 31)
    return h
