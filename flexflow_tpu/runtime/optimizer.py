"""Optimizers: SGD (momentum/nesterov/weight-decay) and Adam.

Reference: src/runtime/optimizer.cc:93-358 + optimizer_kernel.cu. The
reference maintains two sync backends per optimizer (parameter-server gather
and NCCL allreduce); on TPU gradients arrive already summed by the psum that
sharded autodiff inserts, so the update is a pure elementwise pytree map —
both backends collapse into one. Update formulas match the reference kernels:

  SGD  (optimizer_kernel.cu:23-95): g += wd*w; v = mom*v + g;
       g = nesterov ? g + mom*v : v; w -= lr*g
  Adam (optimizer_kernel.cu:188-293): m,v EMA; alpha_t = alpha *
       sqrt(1-beta2^t)/(1-beta1^t)  (optimizer.cc:248-254 next())
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


class Optimizer:
    def init_state(self, params) -> Dict[str, Any]:
        raise NotImplementedError

    def update(self, params, grads, state):
        """Returns (new_params, new_state). Pure; called inside jit."""
        raise NotImplementedError


def _f32_view(*arrays):
    """Upcast update operands to f32: with bf16 master weights
    (FFConfig.master_dtype) storage halves but update MATH stays f32 —
    the casts trace away entirely for f32 storage."""
    return tuple(None if a is None else a.astype(jnp.float32)
                 for a in arrays)


class FusedUpdate(Optimizer):
    """Single-fusion optimizer update over flattened parameter buckets
    (FFConfig.fused_optimizer; VERDICT r3 #4 MFU lever for d=64-class
    models with many leaves).

    The per-leaf tree_map update emits one elementwise loop per weight —
    ~100 kernel launches of mostly-tiny arrays on a transformer. Here all
    leaves of one storage dtype flatten into ONE vector inside the jitted
    step: XLA fuses the concatenate into the elementwise read and the
    splits into the write, so the whole update compiles to one fused loop
    per dtype bucket; optimizer STATE is stored genuinely flat across
    steps (init_state sees the flat pytree), so it pays no reshaping at
    all. Values are bit-identical to the unfused update (same elementwise
    formula, concat changes no values) — tested.

    Only valid when every parameter is replicated (single device, or pure
    DP): flattening GSPMD-sharded leaves would force all-gathers. The
    compile path checks this and falls back to the inner optimizer.
    NOTE: the optimizer-state pytree shape differs from the unfused
    layout, so checkpoints written with fused_optimizer on must be
    restored with it on (and vice versa)."""

    def __init__(self, inner: Optimizer):
        self.inner = inner

    # schedule etc. proxied for code that introspects the optimizer
    def __getattr__(self, name):
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    @staticmethod
    def _flatten(tree):
        """pytree -> ({dtype_name: 1-D vector}, spec) where spec rebuilds
        the original tree. Bucket membership/order follows the flatten
        order, which is stable for a fixed tree structure."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        order = {}
        for i, leaf in enumerate(leaves):
            order.setdefault(jnp.dtype(leaf.dtype).name, []).append(i)
        flat = {dt: (jnp.concatenate([leaves[i].ravel() for i in idxs])
                     if len(idxs) > 1 else leaves[idxs[0]].ravel())
                for dt, idxs in order.items()}
        spec = (treedef, [(jnp.dtype(l.dtype).name, l.shape, l.size)
                          for l in leaves])
        return flat, spec

    @staticmethod
    def _unflatten(flat, spec):
        treedef, leaf_info = spec
        cursors = {dt: 0 for dt in flat}
        leaves = []
        for dt, shape, size in leaf_info:
            c = cursors[dt]
            leaves.append(flat[dt][c:c + size].reshape(shape))
            cursors[dt] = c + size
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def init_state(self, params):
        flat, _ = self._flatten(params)
        return self.inner.init_state(flat)

    def update(self, params, grads, state):
        fp, spec = self._flatten(params)
        fg, _ = self._flatten(grads)
        nfp, nstate = self.inner.update(fp, fg, state)
        return self._unflatten(nfp, spec), nstate


class SGDOptimizer(Optimizer):
    def __init__(self, model=None, lr: float = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, weight_decay: float = 0.0,
                 schedule=None):
        from flexflow_tpu.runtime.schedule import resolve

        self.lr = lr
        self.momentum = momentum
        self.nesterov = nesterov
        self.weight_decay = weight_decay
        # lr schedule (runtime/schedule.py): pure fn of the traced step,
        # compiled into the jitted update. None = constant (reference
        # behavior, optimizer.cc fixed-lr kernels).
        self.schedule = resolve(schedule)

    def init_state(self, params):
        if self.momentum > 0.0:
            v = jax.tree_util.tree_map(jnp.zeros_like, params)
        else:
            v = None
        return {"v": v, "t": jnp.zeros((), jnp.int32)}

    def update(self, params, grads, state):
        mom, wd = self.momentum, self.weight_decay
        lr = self.lr * self.schedule(state["t"])

        if mom > 0.0:
            def upd(w, g, v):
                wt, vt = w.dtype, v.dtype
                w, g, v = _f32_view(w, g, v)
                g = g + wd * w
                v = mom * v + g
                step = g + mom * v if self.nesterov else v
                return (w - lr * step).astype(wt), v.astype(vt)

            flat = jax.tree_util.tree_map(upd, params, grads, state["v"])
            new_params = jax.tree_util.tree_map(lambda t: t[0], flat,
                                                is_leaf=lambda t: isinstance(t, tuple))
            new_v = jax.tree_util.tree_map(lambda t: t[1], flat,
                                           is_leaf=lambda t: isinstance(t, tuple))
            return new_params, {"v": new_v, "t": state["t"] + 1}

        def upd_plain(w, g):
            wt = w.dtype
            w, g = _f32_view(w, g)
            return (w - lr * (g + wd * w)).astype(wt)

        new_params = jax.tree_util.tree_map(upd_plain, params, grads)
        return new_params, {"v": None, "t": state["t"] + 1}


class AdamOptimizer(Optimizer):
    def __init__(self, model=None, alpha: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, weight_decay: float = 0.0,
                 epsilon: float = 1e-8, schedule=None):
        from flexflow_tpu.runtime.schedule import resolve

        self.alpha = alpha
        self.beta1 = beta1
        self.beta2 = beta2
        self.weight_decay = weight_decay
        self.epsilon = epsilon
        self.schedule = resolve(schedule)

    def init_state(self, params):
        zeros = lambda p: jax.tree_util.tree_map(jnp.zeros_like, p)
        return {"m": zeros(params), "v": zeros(params),
                "t": jnp.zeros((), jnp.int32)}

    def update(self, params, grads, state):
        b1, b2, wd, eps = self.beta1, self.beta2, self.weight_decay, self.epsilon
        t = state["t"] + 1
        # bias-corrected step size, as the reference's AdamOptimizer::next()
        alpha_t = self.alpha * self.schedule(state["t"]) \
            * jnp.sqrt(1.0 - jnp.power(b2, t)) / (1.0 - jnp.power(b1, t))

        def upd(w, g, m, v):
            wt, mt, vt = w.dtype, m.dtype, v.dtype
            w, g, m, v = _f32_view(w, g, m, v)
            g = g + wd * w
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            w = w - alpha_t * m / (jnp.sqrt(v) + eps)
            return w.astype(wt), m.astype(mt), v.astype(vt)

        flat = jax.tree_util.tree_map(upd, params, grads, state["m"], state["v"])
        is_triple = lambda t_: isinstance(t_, tuple)
        new_params = jax.tree_util.tree_map(lambda x: x[0], flat, is_leaf=is_triple)
        new_m = jax.tree_util.tree_map(lambda x: x[1], flat, is_leaf=is_triple)
        new_v = jax.tree_util.tree_map(lambda x: x[2], flat, is_leaf=is_triple)
        return new_params, {"m": new_m, "v": new_v, "t": t}
