"""Optimizers: SGD (momentum/nesterov/weight-decay) and Adam.

Reference: src/runtime/optimizer.cc:93-358 + optimizer_kernel.cu. The
reference maintains two sync backends per optimizer (parameter-server gather
and NCCL allreduce); on TPU gradients arrive already summed by the psum that
sharded autodiff inserts, so the update is a pure elementwise pytree map —
both backends collapse into one. Update formulas match the reference kernels:

  SGD  (optimizer_kernel.cu:23-95): g += wd*w; v = mom*v + g;
       g = nesterov ? g + mom*v : v; w -= lr*g
  Adam (optimizer_kernel.cu:188-293): m,v EMA; alpha_t = alpha *
       sqrt(1-beta2^t)/(1-beta1^t)  (optimizer.cc:248-254 next())
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


class Optimizer:
    def init_state(self, params) -> Dict[str, Any]:
        raise NotImplementedError

    def update(self, params, grads, state):
        """Returns (new_params, new_state). Pure; called inside jit."""
        raise NotImplementedError


def _f32_view(*arrays):
    """Upcast update operands to f32: with bf16 master weights
    (FFConfig.master_dtype) storage halves but update MATH stays f32 —
    the casts trace away entirely for f32 storage."""
    return tuple(None if a is None else a.astype(jnp.float32)
                 for a in arrays)


class FusedUpdate(Optimizer):
    """Single-fusion optimizer update over flattened parameter buckets
    (FFConfig.fused_optimizer; VERDICT r3 #4 MFU lever for d=64-class
    models with many leaves).

    The per-leaf tree_map update emits one elementwise loop per weight —
    ~100 kernel launches of mostly-tiny arrays on a transformer. Here all
    leaves of one storage dtype flatten into ONE vector inside the jitted
    step: XLA fuses the concatenate into the elementwise read and the
    splits into the write, so the whole update compiles to one fused loop
    per dtype bucket; optimizer STATE is stored genuinely flat across
    steps (init_state sees the flat pytree), so it pays no reshaping at
    all. Values are bit-identical to the unfused update (same elementwise
    formula, concat changes no values) — tested.

    Only valid when every parameter is replicated (single device, or pure
    DP): flattening GSPMD-sharded leaves in the global view would force
    all-gathers — sharded strategies use ShardedFusedUpdate instead,
    which flattens per-shard inside a shard_map.
    NOTE: the optimizer-state pytree shape differs from the unfused
    layout, so checkpoints written with fused_optimizer on must be
    restored with it on (and vice versa); checkpoint.py records the
    layout in meta.json and refuses a mismatched restore."""

    def __init__(self, inner: Optimizer):
        self.inner = inner

    # schedule etc. proxied for code that introspects the optimizer
    def __getattr__(self, name):
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    @staticmethod
    def _flatten(tree):
        """pytree -> ({dtype_name: 1-D vector}, spec) where spec rebuilds
        the original tree. Bucket membership/order follows the flatten
        order, which is stable for a fixed tree structure."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        order = {}
        for i, leaf in enumerate(leaves):
            order.setdefault(jnp.dtype(leaf.dtype).name, []).append(i)
        flat = {dt: (jnp.concatenate([leaves[i].ravel() for i in idxs])
                     if len(idxs) > 1 else leaves[idxs[0]].ravel())
                for dt, idxs in order.items()}
        spec = (treedef, [(jnp.dtype(l.dtype).name, l.shape, l.size)
                          for l in leaves])
        return flat, spec

    @staticmethod
    def _unflatten(flat, spec):
        treedef, leaf_info = spec
        cursors = {dt: 0 for dt in flat}
        leaves = []
        for dt, shape, size in leaf_info:
            c = cursors[dt]
            leaves.append(flat[dt][c:c + size].reshape(shape))
            cursors[dt] = c + size
        return jax.tree_util.tree_unflatten(treedef, leaves)

    @staticmethod
    def _flatten_grads(params, grads):
        """Flatten grads into the SAME buckets/order as the params (keyed
        by the PARAM leaf dtype): a grad leaf whose dtype differs from its
        param's must not land in a different bucket (silent misalignment —
        worst case wrong pairings). Mismatched grads are upcast to f32 —
        exact for bf16->f32, and a full-precision f32 grad for a bf16
        master param is NOT rounded through bf16, so the math matches the
        per-leaf path bit-for-bit (its _f32_view sees the same values)."""
        p_leaves, _ = jax.tree_util.tree_flatten(params)
        g_leaves, _ = jax.tree_util.tree_flatten(grads)
        order = {}
        for i, p in enumerate(p_leaves):
            order.setdefault(jnp.dtype(p.dtype).name, []).append(i)
        vec = [g.ravel() if g.dtype == p.dtype
               else g.ravel().astype(jnp.float32)
               for p, g in zip(p_leaves, g_leaves)]
        return {dt: (jnp.concatenate([vec[i] for i in idxs])
                     if len(idxs) > 1 else vec[idxs[0]])
                for dt, idxs in order.items()}

    def init_state(self, params):
        flat, _ = self._flatten(params)
        return self.inner.init_state(flat)

    def update(self, params, grads, state):
        fp, spec = self._flatten(params)
        fg = self._flatten_grads(params, grads)
        nfp, nstate = self.inner.update(fp, fg, state)
        return self._unflatten(nfp, spec), nstate


class ShardedFusedUpdate(Optimizer):
    """Fused optimizer update for GSPMD-sharded parameter trees (TP /
    FSDP) — VERDICT r4 #3: the fused lever must not no-op exactly where
    it matters (large sharded models).

    The whole update runs inside a `shard_map` over the full mesh with
    each param/grad leaf mapped by its own PartitionSpec: the body sees
    LOCAL shard blocks as plain arrays, flattens them into one vector
    per dtype bucket, and applies the inner elementwise update — so the
    fusion is shard-local by construction and the step inserts ZERO
    collectives (gradients arrive already reduced, exactly as in the
    per-leaf path). Replicated leaves pass through with spec P() and
    every device updates its identical copy — replicas stay bit-synced
    because the update is deterministic.

    Optimizer STATE is stored genuinely flat ACROSS the mesh: one 1-D
    vector per dtype bucket, sharded over all mesh axes on dim 0, so
    each device persists exactly its local bucket (same per-device HBM
    as the per-leaf state under the same shardings). The layout is a
    pure function of (tree structure, leaf shardings, mesh), so a
    checkpoint restores onto the same strategy; checkpoint.py records
    the layout kind and refuses a mismatched restore.

    Values are bit-identical to the per-leaf update: same elementwise
    formula, and neither the local concat nor the sharding changes any
    operand value (tests/test_mfu_levers.py)."""

    def __init__(self, inner: Optimizer, mesh, specs):
        """specs: pytree matching params, of jax PartitionSpec (P() for
        replicated leaves); mesh: the jax.sharding.Mesh the train step
        compiles over."""
        self.inner = inner
        self.mesh = mesh
        self.specs = specs

    def __getattr__(self, name):
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    def _flat_spec(self):
        from jax.sharding import PartitionSpec as P

        return P(tuple(self.mesh.axis_names))

    def _state_specs(self, state):
        from jax.sharding import PartitionSpec as P

        flat = self._flat_spec()
        return jax.tree_util.tree_map(
            lambda a: P() if jnp.ndim(a) == 0 else flat, state)

    @staticmethod
    def local_leaf_size(shape, spec, mesh) -> int:
        """Per-device element count of a leaf sharded by `spec`."""
        size = 1
        for i, d in enumerate(shape):
            names = spec[i] if i < len(spec) else None
            if names is None:
                size *= d
                continue
            if isinstance(names, str):
                names = (names,)
            k = 1
            for n in names:
                k *= mesh.shape[n]
            if d % k:
                raise ValueError(
                    f"leaf dim {d} not divisible by mesh extent {k} "
                    f"for spec {spec}")
            size *= d // k
        return size

    def init_state(self, params):
        """Build the flat sharded state eagerly: zeros vectors of
        global size (local bucket size x n_devices), committed to the
        all-axes sharding so the jitted step keeps the layout."""
        from jax.sharding import NamedSharding

        leaves, _ = jax.tree_util.tree_flatten(params)
        spec_leaves, _ = jax.tree_util.tree_flatten(
            self.specs, is_leaf=lambda x: x is None or not isinstance(x, dict))
        buckets = {}
        for leaf, spec in zip(leaves, spec_leaves):
            dt = jnp.dtype(leaf.dtype).name
            buckets[dt] = buckets.get(dt, 0) + self.local_leaf_size(
                leaf.shape, spec, self.mesh)
        n = self.mesh.devices.size
        sh = NamedSharding(self.mesh, self._flat_spec())
        flat = {dt: jax.device_put(jnp.zeros(local * n,
                                             dtype=jnp.dtype(dt)), sh)
                for dt, local in buckets.items()}
        return self.inner.init_state(flat)

    def update(self, params, grads, state):
        from flexflow_tpu.parallel import shard_map_compat

        pspecs = self.specs
        sspecs = self._state_specs(state)

        def body(p_local, g_local, s_local):
            fp, spec = FusedUpdate._flatten(p_local)
            fg = FusedUpdate._flatten_grads(p_local, g_local)
            nfp, nstate = self.inner.update(fp, fg, s_local)
            return FusedUpdate._unflatten(nfp, spec), nstate

        return shard_map_compat(body, self.mesh,
                                in_specs=(pspecs, pspecs, sspecs),
                                out_specs=(pspecs, sspecs)
                                )(params, grads, state)


def apply_tree_shardings(tree, shardings, fn, default=None):
    """Walk a ``{op: {weight: leaf}}`` tree alongside a (possibly partial)
    matching dict of NamedShardings and apply ``fn(leaf, sharding)`` where
    a sharding entry exists; leaves without one (tied weights, scalars
    like the optimizer's step counter) get ``fn(leaf, default)`` when a
    ``default`` sharding is given, else pass through untouched. ``fn`` is
    ``jax.device_put`` for eager placement or
    ``jax.lax.with_sharding_constraint`` inside a traced program — the
    shared walk behind the ZeRO-1 layout (executor.grad_scatter_shardings
    consumers)."""
    def walk(sub, sh):
        if sub is None:
            return None
        if isinstance(sub, dict):
            return {k: walk(v, sh.get(k) if isinstance(sh, dict) else None)
                    for k, v in sub.items()}
        if sh is None or isinstance(sh, dict):
            return sub if default is None else fn(sub, default)
        return fn(sub, sh)

    return walk(tree, shardings)


class Zero1Update(Optimizer):
    """ZeRO-1 sharded optimizer update (FFConfig.overlap_grad_sync) — the
    epilogue half of in-graph grad-sync overlap.

    Wraps any per-leaf optimizer with two sharding layouts: ``scatter``
    (executor.grad_scatter_shardings — each weight's strategy(+FSDP)
    sharding with its largest still-unsharded divisible dim additionally
    split over the DATA axis) and ``gather`` (the model's normal param
    shardings). ``update`` constrains grads AND params to the scatter
    layout, runs the inner elementwise update on the 1/N-sized shards,
    and constrains the new params back: GSPMD lowers the grad constraint
    to a reduce-scatter (or a no-op when the accumulation scan already
    delivered scattered buckets) and the return constraint to ONE
    all-gather per weight — instead of every data replica redundantly
    updating the full parameter after a full all-reduce. Optimizer STATE
    is initialized (and therefore persisted across steps) in the scatter
    layout, so its HBM divides by the data degree.

    Values are bit-for-bit the per-leaf update's: sharding constraints
    change placement, never operands. The state PYTREE structure is
    unchanged too, so checkpoints restore across overlap_grad_sync
    on/off (restore re-initializes state and re-places the saved values
    leaf by leaf)."""

    def __init__(self, inner: Optimizer, scatter, gather):
        self.inner = inner
        self.scatter = scatter  # {op: {weight: NamedSharding}} — ZeRO-1
        self.gather = gather    # {op: {weight: NamedSharding}} — params

    def __getattr__(self, name):
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    def init_state(self, params):
        import jax as _jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = next(ns.mesh for per in self.scatter.values()
                    for ns in per.values())
        # leaves without a scatter entry (the step counter, momentum=None)
        # commit REPLICATED on the same mesh: a multihost jit refuses a
        # mix of global-committed moments and a single-device scalar
        rep = NamedSharding(mesh, P())
        state = self.inner.init_state(params)
        return {k: apply_tree_shardings(v, self.scatter, _jax.device_put,
                                        default=rep)
                for k, v in state.items()}

    def update(self, params, grads, state):
        wsc = jax.lax.with_sharding_constraint
        p = apply_tree_shardings(params, self.scatter, wsc)
        g = apply_tree_shardings(grads, self.scatter, wsc)
        s = {k: apply_tree_shardings(v, self.scatter, wsc)
             for k, v in state.items()}
        new_p, new_s = self.inner.update(p, g, s)
        new_p = apply_tree_shardings(new_p, self.gather, wsc)
        new_s = {k: apply_tree_shardings(v, self.scatter, wsc)
                 for k, v in new_s.items()}
        return new_p, new_s


class SGDOptimizer(Optimizer):
    def __init__(self, model=None, lr: float = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, weight_decay: float = 0.0,
                 schedule=None):
        from flexflow_tpu.runtime.schedule import resolve

        self.lr = lr
        self.momentum = momentum
        self.nesterov = nesterov
        self.weight_decay = weight_decay
        # lr schedule (runtime/schedule.py): pure fn of the traced step,
        # compiled into the jitted update. None = constant (reference
        # behavior, optimizer.cc fixed-lr kernels).
        self.schedule = resolve(schedule)

    def init_state(self, params):
        if self.momentum > 0.0:
            v = jax.tree_util.tree_map(jnp.zeros_like, params)
        else:
            v = None
        return {"v": v, "t": jnp.zeros((), jnp.int32)}

    def update(self, params, grads, state):
        mom, wd = self.momentum, self.weight_decay
        lr = self.lr * self.schedule(state["t"])

        if mom > 0.0:
            def upd(w, g, v):
                wt, vt = w.dtype, v.dtype
                w, g, v = _f32_view(w, g, v)
                g = g + wd * w
                v = mom * v + g
                step = g + mom * v if self.nesterov else v
                return (w - lr * step).astype(wt), v.astype(vt)

            flat = jax.tree_util.tree_map(upd, params, grads, state["v"])
            new_params = jax.tree_util.tree_map(lambda t: t[0], flat,
                                                is_leaf=lambda t: isinstance(t, tuple))
            new_v = jax.tree_util.tree_map(lambda t: t[1], flat,
                                           is_leaf=lambda t: isinstance(t, tuple))
            return new_params, {"v": new_v, "t": state["t"] + 1}

        def upd_plain(w, g):
            wt = w.dtype
            w, g = _f32_view(w, g)
            return (w - lr * (g + wd * w)).astype(wt)

        new_params = jax.tree_util.tree_map(upd_plain, params, grads)
        return new_params, {"v": None, "t": state["t"] + 1}


class AdamOptimizer(Optimizer):
    def __init__(self, model=None, alpha: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, weight_decay: float = 0.0,
                 epsilon: float = 1e-8, schedule=None):
        from flexflow_tpu.runtime.schedule import resolve

        self.alpha = alpha
        self.beta1 = beta1
        self.beta2 = beta2
        self.weight_decay = weight_decay
        self.epsilon = epsilon
        self.schedule = resolve(schedule)

    def init_state(self, params):
        zeros = lambda p: jax.tree_util.tree_map(jnp.zeros_like, p)
        return {"m": zeros(params), "v": zeros(params),
                "t": jnp.zeros((), jnp.int32)}

    def update(self, params, grads, state):
        b1, b2, wd, eps = self.beta1, self.beta2, self.weight_decay, self.epsilon
        t = state["t"] + 1
        # bias-corrected step size, as the reference's AdamOptimizer::next()
        alpha_t = self.alpha * self.schedule(state["t"]) \
            * jnp.sqrt(1.0 - jnp.power(b2, t)) / (1.0 - jnp.power(b1, t))

        def upd(w, g, m, v):
            wt, mt, vt = w.dtype, m.dtype, v.dtype
            w, g, m, v = _f32_view(w, g, m, v)
            g = g + wd * w
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            w = w - alpha_t * m / (jnp.sqrt(v) + eps)
            return w.astype(wt), m.astype(mt), v.astype(vt)

        flat = jax.tree_util.tree_map(upd, params, grads, state["m"], state["v"])
        is_triple = lambda t_: isinstance(t_, tuple)
        new_params = jax.tree_util.tree_map(lambda x: x[0], flat, is_leaf=is_triple)
        new_m = jax.tree_util.tree_map(lambda x: x[1], flat, is_leaf=is_triple)
        new_v = jax.tree_util.tree_map(lambda x: x[2], flat, is_leaf=is_triple)
        return new_params, {"m": new_m, "v": new_v, "t": t}
