"""Continuous-batching serving runtime: slot decode over a paged KV cache.

The reference's only inference story is the training graph run forward-only
(CompMode::COMP_MODE_INFERENCE); runtime/generation.py added the modern
one-program KV-cache decode, but as a FIXED batch: finished rows burn full
decode steps emitting pads, a new request cannot start until the whole
batch retires, and every (prompt shape, max_new_tokens) pair compiles its
own program. This module is the serving-side performance subsystem on top
of it:

  * ONE jitted slot-decode step of fixed shape ``(serve_slots, 1)`` runs
    for the life of the engine — the compiled program never changes shape,
    the HOST scheduler moves work in and out of slots (the partition-
    don't-pad philosophy applied to serving: keep XLA static, move the
    raggedness to the host).
  * The KV cache is a POOL of ``(kv_pages, kv_page_size, KVH, Dh)`` blocks
    with a per-slot page table (ops/attention.py paged_decode_forward):
    long and short requests share HBM instead of every slot preallocating
    ``max_seq_len``. Pages are allocated at admission and freed at
    retirement; page 0 is a scratch page inactive slots harmlessly write.
  * Admission prefills the prompt into the slot's pages through the
    EXISTING prefill path (Generator._prefill, chunked via chunk_forward
    when ``prefill_chunk`` is set) on a contiguous per-request cache, then
    scatters that k/v into the pool — prefill numerics are therefore
    identical to batch generate's, and greedy continuous batching is
    token-identical to per-request Generator.generate
    (tests/test_serving.py).
  * Prompt lengths are rounded up to SHAPE BUCKETS (powers of two by
    default, ``decode_buckets`` to pin explicit boundaries) so warm
    prefill programs are reused across mixed lengths; ``recompile_count``
    exposes every program build, and after bucket warmup it stays flat.
  * Every compiled program returns a per-slot finiteness flag computed
    in-graph; a request whose logits go non-finite (e.g. FF_FAULT
    ``nan_loss@serve:<n>`` poisons the n-th admitted request) is retired
    as ``failed`` without stalling the other slots — serving inherits the
    fault-injection story of runtime/faultinject.py.
  * ``drain()``/``health()``: graceful shutdown for deploys and elastic
    topology changes (docs/resilience.md) — stop admitting, finish the
    in-flight slots, final stats snapshot; queued-but-unadmitted requests
    stay queued for re-submission to the replacement engine.
  * FLEET-READY: one engine lock serializes every queue/slot/counter
    mutation so a router (runtime/router.py ServingRouter) can drive
    each replica from its own thread while other threads submit and
    probe; ``submit(..., deadline=)`` retires requests that expire while
    queued as ``"timeout"`` without ever prefilling; ``load()`` is the
    lock-free dispatch signal.
  * RADIX PREFIX CACHE (RadixPrefixCache): a trie over page-aligned
    prompt token chunks maps each full KV page a finished prefill
    produced to its pool page id, with a per-page refcount of the live
    requests referencing it. Admission looks up the longest cached
    page-aligned prefix, bumps refcounts, and prefills ONLY the tail —
    page writes are copy-on-write: a shared page is never written in
    place (the tail, including the recompute of the matched prefix's
    partial last page, scatters into fresh pages; decode appends land
    past the prompt bucket, also in the request's own pages).
    Retirement decrefs; refcount-0 pages stay cached for future hits
    until an LRU evictor reclaims them under pool pressure. Identical
    prompts across millions of requests then share prefill compute AND
    the HBM pages it produced (ROADMAP item 1).
  * SPECULATIVE DECODING (``draft_model`` + ``speculate_k``): a small
    draft model proposes K greedy tokens per slot from its own paged
    pool (same page ids — the prefix cache shares draft pages too), and
    ONE fixed-shape verify program scores all K+1 positions against the
    target in a single dispatch
    (MultiHeadAttention.paged_verify_forward). The host accepts the
    longest prefix of proposals matching the target's greedy argmax and
    emits accepted + 1 tokens — every emitted token is the TARGET's
    greedy token, so the stream is token-identical to non-speculative
    greedy decode; the accept rate rides ``stats()``.

  * QUANTIZED SERVING TIER (``FFConfig.kv_cache_dtype`` /
    ``serve_weight_dtype``, ISSUE 11): the paged pool stores int8/fp8
    payload with per-(page, kv-head) f32 scales alongside, so each page
    holds 2-4x more tokens per HBM byte — prefix-cache capacity and
    slots-per-chip multiply at fixed pool bytes while the allocator,
    COW rule, radix trie, router affinity and speculation (all
    page-granular) are untouched. Dequantization happens in VMEM:
    inside the Pallas paged-attention kernel against scalar-prefetched
    scales, or fused into the einsum gather (the parity oracle) — wide
    KV never materializes in HBM. Serving weights quantize ONCE at
    engine init (per-output-channel scales) and dequantize fused into
    each consuming matmul. Quantization is lossy: greedy streams carry
    a documented per-dtype divergence budget vs the full-width path
    (docs/serving.md "Quantized tier"); pallas-vs-einsum token identity
    and pool bitwise equality still hold exactly.

Per-slot cache layout (identical to the ragged rule of
MultiHeadAttention.decode_forward, with a per-slot prompt pad width):
logical positions ``[0, row_len)`` hold the true prompt, ``[row_len,
prompt_pad)`` hold masked bucket-pad garbage, decode tokens append from
``prompt_pad``; RoPE positions stay LOGICAL (``row_len + emitted``).
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu._env import compilation_cache_entries
from flexflow_tpu.logger import fflogger
from flexflow_tpu.runtime import faultinject
from flexflow_tpu.runtime.generation import Generator


def _ktune_stats():
    from flexflow_tpu.search import kernel_tune

    return kernel_tune.stats()


@dataclass
class Request:
    """One serving request and its full lifecycle record."""

    rid: int
    prompt: np.ndarray              # (S,) int32, true (unpadded) prompt
    max_new_tokens: int
    state: str = "queued"       # queued | running | done | failed | timeout
    # absolute time.perf_counter() deadline (None = none): a request that
    # expires while QUEUED retires as "timeout" without ever prefilling
    # (no pages, no dispatch); an already-admitted request is never
    # cancelled mid-batch — cancellation would disturb the fixed-shape
    # slot program — its late completion is the caller's to discard
    deadline: Optional[float] = None
    tokens: List[int] = field(default_factory=list)  # emitted tokens
    slot: int = -1
    bucket: int = 0
    pages: List[int] = field(default_factory=list)   # full logical table
    # prefix-cache bookkeeping: trie nodes whose refcount this request
    # holds (shared prefix pages + pages it published), and the pages it
    # owns outright (freed at retirement; trie pages are only decref'd)
    trie_nodes: List = field(default_factory=list)
    private_pages: List[int] = field(default_factory=list)
    prefix_tokens: int = 0          # prefill positions served from cache
    t_submit: float = 0.0
    ttft: float = 0.0               # submit -> first emitted token (s)
    t_done: float = 0.0
    error: str = ""

    @property
    def output(self) -> np.ndarray:
        """prompt + emitted tokens, the shape generate() would return
        for this request alone (minus trailing pads it never emitted)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])


def _pow2_bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class _TrieNode:
    """One cached KV page: the page_size-token chunk it encodes (its edge
    label from the parent), the pool page id holding its k/v, and the
    refcount of live requests whose page tables reference it."""

    __slots__ = ("chunk", "page", "parent", "children", "ref", "last_use")

    def __init__(self, chunk, page, parent):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children = {}
        self.ref = 0
        self.last_use = 0


class RadixPrefixCache:
    """Radix/trie index over prompt token prefixes at PAGE granularity.

    Each trie edge is exactly ``page_size`` tokens, so a path of depth d
    names a d-page prompt prefix and maps it to the d pool pages holding
    its KV — the page, not the token, is the unit of sharing because the
    pool scatters, gathers and refcounts pages. A page's KV at position j
    depends only on tokens [0..j] (causal attention), so any request
    whose prompt starts with the same ``d * page_size`` tokens can mount
    those pages read-only and prefill just its tail.

    Ownership protocol (the copy-on-write rule lives HERE, not in the
    kernels): a page in the trie is never written again — its producer
    published it only after prefill, and every borrower's tail/decode
    writes land in freshly allocated pages past the matched prefix.
    ``ref`` counts live requests mounting the page; retirement decrefs.
    A refcount-0 page stays cached (warm for the next hit) until
    ``evict()`` reclaims it under pool pressure, LRU-first and leaves
    only — an interior page must outlive its children, since a match
    walks through it. All host-side, O(prompt/page_size) per lookup;
    ``evict()`` walks the whole trie per pressure call, which is fine at
    the pool sizes this engine runs (hundreds of pages) — a
    persistently-maintained ref-0-leaf LRU makes reclaim O(need) if
    pool sizes grow by orders of magnitude."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self.root = _TrieNode(None, -1, None)
        self.pages = 0          # page-holding nodes currently cached
        self.lookups = 0
        self.hits = 0
        self.tokens_saved = 0   # prefill positions served from cache
        self.evictions = 0      # PRESSURE evictions only (flushes don't
        #                         count — they are not a pool signal)
        self._tick = 0          # monotonic LRU clock (bumped per lookup)
        # incremental mirrors of the trie's refcount state, so stats()
        # and the per-tick health() probe never walk the trie
        self._live_refs = 0     # sum of node.ref
        self._shared = 0        # nodes with ref > 1 right now

    def _chunk(self, prompt, i: int):
        ps = self.page_size
        return tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])

    def match(self, prompt, max_pages: int) -> List[_TrieNode]:
        """Longest cached page-aligned prefix of ``prompt``, capped at
        ``max_pages``; returns the node path root-down (possibly empty).
        Does NOT take references or bump hit statistics — the caller
        commits with acquire()/note_admitted() only once admission is
        certain (a request that stays queued on pool pressure re-matches
        every tick and must leave refcounts AND counters untouched)."""
        self._tick += 1
        node, path = self.root, []
        limit = min(int(max_pages), len(prompt) // self.page_size)
        for i in range(limit):
            child = node.children.get(self._chunk(prompt, i))
            if child is None:
                break
            path.append(child)
            node = child
        for n in path:
            n.last_use = self._tick
        return path

    def note_admitted(self, matched_pages: int):
        """Commit one admission's lookup to the hit statistics — called
        exactly once per ADMITTED request, never for retried matches."""
        self.lookups += 1
        if matched_pages:
            self.hits += 1
            self.tokens_saved += matched_pages * self.page_size

    def acquire(self, nodes):
        for n in nodes:
            n.ref += 1
            self._live_refs += 1
            if n.ref == 2:
                self._shared += 1

    def release(self, nodes):
        for n in nodes:
            n.ref -= 1
            self._live_refs -= 1
            if n.ref == 1:
                self._shared -= 1
            if n.ref < 0:  # accounting bug, not a recoverable state
                raise AssertionError(
                    f"prefix-cache refcount underflow on page {n.page}")

    def insert(self, prompt, matched, start: int,
               pages: List[int]) -> List[_TrieNode]:
        """Publish a finished prefill's full-prompt pages: ``pages[j]``
        holds chunk ``start + j`` of ``prompt``, appended under the
        ``matched`` path. Each created node starts at ref 1 (the
        publishing request still mounts it). Stops at the first chunk
        that already exists — the caller's duplicate page for it stays
        private (only possible when the match was capped below an
        existing deeper path)."""
        node = matched[-1] if matched else self.root
        created = []
        for j, page in enumerate(pages):
            chunk = self._chunk(prompt, start + j)
            if chunk in node.children:
                break
            child = _TrieNode(chunk, page, node)
            child.ref = 1
            self._live_refs += 1
            child.last_use = self._tick
            node.children[chunk] = child
            node = child
            created.append(child)
            self.pages += 1
        return created

    def _iter_nodes(self):
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    def evict(self, need: int, protect=(), pressure: bool = True) \
            -> List[int]:
        """Reclaim up to ``need`` pages from refcount-0 LEAVES, oldest
        last_use first; returns the freed page ids. ``protect`` excludes
        a just-matched path the caller is about to acquire. Evicting a
        leaf may expose its parent — the sweep cascades.
        ``pressure=False`` (hot-swap flush, leak accounting) keeps the
        reclaim out of the ``evictions`` pool-pressure signal."""
        import heapq

        keep = set(id(n) for n in protect)

        def evictable(n):
            return not n.children and n.ref == 0 and id(n) not in keep

        heap = [(n.last_use, id(n), n) for n in self._iter_nodes()
                if evictable(n)]
        heapq.heapify(heap)
        freed: List[int] = []
        while heap and len(freed) < need:
            _, _, n = heapq.heappop(heap)
            del n.parent.children[n.chunk]
            freed.append(n.page)
            self.pages -= 1
            if pressure:
                self.evictions += 1
            parent = n.parent
            if parent is not self.root and evictable(parent):
                heapq.heappush(heap, (parent.last_use, id(parent), parent))
        return freed

    def live_refs(self) -> int:
        return self._live_refs

    def shared_pages(self) -> int:
        """Pages mounted by more than one live request right now."""
        return self._shared


class ServingEngine:
    """Continuous-batching engine over a compiled FFModel decoder LM.

    Build once (after model.compile()); ``submit()`` requests and drive
    ``step()`` yourself, or hand ``run()`` a list of prompts. Construction
    knobs default to the model's FFConfig (serve_slots, kv_page_size,
    kv_pages, decode_buckets)."""

    def __init__(self, model, serve_slots: Optional[int] = None,
                 kv_page_size: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 decode_buckets: Optional[List[int]] = None,
                 max_seq_len: int = 1024, temperature: float = 0.0,
                 top_k: int = 0, eos_id: Optional[int] = None,
                 pad_id: int = 0, prefill_chunk: int = 0,
                 decode_chunk: int = 8,
                 quantize: Optional[str] = None, seed: int = 0,
                 prefix_cache: Optional[bool] = None,
                 draft_model=None, speculate_k: Optional[int] = None,
                 paged_attention_impl: Optional[str] = None,
                 kv_cache_dtype: Optional[str] = None,
                 weight_dtype: Optional[str] = None):
        cfg = model.config
        self.model = model
        self.slots = int(serve_slots or getattr(cfg, "serve_slots", 4))
        # decode steps per device dispatch (an in-graph lax.scan): host
        # round-trips amortize over the chunk — the per-token dispatch of
        # chunk=1 dominates small-model decode. Retirement granularity
        # coarsens to the chunk; tokens a slot computes past its own
        # eos/length are truncated by the host, so outputs are identical
        # at any chunk (tests/test_serving.py). Waste is bounded by
        # chunk-1 steps per retirement, idle-slot time by chunk-1 per
        # admission — keep it well under typical max_new_tokens.
        self.decode_chunk = max(1, int(decode_chunk))
        self.page_size = int(kv_page_size
                             or getattr(cfg, "kv_page_size", 128))
        buckets = (decode_buckets
                   if decode_buckets is not None
                   else getattr(cfg, "decode_buckets", None))
        self.buckets = sorted(int(b) for b in buckets) if buckets else None
        self.max_seq_len = int(max_seq_len)
        self.prefill_chunk = int(prefill_chunk)
        if self.slots < 1 or self.page_size < 1 or self.max_seq_len < 2:
            raise ValueError(
                f"serve_slots={self.slots}, kv_page_size={self.page_size},"
                f" max_seq_len={self.max_seq_len}: all must be positive "
                f"(max_seq_len >= 2)")
        self.pages_per_slot = math.ceil(self.max_seq_len / self.page_size)
        want_pages = 1 + self.slots * self.pages_per_slot  # +1: scratch
        self.num_pages = int(kv_pages or getattr(cfg, "kv_pages", 0)
                             or want_pages)
        if self.num_pages < 1 + self.pages_per_slot:
            raise ValueError(
                f"kv_pages={self.num_pages} cannot hold even one "
                f"max_seq_len={self.max_seq_len} request "
                f"(needs {1 + self.pages_per_slot} incl. scratch page 0)")

        # ---- quantized serving tier (ISSUE 11) ----
        # weights: FFConfig.serve_weight_dtype (or the per-engine
        # weight_dtype override) promotes the weight-only quantized
        # decode path into a first-class serving mode — per-output-
        # channel scales, quantized ONCE below so the fixed-shape
        # programs trace against a stable quantized tree and never
        # retrace. The legacy `quantize` arg keeps working; mixing the
        # two with different values is a config error, not a silent pick.
        wd = (weight_dtype if weight_dtype is not None
              else getattr(cfg, "serve_weight_dtype", "native"))
        if wd not in ("native", "int8", "fp8"):
            raise ValueError(
                f"weight_dtype={wd!r}: must be 'native', 'int8' or 'fp8'")
        if wd != "native":
            if quantize not in (None, wd):
                raise ValueError(
                    f"weight_dtype={wd!r} conflicts with quantize="
                    f"{quantize!r}: pass one or the other")
            quantize = wd
        self.weight_dtype = quantize or "native"
        # KV pool storage: FFConfig.kv_cache_dtype (or the per-engine
        # override). int8/fp8 pools carry per-(page, kv-head) scales and
        # dequantize in VMEM (inside the Pallas kernel / fused into the
        # einsum gather); every page then holds 2-4x more tokens per HBM
        # byte, multiplying prefix-cache capacity and slots-per-chip —
        # the allocator, COW rule, radix trie, router affinity and
        # speculation are page-granular and unchanged.
        from flexflow_tpu.ops.attention import kv_storage_dtype

        kv_raw = (kv_cache_dtype if kv_cache_dtype is not None
                  else getattr(cfg, "kv_cache_dtype", "native"))
        kv_storage_dtype(kv_raw)  # validate early (incl. the fp8 gate)
        self._kv_dtype_arg = (None if kv_raw in (None, "", "native")
                              else kv_raw)

        # Generator supplies graph validation, the graph walk, prefill and
        # sampling — serving adds scheduling + the paged pool around them
        self.gen = Generator(model, temperature=temperature, top_k=top_k,
                             eos_id=eos_id, pad_id=pad_id, quantize=quantize)
        self.eos_id = eos_id
        self.pad_id = pad_id
        cdtype = self.gen._compute_dtype()
        if self._kv_dtype_arg is None:
            self.kv_cache_dtype = jnp.dtype(cdtype).name
        elif kv_raw == "bf16":
            self.kv_cache_dtype = "bfloat16"
        else:
            self.kv_cache_dtype = kv_raw
        if self.gen.quantize:
            # quantize once at engine init: the cached quantized tree is
            # what every program traces against — admission/decode never
            # pays the quantization pass, and the params cache cannot
            # invalidate mid-stream
            self.gen._quantized_params()
        # the pool is COMMITTED (replicated on the model's mesh) up front:
        # an uncommitted fresh pool has a different pjit signature
        # (UnspecifiedValue) than the committed arrays every program
        # RETURNS, so the second call to each warm program would silently
        # retrace and recompile it — a ~0.5 s stall in the serving loop
        # that the recompile counter could not see
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(model.mesh, PartitionSpec())
        self.pool = {
            op.name: jax.tree.map(
                lambda a: jax.device_put(a, repl),
                op.init_paged_cache(self.num_pages, self.page_size,
                                    cdtype, kv_dtype=self._kv_dtype_arg))
            for op in self.gen.attn_ops}
        self._free_pages = list(range(self.num_pages - 1, 0, -1))

        # pool-capacity observability (the router/bench signals ROADMAP
        # item 1 calls for), computed once — the pool's geometry is fixed
        # for the engine's life. The bf16 reference prices the SAME
        # geometry at 2 bytes/element, so kv_capacity_vs_bf16 is exactly
        # the capacity multiplier a quantized pool buys at equal HBM.
        self._pool_bytes = sum(
            int(a.nbytes) for a in jax.tree_util.tree_leaves(self.pool))
        self._kv_bytes_per_token = (
            self._pool_bytes / (self.num_pages * self.page_size))
        self._bf16_bytes_per_token = sum(
            op.num_kv_heads * (op.qk_head_dim + op.v_head_dim) * 2
            for op in self.gen.attn_ops)

        # decode attention impl over the paged pool: the per-engine
        # override wins, else FFConfig.paged_attention_impl; resolved
        # ONCE here ("auto" -> the backend's concrete choice) so every
        # program this engine builds, and stats(), agree on it. Under
        # "auto" a MEASURED winner persisted by search/kernel_tune.py's
        # tune_paged_attention for this engine's exact (page geometry,
        # heads, pool dtype) overrides the backend heuristic — the
        # paper's measured-costs-over-heuristics rule applied to impl
        # choice. The einsum page-gather stays the parity oracle —
        # greedy streams are token-identical either way
        # (tests/test_pallas_paged.py).
        from flexflow_tpu.ops.attention import resolve_paged_attention_impl

        requested = (paged_attention_impl
                     if paged_attention_impl not in (None, "")
                     else getattr(cfg, "paged_attention_impl", "auto")
                     or "auto")
        self.paged_attention_impl = resolve_paged_attention_impl(
            requested, cfg)
        from flexflow_tpu.search import kernel_tune

        # snapshot the autotune-table counter baseline BEFORE the
        # construction-time impl lookup below, so stats() shows that
        # lookup too — the bench stamps it as proof the dtype-keyed
        # entry governed an 'auto' engine
        self._ktune_base = kernel_tune.stats()
        if requested == "auto":
            op0 = self.gen.attn_ops[0]
            tuned = kernel_tune.lookup_paged_impl(
                page_size=self.page_size,
                pages_per_slot=self.pages_per_slot,
                head_dim=op0.qk_head_dim,
                dtype=self.pool[op0.name]["k"].dtype,
                batch=self.slots, heads=op0.num_heads)
            if tuned is not None:
                self.paged_attention_impl = tuned
        fflogger.info(
            "serving: paged decode attention impl=%s kv_cache_dtype=%s "
            "weight_dtype=%s (%.1f KV bytes/token, %.2fx bf16 capacity)",
            self.paged_attention_impl, self.kv_cache_dtype,
            self.weight_dtype, self._kv_bytes_per_token,
            self._bf16_bytes_per_token / self._kv_bytes_per_token)

        # radix prefix cache: page-granular prompt-prefix sharing with
        # copy-on-write allocation (shared pages are read-only; every
        # tail/decode write goes to the request's own fresh pages)
        enable_prefix = (prefix_cache if prefix_cache is not None
                         else getattr(cfg, "serve_prefix_cache", True))
        self.prefix_cache = (RadixPrefixCache(self.page_size)
                             if enable_prefix else None)

        # speculative decoding: a draft model proposes K greedy tokens
        # per slot; one fixed-shape verify program scores all K+1
        # positions in a single dispatch. Greedy-only: every emitted
        # token is the TARGET's argmax, so the stream is token-identical
        # to non-speculative decode by construction.
        self.speculate_k = int(speculate_k if speculate_k is not None
                               else getattr(cfg, "serve_speculate_k", 0))
        self.draft_model = (draft_model if draft_model is not None
                            else getattr(cfg, "draft_model", None))
        if self.speculate_k < 0:
            raise ValueError(
                f"speculate_k={self.speculate_k}: must be >= 0")
        self.draft_gen = None
        self.draft_pool = None
        if self.speculate_k > 0:
            if self.draft_model is None:
                raise ValueError(
                    "speculate_k > 0 needs a draft model (FFConfig."
                    "draft_model or the draft_model constructor arg): "
                    "speculative decoding verifies a DRAFT's proposals")
            if temperature > 0.0:
                raise ValueError(
                    "speculative decoding is greedy-only (temperature="
                    f"{temperature}): the accept rule compares the "
                    "draft's proposal to the target's argmax; a sampled "
                    "path needs rejection sampling, which this engine "
                    "does not implement")
            tgt_v = int(model._final_tensor.dims[-1])
            dft_v = int(self.draft_model._final_tensor.dims[-1])
            if tgt_v != dft_v:
                raise ValueError(
                    f"draft/target vocab mismatch: draft emits {dft_v} "
                    f"logits, target {tgt_v} — the accept rule compares "
                    f"token ids, so the vocabularies must be identical")
            self.draft_gen = Generator(
                self.draft_model, temperature=0.0, top_k=0, eos_id=eos_id,
                pad_id=pad_id, quantize=quantize)
            if self.draft_gen.quantize:
                self.draft_gen._quantized_params()  # once, at init
            ddtype = self.draft_gen._compute_dtype()
            drepl = NamedSharding(self.draft_model.mesh, PartitionSpec())
            # the draft pool mirrors the target pool's page GEOMETRY,
            # page IDS and storage dtype (its own KVH/Dh): one
            # allocator, one page table, one radix trie govern both — a
            # shared prefix page id means target AND draft prefix KV
            # are both resident
            self.draft_pool = {
                op.name: jax.tree.map(
                    lambda a: jax.device_put(a, drepl),
                    op.init_paged_cache(self.num_pages, self.page_size,
                                        ddtype,
                                        kv_dtype=self._kv_dtype_arg))
                for op in self.draft_gen.attn_ops}

        # per-slot scheduler state (host side, shipped to device each step)
        n = self.slots
        self.page_tables = np.zeros((n, self.pages_per_slot), np.int32)
        self.row_len = np.zeros((n,), np.int32)
        self.prompt_pad = np.zeros((n,), np.int32)
        self.emitted = np.zeros((n,), np.int32)
        self.last_tok = np.zeros((n,), np.int32)
        self.active = np.zeros((n,), bool)
        self.poison = np.zeros((n,), np.float32)
        self.slot_req: List[Optional[Request]] = [None] * n

        self._queue: List[Request] = []
        self._draining = False
        self._programs: Dict = {}
        self._key = jax.random.PRNGKey(seed)
        self._next_rid = 0
        # ONE engine lock around every queue/slot/counter mutation so a
        # router can drive this replica from its own thread while other
        # threads submit(), probe health() or snapshot stats(). Reentrant:
        # step() holds it across the whole tick (including the device
        # dispatch) and calls locked helpers underneath — cross-thread
        # callers simply serialize behind the tick.
        self._lock = threading.RLock()
        self.recompile_count = 0
        self.decode_steps = 0
        self._occupancy_sum = 0
        # aggregate counters instead of retaining every Request: a
        # long-lived engine must not grow memory with total traffic.
        # Retired Request objects are dropped (callers keep their own
        # handles — submit()/run() return them); TTFT percentiles come
        # from a bounded window of recent completions
        self._submitted = 0
        self._completed = 0
        self._failed = 0
        self._timeouts = 0      # expired while queued, never dispatched
        self._tokens_emitted = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_dispatches = 0
        # decode-attention observability (ISSUE 7 satellite): pool pages
        # the attention body READS per dispatch (sum over active slots
        # of the final-step frontier's page count — what the pallas
        # kernel streams / the einsum path gathers), plus a snapshot
        # baseline for the kernel-tune table counters. The counters are
        # PROCESS-GLOBAL (lookups fire inside kernel traces, which have
        # no engine identity), so stats() reports the process's
        # consultations since THIS engine was constructed — exact when
        # the engine is the only tracer (the usual serving process),
        # approximate when training or a second engine traces alongside
        self._pages_touched = 0
        self._last_pages_touched = 0
        # (the kernel-tune counter baseline _ktune_base is snapshotted
        # in the impl-resolution block above, before the construction-
        # time table lookup)
        import collections

        self._ttfts = collections.deque(maxlen=4096)

    # ---- request lifecycle --------------------------------------------------

    def _bucket(self, prompt_len: int) -> int:
        if self.buckets:
            for b in self.buckets:
                if b >= prompt_len:
                    return b
            raise ValueError(
                f"prompt length {prompt_len} exceeds the largest decode "
                f"bucket {self.buckets[-1]}")
        return _pow2_bucket(prompt_len)

    def submit(self, prompt, max_new_tokens: int,
               deadline: Optional[float] = None) -> Request:
        """Queue one request. ``deadline`` is an absolute
        ``time.perf_counter()`` instant: a request still queued past it
        retires as ``"timeout"`` without ever prefilling (an admitted
        request is never cancelled — see Request.deadline)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens}: must be >= 1")
        bucket = self._bucket(prompt.size)
        if bucket + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"bucketed prompt ({bucket}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len {self.max_seq_len}")
        with self._lock:
            if self._draining:
                # the serving-side preemption notice: a draining engine is
                # on its way down (elastic restart / deploy) — callers
                # must route new traffic elsewhere, not queue behind a
                # shutdown
                raise RuntimeError(
                    "ServingEngine is draining: new requests are not "
                    "admitted (health()['status'] exposes this to the "
                    "router)")
            req = Request(rid=self._next_rid, prompt=prompt,
                          max_new_tokens=int(max_new_tokens), bucket=bucket,
                          deadline=deadline, t_submit=time.perf_counter())
            self._next_rid += 1
            self._submitted += 1
            self._queue.append(req)
        return req

    def pending(self) -> bool:
        with self._lock:
            return bool(self._queue) or bool(self.active.any())

    def _retire(self, slot: int, state: str, error: str = ""):
        req = self.slot_req[slot]
        req.state = state
        req.error = error
        req.t_done = time.perf_counter()
        if state == "done":
            self._completed += 1
        else:
            self._failed += 1
        if req.ttft:
            self._ttfts.append(req.ttft)
        # COW teardown: pages the trie owns (matched prefix + the pages
        # this request published) are DECREF'd — they stay cached, warm
        # for the next hit, until the evictor needs them. Only the
        # request's private pages (partial prompt page, bucket padding,
        # decode appends) return to the free list.
        if req.trie_nodes:
            self.prefix_cache.release(req.trie_nodes)
            req.trie_nodes = []
        self._free_pages.extend(req.private_pages)
        req.private_pages = []
        req.slot = -1
        self.slot_req[slot] = None
        self.active[slot] = False
        self.poison[slot] = 0.0
        self.page_tables[slot, :] = 0   # scratch page: dead writes land there
        self.row_len[slot] = 0
        self.prompt_pad[slot] = 0
        self.emitted[slot] = 0

    def _record_token(self, slot: int, tok: int, ok: bool):
        """Append a sampled token to the slot's request and retire on
        non-finite logits, eos, or length — shared by prefill/decode."""
        req = self.slot_req[slot]
        if not ok:
            self._retire(slot, "failed", "non-finite logits")
            return
        req.tokens.append(int(tok))
        self._tokens_emitted += 1
        if not req.ttft:
            req.ttft = time.perf_counter() - req.t_submit
        self.emitted[slot] += 1
        self.last_tok[slot] = tok
        if (self.eos_id is not None and tok == self.eos_id) \
                or len(req.tokens) >= req.max_new_tokens:
            self._retire(slot, "done")

    # ---- compiled programs --------------------------------------------------

    def _compiled_call(self, key, build, *args):
        """Program-cache lookup; a miss builds + runs the program and
        bumps recompile_count, logging whether jax's persistent
        compilation cache (FFConfig.compilation_cache_dir) absorbed the
        compile. Every shape-affecting datum is part of `key`, so this
        counter is exactly the number of XLA compiles the engine caused."""
        fn = self._programs.get(key)
        if fn is not None:
            return fn(*args)
        fn = self._programs[key] = build()
        self.recompile_count += 1
        cache_dir = getattr(self.model.config, "compilation_cache_dir", "")
        before = compilation_cache_entries(cache_dir) if cache_dir else 0
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        if cache_dir:
            grew = compilation_cache_entries(cache_dir) - before
            fflogger.info(
                "serving: compiled %r in %.2fs — persistent cache %s",
                key, dt, f"MISS (+{grew} entries)" if grew > 0 else "HIT")
        else:
            fflogger.info("serving: compiled %r in %.2fs", key, dt)
        return out

    @staticmethod
    def _seed_prefix_caches(gen, bucket: int, p0: int, pool, prefix_pages):
        """Gather ``p0`` positions of cached prefix KV READ-ONLY into
        the front of a fresh contiguous per-request cache for every
        attention op — the shared half of every hit prefill. Quantized
        pools dequantize in the gather (op.gather_paged_kv), so the
        borrower attends exactly the lossy values the donor's decode
        sees. Target and draft builders use this one helper so the two
        pools (which share page ids) can never drift apart."""
        cdtype = gen._compute_dtype()
        caches = {}
        for op in gen.attn_ops:
            c = op.init_cache(1, bucket, cdtype)
            g = op.gather_paged_kv(pool[op.name], prefix_pages)
            caches[op.name] = {
                name: c[name].at[:, :p0].set(g[name].astype(c[name].dtype))
                for name in ("k", "v")}
        return caches

    @staticmethod
    def _scatter_tail(gen, pool, caches, pages, p0: int = 0):
        """COW scatter: write the contiguous cache's positions past
        ``p0`` into ``pages`` — the request's own fresh pages, never the
        shared ones. ``p0=0`` is the cold (whole-bucket) case."""
        return {
            op.name: op.paged_prefill_write(
                pool[op.name], caches[op.name]["k"][:, p0:],
                caches[op.name]["v"][:, p0:], pages)
            for op in gen.attn_ops}

    def _build_prefill(self, bucket: int, n_pages: int):
        gen = self.gen
        cdtype = gen._compute_dtype()

        def prefill(params, state, tokens, length, pool, pages, poison,
                    key):
            caches = {op.name: op.init_cache(1, bucket, cdtype)
                      for op in gen.attn_ops}
            logits, caches = gen._prefill(params, state, tokens, caches,
                                          length, self.prefill_chunk)
            logits = logits[:, -1] + poison            # (1, V)
            ok = jnp.isfinite(logits).all(axis=-1)
            tok, _ = gen._sample(logits, key)
            return tok, ok, self._scatter_tail(gen, pool, caches, pages)

        return jax.jit(prefill, donate_argnums=(4,))

    def _build_prefill_hit(self, bucket: int, full: int):
        """Prefix-hit prefill: ``full`` cached pages are gathered
        READ-ONLY into the front of a contiguous per-request cache, the
        tail slab [full*ps, bucket) runs as one chunk_forward pass (each
        tail position attends the gathered prefix + the tail's own causal
        window — bitwise the whole-prompt einsum, runtime/generation.py),
        a gather-last query scores the prompt's true last position, and
        ONLY the tail k/v scatters out — into the request's fresh pages,
        never the shared ones (the copy-on-write rule; the matched
        prefix's partial last page is re-materialized here too)."""
        gen = self.gen
        p0 = full * self.page_size

        def prefill(params, state, tokens_tail, tok_last, length, pool,
                    prefix_pages, tail_pages, poison, key):
            caches = self._seed_prefix_caches(gen, bucket, p0, pool,
                                              prefix_pages)
            _, caches = gen._walk(params, state, tokens_tail, caches,
                                  None, chunk_start=p0, skip_tail=True)
            logits, caches = gen._walk(params, state, tok_last, caches,
                                       None, last_only=True,
                                       row_lengths=length,
                                       gather_last=True)
            logits = logits[:, -1] + poison            # (1, V)
            ok = jnp.isfinite(logits).all(axis=-1)
            tok, _ = gen._sample(logits, key)
            return tok, ok, self._scatter_tail(gen, pool, caches,
                                               tail_pages, p0)

        return jax.jit(prefill, donate_argnums=(5,))

    def _build_draft_prefill(self, bucket: int, n_pages: int):
        """Cold draft prefill: fill the draft pool's pages for the whole
        bucket. Cache-only (skip_tail) — the draft's first proposal is
        sampled by the draft-decode scan, so its prefill logits are
        never needed."""
        gen = self.draft_gen
        cdtype = gen._compute_dtype()

        def prefill(params, state, tokens, pool, pages):
            caches = {op.name: op.init_cache(1, bucket, cdtype)
                      for op in gen.attn_ops}
            _, caches = gen._walk(params, state, tokens, caches, None,
                                  skip_tail=True)
            return self._scatter_tail(gen, pool, caches, pages)

        return jax.jit(prefill, donate_argnums=(3,))

    def _build_draft_prefill_hit(self, bucket: int, full: int):
        """Prefix-hit draft prefill: same gather + tail-chunk + COW
        scatter as the target's hit program (the shared helpers), minus
        the logits tail."""
        gen = self.draft_gen
        p0 = full * self.page_size

        def prefill(params, state, tokens_tail, pool, prefix_pages,
                    tail_pages):
            caches = self._seed_prefix_caches(gen, bucket, p0, pool,
                                              prefix_pages)
            _, caches = gen._walk(params, state, tokens_tail, caches,
                                  None, chunk_start=p0, skip_tail=True)
            return self._scatter_tail(gen, pool, caches, tail_pages, p0)

        return jax.jit(prefill, donate_argnums=(3,))

    def _build_verify(self, k: int):
        """Speculative verify: ONE dispatch scores all K+1 candidate
        positions per slot — the slab [last_tok, d_1..d_K] flows through
        the target graph with paged_verify_forward writing each
        position's k/v at its own (host-clamped) slot and attending at
        its own frontier. Returns the target's greedy argmax at every
        position plus per-position finiteness; acceptance is host-side
        (compare proposals to argmax, emit the matching prefix + 1)."""
        gen = self.gen

        def verify(params, state, pool, page_table, slab, write_pos,
                   rope_pos0, row_len, prompt_pad, poison):
            paged = {"page_table": page_table, "write_pos": write_pos,
                     "rope_pos": rope_pos0, "row_len": row_len,
                     "prompt_pad": prompt_pad,
                     "impl": self.paged_attention_impl}
            logits, pool = gen._walk(params, state, slab, pool, None,
                                     paged=paged)
            logits = logits.astype(jnp.float32) \
                + poison[:, None, None]                # (B, K+1, V)
            ok = jnp.isfinite(logits).all(axis=-1)     # (B, K+1)
            toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return toks, ok, pool

        return jax.jit(verify, donate_argnums=(2,))

    def _build_decode(self, n_steps: int, gen=None):
        gen = gen or self.gen

        def decode(params, state, pool, page_table, last_tok, write_pos0,
                   rope_pos0, row_len, prompt_pad, budget, poison, key):
            """`n_steps` slot-decode steps as ONE in-graph scan. Past a
            slot's own budget (prompt_pad + its max_new_tokens) the write
            position and RoPE clamp to the final allocated slot — those
            steps only produce tokens the host truncates, and the
            repeated overwrite stays inside the slot's own pages."""
            rope_cap = budget - prompt_pad + row_len - 1

            def body(carry, i):
                pool, tok, key = carry
                paged = {
                    "page_table": page_table,
                    "write_pos": jnp.minimum(write_pos0 + i, budget - 1),
                    "rope_pos": jnp.minimum(rope_pos0 + i, rope_cap),
                    "row_len": row_len, "prompt_pad": prompt_pad,
                    "impl": self.paged_attention_impl}
                logits, pool = gen._walk(params, state, tok[:, None],
                                         pool, None, paged=paged)
                logits = logits[:, 0] + poison[:, None]  # (B_slots, V)
                ok = jnp.isfinite(logits).all(axis=-1)
                key, sub = jax.random.split(key)
                nxt, _ = gen._sample(logits, sub)
                return (pool, nxt, key), (nxt, ok)

            (pool, _, _), (toks, oks) = jax.lax.scan(
                body, (pool, last_tok, key),
                jnp.arange(n_steps, dtype=jnp.int32))
            return toks, oks, pool                     # (n_steps, B_slots)

        return jax.jit(decode, donate_argnums=(2,))

    def _split_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    # ---- the scheduler loop -------------------------------------------------

    def _expire_queued(self):
        """Retire queued requests whose deadline has passed as "timeout"
        — they never prefill, hold no pages and cost no dispatch (the
        per-request-deadline half of the fleet-router contract: expiring
        work is dropped at the cheapest possible point)."""
        now = time.perf_counter()
        kept: List[Request] = []
        for req in self._queue:
            if req.deadline is not None and now >= req.deadline:
                req.state = "timeout"
                req.error = "deadline expired while queued"
                req.t_done = now
                self._timeouts += 1
            else:
                kept.append(req)
        self._queue = kept

    def _admit(self):
        """Move queued requests into free slots: look up the longest
        cached prompt prefix, allocate fresh pages for everything past it
        (copy-on-write — shared pages are never written), prefill the
        tail (bucket-shaped program) and seed the slot."""
        self._expire_queued()
        while self._queue:
            try:
                slot = next(i for i in range(self.slots)
                            if not self.active[i])
            except StopIteration:
                return
            req = self._queue[0]
            total = req.bucket + req.max_new_tokens
            n_total = math.ceil(total / self.page_size)
            # longest cached page-aligned prefix, capped so at least the
            # prompt's LAST token is always prefilled (its logits seed
            # the first emitted token). No refcounts move until the
            # admission is certain.
            matched: List[_TrieNode] = []
            if self.prefix_cache is not None:
                cap = (req.prompt.size - 1) // self.page_size
                matched = self.prefix_cache.match(req.prompt, cap)
            full = len(matched)
            need = n_total - full
            if len(self._free_pages) < need:
                if self.prefix_cache is not None:
                    # pool pressure: reclaim cold cached pages (LRU,
                    # refcount-0 leaves only; the just-matched path is
                    # protected — it is about to be mounted)
                    self._free_pages.extend(self.prefix_cache.evict(
                        need - len(self._free_pages), protect=matched))
                if len(self._free_pages) < need:
                    # still short: wait for a retirement to free pages.
                    # Head-of-line blocking is deliberate — FIFO
                    # admission keeps TTFT fairness; submit() already
                    # guarantees the request fits an EMPTY pool (the
                    # trie is fully evictable once its users retire),
                    # so progress is always possible. The request stays
                    # QUEUED with no refcounts or pages held.
                    return
            self._queue.pop(0)
            # fault injection: FF_FAULT=slow(<ms>)@serve:<n> stalls the
            # n-th admission host-side — the deterministic slow-replica
            # drill (a deadline set tighter than <ms> expires while this
            # request is in flight; the router must NOT resubmit it)
            if faultinject.active_plan().fire("slow", "serve"):
                time.sleep((faultinject.active_plan().last_value or 0)
                           / 1000.0)
            fresh = [self._free_pages.pop() for _ in range(need)]
            if self.prefix_cache is not None:
                self.prefix_cache.note_admitted(full)
            if matched:
                self.prefix_cache.acquire(matched)
                req.trie_nodes = list(matched)
                req.prefix_tokens = full * self.page_size
            req.private_pages = list(fresh)
            req.pages = [n.page for n in matched] + fresh
            req.slot = slot
            req.state = "running"
            self.slot_req[slot] = req

            n_prefill = math.ceil(req.bucket / self.page_size)
            # fault injection: FF_FAULT=nan_loss@serve:<n> poisons the
            # n-th ADMITTED request in-graph (NaN added to its logits), so
            # the detect-and-retire path runs end to end, not a host stub
            if faultinject.active_plan().fire("nan_loss", "serve"):
                self.poison[slot] = np.float32(np.nan)
            table = np.zeros((self.pages_per_slot,), np.int32)
            table[:n_total] = req.pages
            self.page_tables[slot] = table
            self.row_len[slot] = req.prompt.size
            self.prompt_pad[slot] = req.bucket
            self.emitted[slot] = 0

            if full:
                # prefix hit: gather the matched pages read-only, prefill
                # only the tail slab [full*ps, bucket) into FRESH pages —
                # the matched prefix's partial last page (tokens past
                # full*ps) is re-materialized into the request's own
                # first tail page, never written in the donor's (the COW
                # rule). One program per (bucket, full): bounded like the
                # buckets themselves, flat after warmup.
                p0 = full * self.page_size
                padded_tail = np.full((1, req.bucket - p0), self.pad_id,
                                      np.int32)
                tail = req.prompt[p0:]
                padded_tail[0, :tail.size] = tail
                tok_last = np.asarray([[req.prompt[-1]]], np.int32)
                tok, ok, self.pool = self._compiled_call(
                    ("prefill_hit", req.bucket, full),
                    lambda: self._build_prefill_hit(req.bucket, full),
                    self.gen._params(), self.model.bn_state, padded_tail,
                    tok_last, np.asarray([req.prompt.size], np.int32),
                    self.pool, np.asarray(req.pages[:full], np.int32),
                    np.asarray(req.pages[full:n_prefill], np.int32),
                    np.float32(self.poison[slot]), self._split_key())
            else:
                padded = np.full((1, req.bucket), self.pad_id, np.int32)
                padded[0, :req.prompt.size] = req.prompt
                tok, ok, self.pool = self._compiled_call(
                    ("prefill", req.bucket, n_prefill, self.prefill_chunk),
                    lambda: self._build_prefill(req.bucket, n_prefill),
                    self.gen._params(), self.model.bn_state, padded,
                    np.asarray([req.prompt.size], np.int32), self.pool,
                    np.asarray(req.pages[:n_prefill], np.int32),
                    np.float32(self.poison[slot]), self._split_key())
            if self.draft_gen is not None:
                # the draft model's prefix KV rides the same page ids, so
                # its prefill mirrors the target's hit/cold split exactly
                if full:
                    self.draft_pool = self._compiled_call(
                        ("draft_prefill_hit", req.bucket, full),
                        lambda: self._build_draft_prefill_hit(req.bucket,
                                                              full),
                        self.draft_gen._params(), self.draft_model.bn_state,
                        padded_tail, self.draft_pool,
                        np.asarray(req.pages[:full], np.int32),
                        np.asarray(req.pages[full:n_prefill], np.int32))
                else:
                    self.draft_pool = self._compiled_call(
                        ("draft_prefill", req.bucket, n_prefill),
                        lambda: self._build_draft_prefill(req.bucket,
                                                          n_prefill),
                        self.draft_gen._params(), self.draft_model.bn_state,
                        padded, self.draft_pool,
                        np.asarray(req.pages[:n_prefill], np.int32))
            ok_host = bool(np.asarray(ok)[0])
            if self.prefix_cache is not None and ok_host:
                # publish this prompt's FULL pages beyond the matched
                # prefix for future sharing (poisoned/non-finite prefills
                # are never published — a NaN prompt cache must not
                # infect later requests). Published pages move from
                # private to trie-owned: decref'd at retirement, freed
                # only by eviction.
                last = req.prompt.size // self.page_size
                if last > full:
                    created = self.prefix_cache.insert(
                        req.prompt, matched, full, req.pages[full:last])
                    if created:
                        adopted = {n.page for n in created}
                        req.trie_nodes.extend(created)
                        req.private_pages = [p for p in req.private_pages
                                             if p not in adopted]
            self.active[slot] = True
            self._record_token(slot, int(np.asarray(tok)[0]), ok_host)

    def _slot_decode_state(self):
        """(write_pos, rope_pos, budget) for one decode/speculate
        dispatch. Inactive slots: state arrays are zeroed, so write_pos
        = -1 would index page -1 — clamp to 0 (the write lands in
        scratch page 0) and give them budget 1, clamping every later
        step there too. Budget is the last legal write position + 1
        (bucket + the request's own max_new_tokens)."""
        write_pos = np.maximum(self.prompt_pad + self.emitted - 1,
                               0).astype(np.int32)
        rope_pos = np.maximum(self.row_len + self.emitted - 1,
                              0).astype(np.int32)
        budget = np.ones((self.slots,), np.int32)
        for slot in range(self.slots):
            req = self.slot_req[slot]
            if req is not None:
                budget[slot] = req.bucket + req.max_new_tokens
        return write_pos, rope_pos, budget

    def _note_pages_touched(self, frontier, budget):
        """Record the pool pages this dispatch's attention READS: per
        active slot, pages up to its final-step write frontier (what the
        pallas kernel streams through VMEM — the einsum path gathers the
        whole table width regardless, which is exactly the delta the
        kernel exists to remove)."""
        fr = np.minimum(frontier, budget - 1)
        touched = int(np.sum((fr // self.page_size + 1)[self.active])) \
            if self.active.any() else 0
        self._last_pages_touched = touched
        self._pages_touched += touched

    def _decode_step(self):
        k = self.decode_chunk
        write_pos, rope_pos, budget = self._slot_decode_state()
        self._note_pages_touched(write_pos + k - 1, budget)
        toks, oks, self.pool = self._compiled_call(
            ("decode", k), lambda: self._build_decode(k),
            self.gen._params(), self.model.bn_state, self.pool,
            self.page_tables, self.last_tok, write_pos, rope_pos,
            self.row_len, self.prompt_pad, budget, self.poison,
            self._split_key())
        toks = np.asarray(toks)                        # (k, B_slots)
        oks = np.asarray(oks)
        self.decode_steps += k
        for slot in range(self.slots):
            for t in range(k):
                if not self.active[slot]:
                    break  # retired mid-chunk: later tokens are truncated
                # occupancy counts USEFUL slot-steps only — a slot that
                # retires mid-chunk stops counting, so the metric is not
                # inflated by the truncated past-retirement steps
                self._occupancy_sum += 1
                self._record_token(slot, int(toks[t, slot]),
                                   bool(oks[t, slot]))

    def _spec_step(self):
        """One speculative iteration: the draft proposes K greedy tokens
        per slot (one K-step scan over its own paged pool), the target
        scores all K+1 candidate positions in ONE verify dispatch, and
        the host emits the longest proposal prefix matching the target's
        argmax plus the target's own next token — between 1 and K+1
        TARGET-greedy tokens per slot per iteration, token-identical to
        the non-speculative stream. k/v written for rejected positions
        sit past the slot's new write frontier and are overwritten by the
        next dispatch before anything can attend them."""
        k = self.speculate_k
        write_pos, rope_pos, budget = self._slot_decode_state()
        # verify-slab frontier (the draft's decode mirrors the same pages)
        self._note_pages_touched(write_pos + k, budget)
        d_toks, _, self.draft_pool = self._compiled_call(
            ("draft_decode", k),
            lambda: self._build_decode(k, gen=self.draft_gen),
            self.draft_gen._params(), self.draft_model.bn_state,
            self.draft_pool, self.page_tables, self.last_tok, write_pos,
            rope_pos, self.row_len, self.prompt_pad, budget,
            np.zeros((self.slots,), np.float32), self._split_key())
        d_toks = np.asarray(d_toks)                    # (k, B_slots)
        slab = np.concatenate(
            [self.last_tok[:, None].astype(np.int32), d_toks.T], axis=1)
        # per-position write slots, clamped to each request's own budget
        # (positions an emitted token can attend never reach the clamp —
        # emission stops at max_new first, so clamp-duplicated writes are
        # only ever visible to host-truncated tokens)
        pos = np.minimum(
            write_pos[:, None] + np.arange(k + 1, dtype=np.int32)[None, :],
            (budget - 1)[:, None]).astype(np.int32)
        t_toks, t_oks, self.pool = self._compiled_call(
            ("verify", k), lambda: self._build_verify(k),
            self.gen._params(), self.model.bn_state, self.pool,
            self.page_tables, slab, pos, rope_pos, self.row_len,
            self.prompt_pad, self.poison)
        t_toks = np.asarray(t_toks)                    # (B_slots, k+1)
        t_oks = np.asarray(t_oks)
        self.decode_steps += k + 1
        self._spec_dispatches += 1
        for slot in range(self.slots):
            if not self.active[slot]:
                continue
            self._spec_proposed += k
            accepted = 0
            while accepted < k \
                    and d_toks[accepted, slot] == t_toks[slot, accepted]:
                accepted += 1
            self._spec_accepted += accepted
            for m in range(accepted + 1):
                if not self.active[slot]:
                    break  # retired mid-window: the rest is truncated
                self._occupancy_sum += 1
                self._record_token(slot, int(t_toks[slot, m]),
                                   bool(t_oks[slot, m]))

    def _decode_tick(self):
        if self.speculate_k > 0 and self.draft_gen is not None:
            self._spec_step()
        else:
            self._decode_step()

    def step(self) -> bool:
        """One scheduler tick: admit what fits (unless draining), then one
        slot-decode step if any slot is live. Returns whether
        PROGRESSABLE work remains — on a draining engine only live slots
        count (the frozen queue can never be admitted here), so a
        while-step loop always terminates. Holds the engine lock for the
        whole tick: concurrent submit()/stats() callers serialize behind
        it (thread-per-replica routers drive step from one thread, so
        the tick itself never contends)."""
        with self._lock:
            if not self._draining:
                self._admit()
            if self.active.any():
                self._decode_tick()
            if self._draining:
                return bool(self.active.any())
            return self.pending()

    def run(self, prompts=None, max_new_tokens: int = 32) -> List[Request]:
        """Submit `prompts` (list of 1-D int32 arrays) and drive the
        scheduler until the engine is idle; returns THIS call's requests
        in submission order (with prompts=None: whatever was pending at
        entry). The engine holds no reference to retired requests."""
        if prompts is not None:
            batch = [self.submit(p, max_new_tokens) for p in prompts]
        else:
            batch = [r for r in self.slot_req if r is not None] \
                + list(self._queue)
        while self.step():
            pass
        return batch

    # ---- graceful shutdown --------------------------------------------------

    def drain(self) -> Dict:
        """Graceful shutdown (the serving half of elastic recovery: a
        preemption notice or planned restart must not drop tokens already
        being decoded): stop admitting new requests, run the decode loop
        until every in-flight slot retires on eos/length/failure, and
        return a final stats snapshot. Requests still QUEUED (never
        admitted) stay queued untouched — the caller re-submits them to
        the replacement engine; their count rides the snapshot. Idempotent
        — a second drain() finds no live slots and returns the snapshot
        again."""
        with self._lock:
            self._draining = True
        while True:
            # lock per tick, not across the drain: submit() callers get a
            # prompt RuntimeError instead of blocking on the whole drain
            with self._lock:
                if not self.active.any():
                    break
                self._decode_tick()
        with self._lock:
            snap = self.stats()
            snap["drained"] = True
            snap["queued"] = len(self._queue)
        fflogger.info(
            "serving: drained — %d completed, %d failed, %d still queued "
            "(re-submit to the replacement engine), occupancy %.2f, "
            "%d recompiles", snap["completed"], snap["failed"],
            snap["queued"], snap["occupancy"], snap["recompiles"])
        return snap

    def health(self) -> Dict:
        """Cheap liveness/readiness probe for a router: admission status
        plus the load counters a balancer steers by, sliced from the one
        ``stats()`` snapshot so the two probes share every formula and
        key name. Never compiles or touches the device. Serializes
        behind a running tick — for a contention-free mid-tick load
        estimate use ``load()``."""
        with self._lock:
            active = int(self.active.sum())
            if self._draining:
                # the frozen queue does not hold "draining": those
                # requests can never be admitted here (they belong to the
                # replacement engine), so the drain is over when the live
                # slots are
                status = "draining" if active else "drained"
            else:
                status = "busy" if (active or self._queue) else "idle"
            snap = self.stats()
            return {
                "status": status,
                "admitting": not self._draining,
                "active_slots": active,
                "queued": len(self._queue),
                **{k: snap[k] for k in ("serve_slots", "free_pages",
                                        "completed", "failed", "timeouts",
                                        "occupancy", "recompiles",
                                        "pages_in_use", "kv_pages_shared",
                                        "prefix_hit_rate",
                                        "spec_accept_rate",
                                        "kv_cache_dtype", "weight_dtype",
                                        "kv_bytes_per_token",
                                        "tokens_per_pool_gb")},
            }

    def load(self) -> Dict:
        """Lock-free load snapshot for a router's dispatch loop: active
        slots + queue depth, read WITHOUT the engine lock so a dispatcher
        never blocks behind a replica mid-tick. The reads race the owning
        thread by design — a balancer steering on slightly stale load is
        correct; a balancer stalled behind every decode dispatch is not."""
        return {"active_slots": int(self.active.sum()),
                "queued": len(self._queue)}

    # ---- metrics ------------------------------------------------------------

    def flush_prefix_cache(self) -> int:
        """Evict EVERY refcount-0 cached page back to the free list;
        returns the number reclaimed. For weight hot-swap (cached KV is
        stale under new weights) and for page-leak accounting: after
        drain() + flush, free_pages must equal kv_pages - 1. Pages still
        mounted by live requests survive (and stay cached)."""
        if self.prefix_cache is None:
            return 0
        with self._lock:
            freed = self.prefix_cache.evict(self.num_pages, pressure=False)
            self._free_pages.extend(freed)
            return len(freed)

    def stats(self) -> Dict:
        with self._lock:
            return self._stats_locked()

    def _stats_locked(self) -> Dict:
        pc = self.prefix_cache
        ttfts = sorted(self._ttfts)  # bounded window of completions

        def pct(p):
            if not ttfts:
                return 0.0
            return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))]

        return {
            "requests": self._submitted,
            "completed": self._completed,
            "failed": self._failed,
            "timeouts": self._timeouts,
            "tokens_generated": self._tokens_emitted,
            "decode_steps": self.decode_steps,
            "recompiles": self.recompile_count,
            # mean fraction of computed positions doing USEFUL work per
            # decode step (mid-chunk retirements stop counting) — the
            # engine's steady-state utilization headline. Under
            # speculation the denominator counts all K+1 verify
            # positions, so occupancy folds the accept rate in
            # ((1 + aK)/(K+1) on a saturated engine): it measures wasted
            # COMPUTE, not idle slots — a router balancing on busyness
            # should use active_slots/queued (health()) and read
            # spec_accept_rate separately. occupied_slot_steps is the
            # raw numerator so callers can compute occupancy over a
            # WINDOW from two stats() snapshots
            "occupancy": (self._occupancy_sum
                          / max(1, self.decode_steps) / self.slots),
            "occupied_slot_steps": self._occupancy_sum,
            "ttft_p50_ms": round(pct(0.50) * 1e3, 3),
            "ttft_p99_ms": round(pct(0.99) * 1e3, 3),
            "free_pages": len(self._free_pages),
            "kv_pages": self.num_pages,
            "kv_page_size": self.page_size,
            "serve_slots": self.slots,
            # quantized-tier observability (ISSUE 11): what the pool and
            # weights are stored as, what a token of KV costs in HBM
            # (scales included), how many tokens a GB of pool holds, and
            # the capacity multiplier vs a bf16 pool of the same
            # geometry — effective page capacity = kv_page_size x that
            # multiplier in bf16-equivalent tokens per page's bytes.
            # These are the router/bench placement signals: a quantized
            # replica advertises more tokens per byte, not more bytes.
            "kv_cache_dtype": self.kv_cache_dtype,
            "weight_dtype": self.weight_dtype,
            "kv_pool_bytes": self._pool_bytes,
            "kv_bytes_per_token": round(self._kv_bytes_per_token, 3),
            "tokens_per_pool_gb": int((1 << 30)
                                      / self._kv_bytes_per_token),
            "kv_capacity_vs_bf16": round(
                self._bf16_bytes_per_token / self._kv_bytes_per_token, 3),
            "kv_effective_page_capacity": round(
                self.page_size * self._bf16_bytes_per_token
                / self._kv_bytes_per_token, 1),
            # KV-pool observability (ROADMAP item 1: the router balances
            # on these): in-use counts every non-free page (live-private
            # + cached), cached the pages the radix trie holds (warm,
            # reclaimable at refcount 0), shared those mounted by >1
            # live request right now
            "pages_in_use": self.num_pages - 1 - len(self._free_pages),
            "kv_pages_cached": pc.pages if pc else 0,
            "kv_pages_shared": pc.shared_pages() if pc else 0,
            "prefix_cache": pc is not None,
            "prefix_lookups": pc.lookups if pc else 0,
            "prefix_hits": pc.hits if pc else 0,
            "prefix_hit_rate": (round(pc.hits / max(1, pc.lookups), 4)
                                if pc else 0.0),
            "prefill_tokens_saved": pc.tokens_saved if pc else 0,
            "prefix_evictions": pc.evictions if pc else 0,
            # live references into the trie: must be 0 after drain() —
            # nonzero at idle means a refcount leak
            "prefix_refs_live": pc.live_refs() if pc else 0,
            "speculate_k": self.speculate_k,
            "spec_proposed": self._spec_proposed,
            "spec_accepted": self._spec_accepted,
            "spec_accept_rate": round(
                self._spec_accepted / max(1, self._spec_proposed), 4),
            # decode-attention hot-path observability (ISSUE 7): which
            # impl this engine's programs trace, how many pool pages the
            # last dispatch's attention read (vs the table-width gather
            # the einsum path always re-materializes), and the kernel
            # autotune table's process-wide hit/miss deltas since engine
            # construction (see the baseline note in __init__)
            "paged_attention_impl": self.paged_attention_impl,
            "pages_touched": self._pages_touched,
            "last_pages_touched": self._last_pages_touched,
            **{f"kernel_tune_{k}": v - self._ktune_base.get(k, 0)
               for k, v in _ktune_stats().items()
               if k in ("hits", "misses")},
        }
