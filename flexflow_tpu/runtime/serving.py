"""Continuous-batching serving runtime: slot decode over a paged KV cache.

The reference's only inference story is the training graph run forward-only
(CompMode::COMP_MODE_INFERENCE); runtime/generation.py added the modern
one-program KV-cache decode, but as a FIXED batch: finished rows burn full
decode steps emitting pads, a new request cannot start until the whole
batch retires, and every (prompt shape, max_new_tokens) pair compiles its
own program. This module is the serving-side performance subsystem on top
of it:

  * ONE jitted slot-decode step of fixed shape ``(serve_slots, 1)`` runs
    for the life of the engine — the compiled program never changes shape,
    the HOST scheduler moves work in and out of slots (the partition-
    don't-pad philosophy applied to serving: keep XLA static, move the
    raggedness to the host).
  * The KV cache is a POOL of ``(kv_pages, kv_page_size, KVH, Dh)`` blocks
    with a per-slot page table (ops/attention.py paged_decode_forward):
    long and short requests share HBM instead of every slot preallocating
    ``max_seq_len``. Pages are allocated at admission and freed at
    retirement; page 0 is a scratch page inactive slots harmlessly write.
  * Admission prefills the prompt into the slot's pages through the
    EXISTING prefill path (Generator._prefill, chunked via chunk_forward
    when ``prefill_chunk`` is set) on a contiguous per-request cache, then
    scatters that k/v into the pool — prefill numerics are therefore
    identical to batch generate's, and greedy continuous batching is
    token-identical to per-request Generator.generate
    (tests/test_serving.py).
  * Prompt lengths are rounded up to SHAPE BUCKETS (powers of two by
    default, ``decode_buckets`` to pin explicit boundaries) so warm
    prefill programs are reused across mixed lengths; ``recompile_count``
    exposes every program build, and after bucket warmup it stays flat.
  * Every compiled program returns a per-slot finiteness flag computed
    in-graph; a request whose logits go non-finite (e.g. FF_FAULT
    ``nan_loss@serve:<n>`` poisons the n-th admitted request) is retired
    as ``failed`` without stalling the other slots — serving inherits the
    fault-injection story of runtime/faultinject.py.
  * ``drain()``/``health()``: graceful shutdown for deploys and elastic
    topology changes (docs/resilience.md) — stop admitting, finish the
    in-flight slots, final stats snapshot; queued-but-unadmitted requests
    stay queued for re-submission to the replacement engine.
  * FLEET-READY: one engine lock serializes every queue/slot/counter
    mutation so a router (runtime/router.py ServingRouter) can drive
    each replica from its own thread while other threads submit and
    probe; ``submit(..., deadline=)`` retires requests that expire while
    queued as ``"timeout"`` without ever prefilling; ``load()`` is the
    lock-free dispatch signal.
  * RADIX PREFIX CACHE (RadixPrefixCache): a trie over page-aligned
    prompt token chunks maps each full KV page a finished prefill
    produced to its pool page id, with a per-page refcount of the live
    requests referencing it. Admission looks up the longest cached
    page-aligned prefix, bumps refcounts, and prefills ONLY the tail —
    page writes are copy-on-write: a shared page is never written in
    place (the tail, including the recompute of the matched prefix's
    partial last page, scatters into fresh pages; decode appends land
    past the prompt bucket, also in the request's own pages).
    Retirement decrefs; refcount-0 pages stay cached for future hits
    until an LRU evictor reclaims them under pool pressure. Identical
    prompts across millions of requests then share prefill compute AND
    the HBM pages it produced (ROADMAP item 1).
  * SPECULATIVE DECODING (``draft_model`` + ``speculate_k``): a small
    draft model proposes K greedy tokens per slot from its own paged
    pool (same page ids — the prefix cache shares draft pages too), and
    ONE fixed-shape verify program scores all K+1 positions against the
    target in a single dispatch
    (MultiHeadAttention.paged_verify_forward). Greedy slots accept the
    longest prefix of proposals matching the target's argmax (the
    stream is token-identical to non-speculative greedy decode);
    SAMPLED slots run the REJECTION-SAMPLED accept rule (ISSUE 14):
    accept proposal i w.p. min(1, p_i(d_i)/q_i(d_i)), re-draw the
    first rejection in-graph from the residual norm(max(p - q, 0)) —
    distribution-identical to the non-speculative sampler by
    construction. The accept rate rides ``stats()``.

  * PER-REQUEST SAMPLING (ISSUE 14): temperature / top-p / top-k /
    seed are SLOT-RESIDENT STATE inside the one fixed-shape program
    (per-slot scalar arrays, like ``write_pos``) — mixed sampling
    configs never recompile, and greedy is the bitwise temperature-0
    degenerate case. Sample streams are counter-based
    (ops/sampling.py): a pure function of (seed, stream, token index),
    reproducible across slot reassignment and failover resubmission.

  * PAGED LoRA ADAPTER POOL (ISSUE 14): per-request adapters served
    from a fixed-geometry device pool mirroring the KV pool's design —
    host allocator/LRU with refcounts (runtime/lora.py), ONE
    fixed-shape fault-in writer, per-slot adapter pages gathered into
    batched segmented LoRA matmuls inside the slot program
    (ops/lora.py; page 0 = the zero null adapter). The radix trie and
    router affinity are namespaced per adapter (KV depends on the
    adapter), and telemetry gains per-adapter labeled series. N
    tenants share a replica with zero recompiles.

  * QUANTIZED SERVING TIER (``FFConfig.kv_cache_dtype`` /
    ``serve_weight_dtype``, ISSUE 11): the paged pool stores int8/fp8
    payload with per-(page, kv-head) f32 scales alongside, so each page
    holds 2-4x more tokens per HBM byte — prefix-cache capacity and
    slots-per-chip multiply at fixed pool bytes while the allocator,
    COW rule, radix trie, router affinity and speculation (all
    page-granular) are untouched. Dequantization happens in VMEM:
    inside the Pallas paged-attention kernel against scalar-prefetched
    scales, or fused into the einsum gather (the parity oracle) — wide
    KV never materializes in HBM. Serving weights quantize ONCE at
    engine init (per-output-channel scales) and dequantize fused into
    each consuming matmul. Quantization is lossy: greedy streams carry
    a documented per-dtype divergence budget vs the full-width path
    (docs/serving.md "Quantized tier"); pallas-vs-einsum token identity
    and pool bitwise equality still hold exactly.

  * TIERED PREFIX CACHE + DISAGGREGATION PRIMITIVES (ISSUE 12):
    ``host_kv_pages`` gives the radix trie a pinned host-memory second
    tier — refcount-0 pages evicted under pool pressure DEMOTE (async
    ordered D2H publisher, generation-checked) instead of dying, and a
    trie match against a host-resident edge PROMOTES the payload back
    (H2D, bitwise), so the shared-prefix corpus is host-RAM-sized. The
    same page-payload plumbing powers the prefill/decode role split
    (runtime/router.py): ``prefill_into_cache()`` runs a prompt's
    prefill through the normal bucket programs and publishes its full
    pages at refcount 0, ``export_prefix_slab()`` serializes them (+
    draft-pool KV + quantized scales) to host bytes, and a decode
    replica's ``import_prefix_slab()`` scatters them in through ONE
    fixed-shape page-writer program and republishes the trie path — the
    subsequent submit admits as a prefix hit, so the handoff moves
    pages, never tokens. ``warmup(prompts)`` drives every reachable
    (bucket, matched_pages) prefill variant plus the page writer, the
    thrice-relearned bench gotcha promoted to an API.

Per-slot cache layout (identical to the ragged rule of
MultiHeadAttention.decode_forward, with a per-slot prompt pad width):
logical positions ``[0, row_len)`` hold the true prompt, ``[row_len,
prompt_pad)`` hold masked bucket-pad garbage, decode tokens append from
``prompt_pad``; RoPE positions stay LOGICAL (``row_len + emitted``).
"""

from __future__ import annotations

import collections
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu._env import compilation_cache_entries
from flexflow_tpu.logger import fflogger
from flexflow_tpu.ops import sampling as sampling_ops
from flexflow_tpu.runtime import faultinject, flightrec, locks, telemetry
from flexflow_tpu.runtime.generation import Generator
from flexflow_tpu.runtime.lora import LoraAdapterPool

# process-wide engine ids: the default telemetry `replica` label when no
# router assigns a fleet identity (set_telemetry_identity)
_ENGINE_IDS = iter(range(1 << 30))

# the weight version every engine serves until a rolling deploy swaps it
# (runtime/deploy.py). The default version salts NOTHING — version_ns
# returns the bare adapter namespace, so pre-deploy behavior (cache keys,
# affinity hashes, slab namespaces) is bit-identical to builds without
# versioning.
DEFAULT_WEIGHT_VERSION = "v0"


def version_ns(version, adapter=None):
    """The prefix-cache namespace for (weight version, LoRA adapter) —
    the ISSUE-14 ``("ns", adapter)`` salt extended to versions (ISSUE
    17): KV depends on the weights that produced it, so cached prefixes
    must never cross weight versions during an A/B roll. Kept next to
    RadixPrefixCache.first_chunk so the engine, router affinity, and
    slab import/export derive the SAME key and cannot drift. The default
    version maps to the bare adapter (None for no adapter): zero change
    to any pre-deploy trie or affinity key."""
    if version in (None, "", DEFAULT_WEIGHT_VERSION):
        return adapter
    return (version, adapter)


def _ktune_stats():
    from flexflow_tpu.search import kernel_tune

    return kernel_tune.stats()


@dataclass
class Request:
    """One serving request and its full lifecycle record."""

    rid: int
    prompt: np.ndarray              # (S,) int32, true (unpadded) prompt
    max_new_tokens: int
    state: str = "queued"       # queued | running | done | failed | timeout
    # per-request sampling config (ISSUE 14): slot-resident scalars in
    # the ONE fixed-shape program — temperature 0 is the greedy
    # degenerate case (bitwise the pre-sampling argmax). ``seed`` keys
    # the request's counter-based sample streams (ops/sampling.py): the
    # stream is a pure function of (seed, stream, token index), so it
    # reproduces across slot reassignment and failover resubmission.
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    seed: int = 0
    # multi-tenant LoRA (ISSUE 14): the registered adapter this request
    # decodes under (None = base model / null adapter page 0), and the
    # adapter-pool page pinned for it while the slot is live
    adapter: Optional[str] = None
    adapter_page: int = 0
    # absolute time.perf_counter() deadline (None = none): a request that
    # expires while QUEUED retires as "timeout" without ever prefilling
    # (no pages, no dispatch); an already-admitted request is never
    # cancelled mid-batch — cancellation would disturb the fixed-shape
    # slot program — its late completion is the caller's to discard
    deadline: Optional[float] = None
    tokens: List[int] = field(default_factory=list)  # emitted tokens
    slot: int = -1
    bucket: int = 0
    pages: List[int] = field(default_factory=list)   # full logical table
    # prefix-cache bookkeeping: trie nodes whose refcount this request
    # holds (shared prefix pages + pages it published), and the pages it
    # owns outright (freed at retirement; trie pages are only decref'd)
    trie_nodes: List = field(default_factory=list)
    private_pages: List[int] = field(default_factory=list)
    prefix_tokens: int = 0          # prefill positions served from cache
    t_submit: float = 0.0
    ttft: float = 0.0               # submit -> first emitted token (s)
    t_done: float = 0.0
    error: str = ""
    # telemetry (runtime/telemetry.py): the trace id this request's
    # spans carry — a router-assigned fleet id survives resubmission and
    # the prefill->decode handoff; engine-local requests get their own.
    # t_last_tok clocks the inter-token-latency histogram; decode_span
    # is the open cross-thread span handle closed at retirement.
    trace_id: str = ""
    t_last_tok: float = 0.0
    decode_span: int = 0

    @property
    def output(self) -> np.ndarray:
        """prompt + emitted tokens, the shape generate() would return
        for this request alone (minus trailing pads it never emitted)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])


def _pow2_bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class _TrieNode:
    """One cached KV page: the page_size-token chunk it encodes (its edge
    label from the parent), the pool page id holding its k/v, and the
    refcount of live requests whose page tables reference it.

    Tiering (ISSUE 12): ``tier`` is "hbm" (``page`` is a live pool page),
    "host" (the page was demoted — ``page`` is -1 and ``hostdata`` holds
    the pinned host copy, None while the async D2H publish is still in
    flight) or "dead" (a failed migration marked it for lazy reaping).
    ``gen`` is the migration generation: every demote/kill bumps it, so a
    late-completing publish for an abandoned migration is dropped by the
    ordered publisher instead of resurrecting a reused node."""

    __slots__ = ("chunk", "page", "parent", "children", "ref", "last_use",
                 "tier", "hostdata", "gen")

    def __init__(self, chunk, page, parent):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children = {}
        self.ref = 0
        self.last_use = 0
        self.tier = "hbm"
        self.hostdata = None
        self.gen = 0


class RadixPrefixCache:
    """Radix/trie index over prompt token prefixes at PAGE granularity.

    Each trie edge is exactly ``page_size`` tokens, so a path of depth d
    names a d-page prompt prefix and maps it to the d pool pages holding
    its KV — the page, not the token, is the unit of sharing because the
    pool scatters, gathers and refcounts pages. A page's KV at position j
    depends only on tokens [0..j] (causal attention), so any request
    whose prompt starts with the same ``d * page_size`` tokens can mount
    those pages read-only and prefill just its tail.

    TIERED (HBM -> host) CACHE (ISSUE 12): with ``host_pages > 0`` a
    refcount-0 page reclaimed under pool pressure MIGRATES to a pinned
    host-memory tier instead of dying — the node stays in the trie with
    ``tier == "host"``, its HBM page frees immediately, and the page
    payload (pool storage bytes + quantized scales, target AND draft
    pools) publishes to host memory on ONE ordered background publisher
    thread (the async-checkpointing pattern, runtime/checkpoint.py): the
    D2H starts in device order before the page can be reused, resolves
    off the hot path, and a generation check drops the publish if the
    node was killed/reused meanwhile. A later match against a
    host-resident edge PROMOTES it back: allocate a fresh HBM page, H2D
    the payload (bitwise — export/import never requantize), mount. The
    effective shared-prefix corpus is then host-RAM-sized, not
    HBM-sized. Tier invariant: on any root->node path the tiers read
    ``hbm* host*`` — demotion picks nodes with no HBM children,
    promotion walks the matched path root-down — so a mounted (hbm,
    ref>0) prefix never sits below a host page. The host tier itself is
    LRU-bounded at ``host_pages``: overflow evicts the oldest host leaf
    for real. Failure policy (FF_FAULT ``d2h_fail@migrate:<n>`` /
    ``h2d_fail@promote:<n>``): a failed demotion means the page dies
    exactly as it did without the tier; a failed promotion kills the
    host copy and falls back to cold prefill — never a stall, never a
    corrupt page mounted.

    Ownership protocol (the copy-on-write rule lives HERE, not in the
    kernels): a page in the trie is never written again — its producer
    published it only after prefill, and every borrower's tail/decode
    writes land in freshly allocated pages past the matched prefix.
    ``ref`` counts live requests mounting the page; retirement decrefs.
    A refcount-0 page stays cached (warm for the next hit) until
    ``evict()`` reclaims it under pool pressure, LRU-first and leaves
    only — an interior page must outlive its children, since a match
    walks through it. All host-side, O(prompt/page_size) per lookup;
    ``evict()`` walks the whole trie per pressure call, which is fine at
    the pool sizes this engine runs (hundreds of pages) — a
    persistently-maintained ref-0-leaf LRU makes reclaim O(need) if
    pool sizes grow by orders of magnitude."""

    def __init__(self, page_size: int, host_pages: int = 0,
                 d2h=None, h2d=None):
        self.page_size = int(page_size)
        self.root = _TrieNode(None, -1, None)
        self.pages = 0          # HBM-page-holding nodes currently cached
        self.lookups = 0
        self.hits = 0
        self.tokens_saved = 0   # prefill positions served from cache
        self.evictions = 0      # PRESSURE evictions only (flushes don't
        #                         count — they are not a pool signal)
        self._tick = 0          # monotonic LRU clock (bumped per lookup)
        # incremental mirrors of the trie's refcount state, so stats()
        # and the per-tick health() probe never walk the trie
        self._live_refs = 0     # sum of node.ref
        self._shared = 0        # nodes with ref > 1 right now
        # ---- host tier (ISSUE 12) ----
        # d2h(pages) -> resolver() -> [payload, ...]: starts the async
        # copy of a LIST of pool pages host-ward (one batched gather per
        # demotion sweep) and returns the callable the ordered publisher
        # resolves off the hot path; h2d(pages, payloads): writes
        # payloads back into fresh pool pages (one batched writer
        # dispatch). The engine injects real device IO; the pure-host
        # tier tests inject fakes — the state machine itself never
        # touches a device.
        self.host_pages = int(host_pages)
        if self.host_pages < 0:
            raise ValueError(f"host_pages={host_pages}: must be >= 0")
        if self.host_pages and (d2h is None or h2d is None):
            raise ValueError("host_pages > 0 needs d2h and h2d callables")
        self.d2h = d2h
        self.h2d = h2d
        self.host_used = 0      # host-resident pages (pending included)
        self.demotions = 0
        self.promotions = 0
        self.demote_failures = 0
        self.promote_failures = 0
        self.host_evictions = 0  # host-LRU overflow kills (pages died)
        # ordered publisher: demotions publish host-ward in submission
        # order on ONE daemon thread (the async-checkpointing pattern);
        # _cv guards hostdata/gen/queue handoff between that thread and
        # the engine-lock holder. Structural trie mutation stays under
        # the ENGINE lock only.
        self._cv = locks.make_condition("prefix-cache")
        self._pending = collections.deque()
        self._inflight = 0
        self._publisher: Optional[threading.Thread] = None
        # depth-1 tier transitions for the router's tier-aware affinity:
        # (first-page chunk, "host"|"hbm"|None) — None means the prefix
        # died entirely (affinity entries pointing at it should drop)
        self.tier_events = collections.deque(maxlen=4096)

    def _chunk(self, prompt, i: int, ns=None):
        ps = self.page_size
        tup = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
        if ns is not None and i == 0:
            # namespace salt (ISSUE 14): KV depends on the LoRA adapter
            # the prompt was prefilled under, so cached prefixes must
            # never cross tenants — salting the FIRST edge partitions
            # the whole trie per adapter (every deeper edge hangs under
            # it). The salted first chunk is also the router's
            # adapter-aware affinity key (first_chunk()).
            return ("ns", ns) + tup
        return tup

    @staticmethod
    def first_chunk(tokens, ns=None):
        """The trie's first-edge key for ``tokens`` (one page worth of
        prompt) under adapter namespace ``ns`` — the fleet router's
        affinity hash, kept in one place so the two layers cannot
        drift."""
        tup = tuple(int(t) for t in tokens)
        return (("ns", ns) + tup) if ns is not None else tup

    def match(self, prompt, max_pages: int, ns=None) -> List[_TrieNode]:
        """Longest cached page-aligned prefix of ``prompt``, capped at
        ``max_pages``; returns the node path root-down (possibly empty).
        Does NOT take references or bump hit statistics — the caller
        commits with acquire()/note_admitted() only once admission is
        certain (a request that stays queued on pool pressure re-matches
        every tick and must leave refcounts AND counters untouched)."""
        self._tick += 1
        node, path = self.root, []
        limit = min(int(max_pages), len(prompt) // self.page_size)
        for i in range(limit):
            child = node.children.get(self._chunk(prompt, i, ns))
            if child is None:
                break
            if child.tier == "dead":
                # a migration failed on the publisher thread; the node
                # was only MARKED there (trie structure is engine-lock
                # territory) — reap it lazily here
                self._kill_subtree(child)
                break
            path.append(child)
            node = child
        for n in path:
            n.last_use = self._tick
        return path

    def note_admitted(self, matched_pages: int):
        """Commit one admission's lookup to the hit statistics — called
        exactly once per ADMITTED request, never for retried matches."""
        self.lookups += 1
        if matched_pages:
            self.hits += 1
            self.tokens_saved += matched_pages * self.page_size

    def acquire(self, nodes):
        for n in nodes:
            if n.tier != "hbm":  # the cross-tier refcount rule: only a
                #  resident page can be mounted — promote first
                raise AssertionError(
                    f"acquire on a {n.tier}-tier page: host-resident "
                    f"prefix pages must be promoted before mounting")
            n.ref += 1
            self._live_refs += 1
            if n.ref == 2:
                self._shared += 1

    def release(self, nodes):
        for n in nodes:
            n.ref -= 1
            self._live_refs -= 1
            if n.ref == 1:
                self._shared -= 1
            if n.ref < 0:  # accounting bug, not a recoverable state
                raise AssertionError(
                    f"prefix-cache refcount underflow on page {n.page}")

    def insert(self, prompt, matched, start: int,
               pages: List[int], ns=None) -> List[_TrieNode]:
        """Publish a finished prefill's full-prompt pages: ``pages[j]``
        holds chunk ``start + j`` of ``prompt``, appended under the
        ``matched`` path. Each created node starts at ref 1 (the
        publishing request still mounts it). Stops at the first chunk
        that already exists — the caller's duplicate page for it stays
        private (only possible when the match was capped below an
        existing deeper path)."""
        node = matched[-1] if matched else self.root
        created = []
        for j, page in enumerate(pages):
            chunk = self._chunk(prompt, start + j, ns)
            if chunk in node.children:
                break
            child = _TrieNode(chunk, page, node)
            child.ref = 1
            self._live_refs += 1
            child.last_use = self._tick
            node.children[chunk] = child
            node = child
            created.append(child)
            self.pages += 1
        return created

    def _iter_nodes(self):
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    def cached_paths(self) -> List[Tuple[np.ndarray, object, int]]:
        """Every root-to-leaf cached prefix, hottest first, as
        ``(tokens, ns, last_use)`` — the evacuation manifest a
        preempted/retiring replica walks (ISSUE 20). Tokens are
        reconstructed from the edge chunks themselves (the first edge's
        ``("ns", ns)`` salt is peeled back into the namespace), so the
        caller can re-export each path with export_prefix_slab under the
        exact per-version/per-adapter key it was cached under. Leaves
        only: exporting a leaf path carries every interior page, and the
        importer dedupes shared prefixes. Dead (lost-host-copy) nodes
        prune their subtrees — there is nothing to evacuate below them."""
        out = []
        for first, child in self.root.children.items():
            if first and first[0] == "ns":
                ns, toks0 = first[1], first[2:]
            else:
                ns, toks0 = None, first
            stack = [(child, toks0)]
            while stack:
                node, toks = stack.pop()
                if node.tier == "dead":
                    continue
                kids = [(c.chunk, c) for c in node.children.values()
                        if c.tier != "dead"]
                if not kids:
                    out.append((np.asarray(toks, np.int32), ns,
                                node.last_use))
                    continue
                for chunk, c in kids:
                    stack.append((c, toks + chunk))
        out.sort(key=lambda e: -e[2])
        return out

    def evict(self, need: int, protect=(), pressure: bool = True) \
            -> List[int]:
        """Reclaim up to ``need`` HBM pages, oldest last_use first;
        returns the freed page ids. Without a host tier this evicts
        refcount-0 LEAVES and the page dies; with ``host_pages > 0`` and
        ``pressure=True`` the page DEMOTES instead — the node stays in
        the trie host-resident (eligible nodes are ref-0 with no HBM
        children, preserving the hbm*-then-host* path invariant) and the
        payload publishes host-ward asynchronously in order. ``protect``
        excludes a just-matched path the caller is about to acquire.
        Reclaiming a node may expose its parent — the sweep cascades.
        ``pressure=False`` (hot-swap flush, leak accounting) kills
        outright — host copies included, since both tiers hold KV that a
        weight swap staled — and stays out of the ``evictions``
        pool-pressure signal."""
        import heapq

        keep = set(id(n) for n in protect)
        demote = pressure and self.host_pages > 0

        def reclaimable(n):
            if n.ref != 0 or id(n) in keep or n.tier == "reaped":
                return False
            if not pressure:
                # flush kills outright — any tier, leaves only
                return not n.children
            if n.tier != "hbm":
                return False
            if demote:
                # demotion keeps the node: children only need to be
                # non-HBM so the hbm*-then-host* path invariant holds
                return all(c.tier != "hbm" for c in n.children.values())
            return not n.children

        heap = [(n.last_use, id(n), n) for n in self._iter_nodes()
                if reclaimable(n)]
        heapq.heapify(heap)
        freed: List[int] = []
        selected: List[_TrieNode] = []
        while heap and (len(freed) + len(selected) < need
                        or not pressure):
            _, _, n = heapq.heappop(heap)
            if not reclaimable(n):
                continue        # a cascade re-push raced a state change
            parent = n.parent
            if demote and n.tier == "hbm":
                if faultinject.active_plan().fire("d2h_fail", "migrate"):
                    # failed demotion: the page dies exactly as it did
                    # before a host tier existed
                    self.demote_failures += 1
                    freed.extend(self._kill_subtree(n))
                else:
                    # mark now (the cascade must see a non-HBM child);
                    # the ONE batched D2H snapshot happens below,
                    # before any freed page can be reused
                    n.tier = "host"
                    n.hostdata = None
                    n.gen += 1
                    self.pages -= 1
                    self.host_used += 1
                    self.demotions += 1
                    self._tier_event(n, "host")
                    selected.append(n)
                self.evictions += 1
            else:
                freed.extend(self._kill_subtree(n))
                if pressure:
                    self.evictions += 1
            if parent is not self.root and reclaimable(parent):
                heapq.heappush(heap, (parent.last_use, id(parent), parent))
        # a failed-demotion kill (d2h_fail on a parent) may have reaped
        # an already-selected descendant — its page was freed by the
        # kill, so it must not reach the snapshot (a page -1 gather
        # would read junk and double-free)
        selected = [n for n in selected if n.tier == "host"]
        if selected:
            freed.extend(self._demote_sweep(selected))
            # host-LRU capacity is enforced per SWEEP (a mid-sweep
            # victim could be a selected-but-unsnapshot node, whose kill
            # would leak its pool page): after the snapshot every host
            # node is a legal victim
            self._make_host_room()
        return freed

    # ---- the HBM -> host tier state machine (ISSUE 12) -------------------

    def _tier_event(self, node, tier):
        """Record a depth-1 tier transition for the router's tier-aware
        prefix affinity: the first-page chunk IS the affinity key."""
        if node.parent is self.root:
            self.tier_events.append((node.chunk, tier))

    def _kill_subtree(self, node) -> List[int]:
        """Remove ``node`` (and its now-unreachable descendants — all
        non-HBM by the path invariant when a migration kills an interior
        node) from the trie. Bumps every generation so late publishes
        abandon, returns the HBM pages freed."""
        if node.tier == "reaped":
            return []
        if node.parent is not None \
                and node.parent.children.get(node.chunk) is node:
            del node.parent.children[node.chunk]
        self._tier_event(node, None)
        freed: List[int] = []
        stack = [node]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            n.children = {}
            if n.ref:
                raise AssertionError(
                    f"killing a mounted prefix page (ref={n.ref})")
            if n.tier == "hbm":
                freed.append(n.page)
                self.pages -= 1
            elif n.tier in ("host", "dead"):
                self.host_used -= 1
                if n.page >= 0:
                    # selected-for-demotion but not yet snapshot: its
                    # pool page is still allocated — free it too
                    freed.append(n.page)
            n.tier = "reaped"
            n.page = -1
            n.hostdata = None
            n.gen += 1      # abandon any in-flight migration publish
        with self._cv:
            self._cv.notify_all()   # wake promoters waiting on a corpse
        return freed

    def _demote_sweep(self, nodes) -> List[int]:
        """ONE batched D2H snapshot for a whole eviction sweep's
        demotions (per-page slicing was measurable host overhead on
        small hosts): the slices are enqueued BEFORE the freed pages can
        be reused (device programs execute in order — the PR-9
        snapshot-before-donate rule), and the ordered publisher resolves
        them to pinned host memory off the hot path. Returns the freed
        HBM page ids."""
        pages = [n.page for n in nodes]
        handle = self.d2h(list(pages))
        gens = []
        for n in nodes:
            n.page = -1
            gens.append(n.gen)
        with self._cv:
            self._pending.append((list(nodes), gens, handle))
            self._inflight += len(nodes)
            self._cv.notify_all()
        self._ensure_publisher()
        return pages

    def _make_host_room(self):
        """LRU within the host tier: overflow evicts the oldest host
        LEAVES for real (host nodes' children are host by the
        invariant, so a leaf always exists while host_used > 0). ONE
        trie walk collects a whole sweep's victims — dead nodes (failed
        publishes awaiting reap: budget, no data) first, then oldest
        last_use — and the outer loop re-walks only when killing leaves
        exposed new ones. Nodes selected for demotion in the CURRENT
        sweep (page still >= 0, snapshot not yet taken) are never
        victims — killing one would leak its pool page."""
        while self.host_used > self.host_pages:
            cands = [n for n in self._iter_nodes()
                     if n.tier in ("host", "dead") and not n.children
                     and n.page < 0]
            if not cands:
                return
            cands.sort(key=lambda n: (0 if n.tier == "dead" else 1,
                                      n.last_use))
            for n in cands:
                if self.host_used <= self.host_pages:
                    break
                if n.tier == "reaped" or n.children:
                    continue
                self._kill_subtree(n)
                self.host_evictions += 1

    def promote(self, node, page) -> bool:
        """H2D one host-resident node into freshly allocated HBM
        ``page``; True on success (see promote_path)."""
        if node.tier == "hbm":
            return True
        return self.promote_path([node], [page]) == 1

    def promote_path(self, nodes, pages) -> int:
        """Promote host-resident ``nodes`` (a matched path's host tail,
        root-down) into ``pages``: per-node failure checks first —
        FF_FAULT ``h2d_fail@promote:<n>``, a publish that never landed —
        truncate the run and KILL the failed copy (the caller falls back
        to cold prefill past it: never a stall, never a corrupt page
        mounted); then ONE batched H2D writes the surviving prefix back
        bitwise. Returns the number promoted; unused pages are the
        caller's to reclaim."""
        ok_nodes, payloads = [], []
        for node in nodes:
            if node.tier != "host":
                break
            if faultinject.active_plan().fire("h2d_fail", "promote"):
                self.promote_failures += 1
                self._kill_subtree(node)
                break
            payload = self.host_payload(node)
            if payload is None:
                self.promote_failures += 1
                self._kill_subtree(node)
                break
            ok_nodes.append(node)
            payloads.append(payload)
        if not ok_nodes:
            return 0
        use = list(pages[:len(ok_nodes)])
        try:
            self.h2d(use, payloads)
        except Exception:   # noqa: BLE001 — any H2D loss falls back cold
            self.promote_failures += 1
            for node in ok_nodes:
                self._kill_subtree(node)
            return 0
        for node, page in zip(ok_nodes, use):
            node.page = int(page)
            node.tier = "hbm"
            node.hostdata = None
            node.gen += 1   # abandon any stale pending publish
            self.pages += 1
            self.host_used -= 1
            self.promotions += 1
            self._tier_event(node, "hbm")
        return len(ok_nodes)

    def host_payload(self, node, timeout: float = 60.0):
        """The node's host-tier payload, waiting (bounded) for an
        in-flight ordered publish; None if the node died or the publish
        never lands (the caller treats it as a promotion failure)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while node.tier == "host" and node.hostdata is None:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._cv.wait(left)
            return node.hostdata if node.tier == "host" else None

    def _ensure_publisher(self):
        if self._publisher is None or not self._publisher.is_alive():
            self._publisher = threading.Thread(
                target=self._publisher_main, daemon=True,
                name="ff-prefix-tier-publisher")
            self._publisher.start()

    def _publisher_main(self):
        """ONE background thread publishes demoted pages host-ward in
        submission order (the async-checkpointing ordered-publisher
        contract): resolve the D2H handle, then commit the payload ONLY
        if the node's generation still matches — an abandoned migration
        (the node was killed, flushed or re-promoted meanwhile) is
        dropped, never resurrected."""
        while True:
            with self._cv:
                while not self._pending:
                    self._cv.wait()
                nodes, gens, handle = self._pending.popleft()
            payloads, err = None, None
            try:
                payloads = handle()
            except Exception as e:  # noqa: BLE001 — a failed resolve is
                #   a failed demotion: the pages die, serving continues
                err = e
            with self._cv:
                self._inflight -= len(nodes)
                for i, (node, gen) in enumerate(zip(nodes, gens)):
                    if node.gen != gen or node.tier != "host":
                        continue    # abandoned migration: gen check
                    if err is not None:
                        # structural removal needs the engine lock —
                        # mark dead for lazy reaping by the next
                        # match/evict walk
                        node.tier = "dead"
                        node.hostdata = None
                        self.demote_failures += 1
                    else:
                        node.hostdata = payloads[i]
                self._cv.notify_all()
            if err is not None:
                fflogger.warning(
                    "prefix tier: D2H publish failed (%s) — %d pages "
                    "die as if untiered", err, len(nodes))

    def pending_migrations(self) -> int:
        with self._cv:
            return self._inflight

    def wait_migrations(self, timeout: float = 60.0) -> bool:
        """Quiesce the ordered publisher (drain/tests): True when every
        submitted demotion has published or abandoned."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
            return True

    def forget(self, prompt, ns=None) -> List[int]:
        """Kill the deepest unmounted, childless tail of ``prompt``'s
        cached path (any tier); returns freed HBM pages. The
        warm-the-import-writer helper: export, forget, re-import leaves
        the trie state unchanged with the writer program compiled."""
        path = self.match(prompt, len(prompt) // self.page_size, ns)
        freed: List[int] = []
        for n in reversed(path):
            if n.children or n.ref:
                break
            freed.extend(self._kill_subtree(n))
        return freed

    def flush_namespace(self, ns) -> List[int]:
        """Kill EVERY cached page under adapter namespace ``ns``, both
        tiers: the adapter's weights are being replaced, so KV computed
        under the old weights must never serve a prefix hit for the new
        ones (it would splice two weight versions into one stream).
        Refuses while any namespace page is mounted — impossible when
        the adapter itself is unpinned, since a mounted ns page always
        belongs to a live request holding the adapter. Returns the
        freed HBM pages."""
        roots = [c for c in self.root.children.values()
                 if isinstance(c.chunk, tuple) and len(c.chunk) >= 2
                 and c.chunk[0] == "ns" and c.chunk[1] == ns]
        for node in roots:
            stack = [node]
            while stack:
                n = stack.pop()
                if n.ref:
                    raise ValueError(
                        f"adapter namespace {ns!r} has a mounted cached "
                        f"page (ref={n.ref}): drain its requests before "
                        f"replacing the adapter")
                stack.extend(n.children.values())
        freed: List[int] = []
        for node in roots:
            freed.extend(self._kill_subtree(node))
        return freed

    def drain_tier_events(self) -> List:
        """Pop the recorded depth-1 tier transitions (router affinity
        feed)."""
        out = []
        while self.tier_events:
            out.append(self.tier_events.popleft())
        return out

    def live_refs(self) -> int:
        return self._live_refs

    def shared_pages(self) -> int:
        """Pages mounted by more than one live request right now."""
        return self._shared


class ServingEngine:
    """Continuous-batching engine over a compiled FFModel decoder LM.

    Build once (after model.compile()); ``submit()`` requests and drive
    ``step()`` yourself, or hand ``run()`` a list of prompts. Construction
    knobs default to the model's FFConfig (serve_slots, kv_page_size,
    kv_pages, decode_buckets)."""

    def __init__(self, model, serve_slots: Optional[int] = None,
                 kv_page_size: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 decode_buckets: Optional[List[int]] = None,
                 max_seq_len: int = 1024,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 pad_id: int = 0, prefill_chunk: int = 0,
                 decode_chunk: int = 8,
                 quantize: Optional[str] = None, seed: int = 0,
                 prefix_cache: Optional[bool] = None,
                 host_kv_pages: Optional[int] = None,
                 draft_model=None, speculate_k: Optional[int] = None,
                 paged_attention_impl: Optional[str] = None,
                 kv_cache_dtype: Optional[str] = None,
                 weight_dtype: Optional[str] = None,
                 adapter_pool_pages: Optional[int] = None,
                 lora_rank: Optional[int] = None,
                 lora_targets: Optional[List[str]] = None,
                 prefill_interleave_chunks: Optional[int] = None):
        cfg = model.config
        # sanitize mode is read at LOCK CREATION time: adopt
        # FFConfig.sanitize before this engine (or its pools)
        # creates a single lock (runtime/locks.py)
        locks.configure(cfg)
        self.model = model
        # ---- per-request sampling defaults (ISSUE 14) ----
        # requests carry their own temperature/top_p/top_k/seed as
        # slot-resident state inside the one fixed-shape program
        # (ops/sampling.py); the engine-level values are only the
        # submit() defaults. temperature 0 = greedy argmax, bitwise the
        # pre-sampling path.
        t0 = (temperature if temperature is not None
              else getattr(cfg, "serve_temperature", 0.0))
        p0 = (top_p if top_p is not None
              else getattr(cfg, "serve_top_p", 1.0))
        k0 = (top_k if top_k is not None
              else getattr(cfg, "serve_top_k", 0))
        self.default_temperature, self.default_top_p, self.default_top_k \
            = sampling_ops.validate_sampling(t0, p0, k0, "ServingEngine")
        # request-seed base: a submit() without an explicit seed gets a
        # deterministic per-rid seed derived from the engine seed. Fleet
        # routers pass explicit seeds (stable across failover
        # resubmission — engine rids differ between replicas).
        self._seed_base = (int(seed) * 1000003) & 0x7FFFFFFF
        self.slots = int(serve_slots or getattr(cfg, "serve_slots", 4))
        # decode steps per device dispatch (an in-graph lax.scan): host
        # round-trips amortize over the chunk — the per-token dispatch of
        # chunk=1 dominates small-model decode. Retirement granularity
        # coarsens to the chunk; tokens a slot computes past its own
        # eos/length are truncated by the host, so outputs are identical
        # at any chunk (tests/test_serving.py). Waste is bounded by
        # chunk-1 steps per retirement, idle-slot time by chunk-1 per
        # admission — keep it well under typical max_new_tokens.
        self.decode_chunk = max(1, int(decode_chunk))
        self.page_size = int(kv_page_size
                             or getattr(cfg, "kv_page_size", 128))
        buckets = (decode_buckets
                   if decode_buckets is not None
                   else getattr(cfg, "decode_buckets", None))
        self.buckets = sorted(int(b) for b in buckets) if buckets else None
        self.max_seq_len = int(max_seq_len)
        self.prefill_chunk = int(prefill_chunk)
        # chunk-interleaved admission (ISSUE 18): > 0 makes each cold
        # prompt's prefill chunks schedulable quanta — step() runs at
        # most this many chunks per tick between decode dispatches, so
        # a maximal prompt admits without stalling live decode streams.
        # Needs prefill_chunk > 0 (the chunk IS the quantum).
        self.prefill_interleave_chunks = int(
            prefill_interleave_chunks
            if prefill_interleave_chunks is not None
            else getattr(cfg, "prefill_interleave_chunks", 0))
        if self.prefill_interleave_chunks < 0:
            raise ValueError(
                f"prefill_interleave_chunks="
                f"{self.prefill_interleave_chunks}: must be >= 0")
        if self.prefill_interleave_chunks and self.prefill_chunk <= 0:
            raise ValueError(
                "prefill_interleave_chunks > 0 needs prefill_chunk > 0: "
                "the chunk is the interleave quantum")
        if self.slots < 1 or self.page_size < 1 or self.max_seq_len < 2:
            raise ValueError(
                f"serve_slots={self.slots}, kv_page_size={self.page_size},"
                f" max_seq_len={self.max_seq_len}: all must be positive "
                f"(max_seq_len >= 2)")
        self.pages_per_slot = math.ceil(self.max_seq_len / self.page_size)
        # prefix-cache membership decides the derived pool size below, so
        # resolve it before the derive (the trie itself is built later)
        enable_prefix = (prefix_cache if prefix_cache is not None
                         else getattr(cfg, "serve_prefix_cache", True))
        # kv_pages = 0 derive: scratch page + one slot's worth of pages
        # per slot + prefix-cache slack. The slack matters: with exactly
        # slots*pages_per_slot pages, a full house leaves ZERO free pages
        # for refcount-0 cached prefixes, so every retirement's pages are
        # immediately reclaimed by the next admission and the radix cache
        # silently goes cold (ISSUE 18; found as PR 11's derive bug).
        # Half the slot pages — at least one slot's worth — keeps a warm
        # working set of shared prefixes alive at full occupancy. Page
        # ids are allocated pool-size-independently (pop from the low
        # end), so growing the pool never changes which pages a request
        # gets — streams are bitwise unaffected.
        slot_pages = self.slots * self.pages_per_slot
        cache_slack = (max(self.pages_per_slot, slot_pages // 2)
                       if enable_prefix else 0)
        want_pages = 1 + slot_pages + cache_slack  # +1: scratch
        explicit_pages = int(kv_pages or getattr(cfg, "kv_pages", 0) or 0)
        self.num_pages = explicit_pages or want_pages
        if not explicit_pages:
            fflogger.info(
                "serving: derived kv_pages=%d (scratch 1 + slots %d x "
                "pages_per_slot %d = %d + prefix-cache slack %d)",
                self.num_pages, self.slots, self.pages_per_slot,
                slot_pages, cache_slack)
        if self.num_pages < 1 + self.pages_per_slot:
            raise ValueError(
                f"kv_pages={self.num_pages} cannot hold even one "
                f"max_seq_len={self.max_seq_len} request "
                f"(needs {1 + self.pages_per_slot} incl. scratch page 0)")

        # ---- quantized serving tier (ISSUE 11) ----
        # weights: FFConfig.serve_weight_dtype (or the per-engine
        # weight_dtype override) promotes the weight-only quantized
        # decode path into a first-class serving mode — per-output-
        # channel scales, quantized ONCE below so the fixed-shape
        # programs trace against a stable quantized tree and never
        # retrace. The legacy `quantize` arg keeps working; mixing the
        # two with different values is a config error, not a silent pick.
        wd = (weight_dtype if weight_dtype is not None
              else getattr(cfg, "serve_weight_dtype", "native"))
        if wd not in ("native", "int8", "fp8"):
            raise ValueError(
                f"weight_dtype={wd!r}: must be 'native', 'int8' or 'fp8'")
        if wd != "native":
            if quantize not in (None, wd):
                raise ValueError(
                    f"weight_dtype={wd!r} conflicts with quantize="
                    f"{quantize!r}: pass one or the other")
            quantize = wd
        self.weight_dtype = quantize or "native"
        # KV pool storage: FFConfig.kv_cache_dtype (or the per-engine
        # override). int8/fp8 pools carry per-(page, kv-head) scales and
        # dequantize in VMEM (inside the Pallas kernel / fused into the
        # einsum gather); every page then holds 2-4x more tokens per HBM
        # byte, multiplying prefix-cache capacity and slots-per-chip —
        # the allocator, COW rule, radix trie, router affinity and
        # speculation are page-granular and unchanged.
        from flexflow_tpu.ops.attention import kv_storage_dtype

        kv_raw = (kv_cache_dtype if kv_cache_dtype is not None
                  else getattr(cfg, "kv_cache_dtype", "native"))
        kv_storage_dtype(kv_raw)  # validate early (incl. the fp8 gate)
        self._kv_dtype_arg = (None if kv_raw in (None, "", "native")
                              else kv_raw)

        # Generator supplies graph validation, the graph walk and prefill
        # — serving adds scheduling, the paged pool and the PER-SLOT
        # sampler (ops/sampling.py) around them, so the Generator's own
        # engine-wide sampler is never used by serving programs
        self.gen = Generator(model, temperature=0.0, top_k=0,
                             eos_id=eos_id, pad_id=pad_id, quantize=quantize)
        self.eos_id = eos_id
        self.pad_id = pad_id
        cdtype = self.gen._compute_dtype()
        if self._kv_dtype_arg is None:
            self.kv_cache_dtype = jnp.dtype(cdtype).name
        elif kv_raw == "bf16":
            self.kv_cache_dtype = "bfloat16"
        else:
            self.kv_cache_dtype = kv_raw
        if self.gen.quantize:
            # quantize once at engine init: the cached quantized tree is
            # what every program traces against — admission/decode never
            # pays the quantization pass, and the params cache cannot
            # invalidate mid-stream
            self.gen._quantized_params()
        # the pool is COMMITTED (replicated on the model's mesh) up front:
        # an uncommitted fresh pool has a different pjit signature
        # (UnspecifiedValue) than the committed arrays every program
        # RETURNS, so the second call to each warm program would silently
        # retrace and recompile it — a ~0.5 s stall in the serving loop
        # that the recompile counter could not see
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(model.mesh, PartitionSpec())
        self.pool = {
            op.name: jax.tree.map(
                lambda a: jax.device_put(a, repl),
                op.init_paged_cache(self.num_pages, self.page_size,
                                    cdtype, kv_dtype=self._kv_dtype_arg))
            for op in self.gen.attn_ops}
        self._free_pages = list(range(self.num_pages - 1, 0, -1))

        # pool-capacity observability (the router/bench signals ROADMAP
        # item 1 calls for), computed once — the pool's geometry is fixed
        # for the engine's life. The bf16 reference prices the SAME
        # geometry at 2 bytes/element, so kv_capacity_vs_bf16 is exactly
        # the capacity multiplier a quantized pool buys at equal HBM.
        self._pool_bytes = sum(
            int(a.nbytes) for a in jax.tree_util.tree_leaves(self.pool))
        self._kv_bytes_per_token = (
            self._pool_bytes / (self.num_pages * self.page_size))
        self._bf16_bytes_per_token = sum(
            op.num_kv_heads * (op.qk_head_dim + op.v_head_dim) * 2
            for op in self.gen.attn_ops)

        # decode attention impl over the paged pool: the per-engine
        # override wins, else FFConfig.paged_attention_impl; resolved
        # ONCE here ("auto" -> the backend's concrete choice) so every
        # program this engine builds, and stats(), agree on it. Under
        # "auto" a MEASURED winner persisted by search/kernel_tune.py's
        # tune_paged_attention for this engine's exact (page geometry,
        # heads, pool dtype) overrides the backend heuristic — the
        # paper's measured-costs-over-heuristics rule applied to impl
        # choice. The einsum page-gather stays the parity oracle —
        # greedy streams are token-identical either way
        # (tests/test_pallas_paged.py).
        from flexflow_tpu.ops.attention import resolve_paged_attention_impl

        requested = (paged_attention_impl
                     if paged_attention_impl not in (None, "")
                     else getattr(cfg, "paged_attention_impl", "auto")
                     or "auto")
        self.paged_attention_impl = resolve_paged_attention_impl(
            requested, cfg)
        from flexflow_tpu.search import kernel_tune

        # snapshot the autotune-table counter baseline BEFORE the
        # construction-time impl lookup below, so stats() shows that
        # lookup too — the bench stamps it as proof the dtype-keyed
        # entry governed an 'auto' engine
        self._ktune_base = kernel_tune.stats()
        if requested == "auto":
            op0 = self.gen.attn_ops[0]
            tuned = kernel_tune.lookup_paged_impl(
                page_size=self.page_size,
                pages_per_slot=self.pages_per_slot,
                head_dim=op0.qk_head_dim,
                dtype=self.pool[op0.name]["k"].dtype,
                batch=self.slots, heads=op0.num_heads)
            if tuned is not None:
                self.paged_attention_impl = tuned
        # prefill/append page-scatter impl (ISSUE 18): the same knob
        # routes the KV WRITE path — "pallas" scatters pages to the pool
        # from VMEM one page at a time (ops/pallas_kernels.py
        # paged_prefill_write_pallas), "einsum" is the whole-slab
        # dynamic-update scatter and stays the parity oracle (prefill
        # writes are bitwise identical either way; tests pin it). Under
        # "auto" a measured tune_paged_prefill winner for this engine's
        # shape overrides the backend default, same as decode above.
        self.paged_prefill_impl = resolve_paged_attention_impl(
            requested, cfg)
        if requested == "auto":
            op0 = self.gen.attn_ops[0]
            tuned_pf = kernel_tune.lookup_paged_prefill_impl(
                page_size=self.page_size,
                pages_per_slot=self.pages_per_slot,
                head_dim=op0.qk_head_dim,
                dtype=self.pool[op0.name]["k"].dtype,
                batch=self.slots, heads=op0.num_heads)
            if tuned_pf is not None:
                self.paged_prefill_impl = tuned_pf
        fflogger.info(
            "serving: paged decode attention impl=%s prefill impl=%s "
            "kv_cache_dtype=%s "
            "weight_dtype=%s (%.1f KV bytes/token, %.2fx bf16 capacity)",
            self.paged_attention_impl, self.paged_prefill_impl,
            self.kv_cache_dtype,
            self.weight_dtype, self._kv_bytes_per_token,
            self._bf16_bytes_per_token / self._kv_bytes_per_token)

        # radix prefix cache: page-granular prompt-prefix sharing with
        # copy-on-write allocation (shared pages are read-only; every
        # tail/decode write goes to the request's own fresh pages).
        # enable_prefix was resolved above, before the kv_pages derive.
        # tiered prefix cache (ISSUE 12): host_kv_pages > 0 gives the
        # trie a pinned host-memory second tier — ref-0 pages evicted
        # under pool pressure demote (async ordered D2H) instead of
        # dying, and a match against a host-resident edge promotes the
        # payload back (H2D through the same compiled page writer the
        # fleet handoff uses). The effective shared-prefix corpus is
        # then host-RAM-sized.
        hp = int(host_kv_pages if host_kv_pages is not None
                 else getattr(cfg, "host_kv_pages", 0))
        if hp < 0:
            raise ValueError(f"host_kv_pages={hp}: must be >= 0")
        if hp and not enable_prefix:
            raise ValueError(
                "host_kv_pages > 0 needs the radix prefix cache: the "
                "host tier lives UNDER the trie (prefix_cache=False "
                "engines have nothing to demote)")
        self.host_kv_pages = hp
        self.prefix_cache = (RadixPrefixCache(
            self.page_size, host_pages=hp,
            d2h=self._page_d2h, h2d=self._page_h2d)
            if enable_prefix else None)

        # speculative decoding: a draft model proposes K greedy tokens
        # per slot; one fixed-shape verify program scores all K+1
        # positions in a single dispatch. Greedy-only: every emitted
        # token is the TARGET's argmax, so the stream is token-identical
        # to non-speculative decode by construction.
        self.speculate_k = int(speculate_k if speculate_k is not None
                               else getattr(cfg, "serve_speculate_k", 0))
        self.draft_model = (draft_model if draft_model is not None
                            else getattr(cfg, "draft_model", None))
        if self.speculate_k < 0:
            raise ValueError(
                f"speculate_k={self.speculate_k}: must be >= 0")
        self.draft_gen = None
        self.draft_pool = None
        if self.speculate_k > 0:
            if self.draft_model is None:
                raise ValueError(
                    "speculate_k > 0 needs a draft model (FFConfig."
                    "draft_model or the draft_model constructor arg): "
                    "speculative decoding verifies a DRAFT's proposals")
            tgt_v = int(model._final_tensor.dims[-1])
            dft_v = int(self.draft_model._final_tensor.dims[-1])
            if tgt_v != dft_v:
                raise ValueError(
                    f"draft/target vocab mismatch: draft emits {dft_v} "
                    f"logits, target {tgt_v} — the accept rule compares "
                    f"token ids, so the vocabularies must be identical")
            self.draft_gen = Generator(
                self.draft_model, temperature=0.0, top_k=0, eos_id=eos_id,
                pad_id=pad_id, quantize=quantize)
            if self.draft_gen.quantize:
                self.draft_gen._quantized_params()  # once, at init
            ddtype = self.draft_gen._compute_dtype()
            drepl = NamedSharding(self.draft_model.mesh, PartitionSpec())
            # the draft pool mirrors the target pool's page GEOMETRY,
            # page IDS and storage dtype (its own KVH/Dh): one
            # allocator, one page table, one radix trie govern both — a
            # shared prefix page id means target AND draft prefix KV
            # are both resident
            self.draft_pool = {
                op.name: jax.tree.map(
                    lambda a: jax.device_put(a, drepl),
                    op.init_paged_cache(self.num_pages, self.page_size,
                                        ddtype,
                                        kv_dtype=self._kv_dtype_arg))
                for op in self.draft_gen.attn_ops}

        # ---- paged LoRA adapter pool (ISSUE 14) ----
        # fixed-geometry adapter pages mirroring the KV pool's design: a
        # host allocator/LRU (runtime/lora.py) decides residency, ONE
        # fixed-shape writer program faults adapters in, and the slot
        # program gathers each slot's adapter page (page 0 = null
        # adapter) into batched segmented LoRA matmuls — N tenants, one
        # replica, zero recompiles.
        app = int(adapter_pool_pages if adapter_pool_pages is not None
                  else getattr(cfg, "serve_adapter_pool_pages", 0))
        if app < 0:
            raise ValueError(
                f"adapter_pool_pages={app}: must be >= 0 (0 = no "
                f"adapter pool)")
        self.adapter_pool_pages = app
        self.lora = None
        self.lora_pool = None
        self.lora_rank = int(lora_rank if lora_rank is not None
                             else getattr(cfg, "serve_lora_rank", 8))
        if app > 0:
            from flexflow_tpu.ffconst import OperatorType
            from flexflow_tpu.ops import lora as lora_ops

            targets = [op for op in model.ops
                       if op.op_type == OperatorType.OP_LINEAR]
            if lora_targets is not None:
                want = set(lora_targets)
                unknown = want - {op.name for op in targets}
                if unknown:
                    raise ValueError(
                        f"lora_targets {sorted(unknown)} are not Linear "
                        f"ops of this graph (Linear ops: "
                        f"{sorted(op.name for op in targets)})")
                targets = [op for op in targets if op.name in want]
            if not targets:
                raise ValueError(
                    "adapter_pool_pages > 0 but the graph has no "
                    "LoRA-targetable Linear ops")
            self._lora_ops = lora_ops
            self._lora_targets = targets
            self.lora = LoraAdapterPool(app, self.lora_rank, targets)
            self.lora_pool = jax.tree.map(
                lambda a: jax.device_put(a, repl),
                lora_ops.init_lora_pool(targets, app, self.lora_rank))
            self._zero_payload = lora_ops.zero_payload(targets,
                                                       self.lora_rank)

        # per-slot scheduler state (host side, shipped to device each step)
        n = self.slots
        self.page_tables = np.zeros((n, self.pages_per_slot), np.int32)
        self.row_len = np.zeros((n,), np.int32)
        self.prompt_pad = np.zeros((n,), np.int32)
        self.emitted = np.zeros((n,), np.int32)
        self.last_tok = np.zeros((n,), np.int32)
        self.active = np.zeros((n,), bool)
        self.poison = np.zeros((n,), np.float32)
        self.slot_req: List[Optional[Request]] = [None] * n
        # slot-resident sampling state (ISSUE 14): just more per-slot
        # scalars, like write_pos — idle slots sit at the greedy
        # defaults and their draws are discarded with the scratch writes
        self.temps = np.zeros((n,), np.float32)
        self.top_ps = np.ones((n,), np.float32)
        self.top_ks = np.zeros((n,), np.int32)
        self.seeds = np.zeros((n,), np.int32)
        # per-slot adapter-pool page (0 = null adapter)
        self.lora_pages = np.zeros((n,), np.int32)
        self._vocab = int(model._final_tensor.dims[-1])

        self._queue: List[Request] = []
        self._draining = False
        # mid-prefill slots (ISSUE 18): slot -> partial-prefill state
        # (request, chunked caches so far, next chunk start, padded
        # tokens). The slot is HELD (slot_req set) but inactive, so
        # decode dispatches clamp its writes to scratch page 0; the
        # state survives the scheduler loop until _finish_prefill flips
        # the slot active. _prefill_rr round-robins chunk budget across
        # mid-prefill slots so two long prompts make equal progress.
        self._partial: Dict[int, dict] = {}
        self._prefill_rr = 0
        # rolling-deploy identity (ISSUE 17): the weight version this
        # engine serves (salts cache namespaces + affinity keys via
        # version_ns) and where it stands in a roll —
        # "serving" | "draining" | "swapping" | "canary". Both ride
        # stats()/health()/telemetry.
        self.weight_version = DEFAULT_WEIGHT_VERSION
        self.deploy_state = "serving"
        self._weight_swaps = 0
        self._programs: Dict = {}
        # ffsan retrace sentinel: warmup() closes the program set;
        # armed + sanitize on, _compiled_call reports any further
        # jit cache miss with the argument signature that diverged
        self._retrace = locks.RetraceSentinel()
        self._key = jax.random.PRNGKey(seed)
        self._next_rid = 0
        # ONE engine lock around every queue/slot/counter mutation so a
        # router can drive this replica from its own thread while other
        # threads submit(), probe health() or snapshot stats(). Reentrant:
        # step() holds it across the whole tick (including the device
        # dispatch) and calls locked helpers underneath — cross-thread
        # callers simply serialize behind the tick.
        self._lock = locks.make_rlock("engine")
        self.recompile_count = 0
        self.decode_steps = 0
        self._occupancy_sum = 0
        # aggregate counters instead of retaining every Request: a
        # long-lived engine must not grow memory with total traffic.
        # Retired Request objects are dropped (callers keep their own
        # handles — submit()/run() return them); TTFT percentiles come
        # from a bounded window of recent completions
        self._submitted = 0
        self._completed = 0
        self._failed = 0
        self._timeouts = 0      # expired while queued, never dispatched
        self._tokens_emitted = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_dispatches = 0
        # disaggregated-fleet counters (ISSUE 12): prefill-only
        # admissions run for the role split, page slabs exported to /
        # imported from peer replicas, and the pages those imports wrote
        self._prefill_only = 0
        self._slab_exports = 0
        self._slab_imports = 0
        self._import_pages = 0
        # long-context counters (ISSUE 18): prefill chunks run
        # interleaved with decode ticks, ticks where a mid-prefill slot
        # still had chunks left when the per-tick budget ran out, and
        # partial-prefix slab imports (start_page > 0 merges from
        # sequence-parallel prefill shards)
        self._prefill_chunks_interleaved = 0
        self._prefill_preempted_ticks = 0
        self._partial_slab_imports = 0
        # decode-attention observability (ISSUE 7 satellite): pool pages
        # the attention body READS per dispatch (sum over active slots
        # of the final-step frontier's page count — what the pallas
        # kernel streams / the einsum path gathers), plus a snapshot
        # baseline for the kernel-tune table counters. The counters are
        # PROCESS-GLOBAL (lookups fire inside kernel traces, which have
        # no engine identity), so stats() reports the process's
        # consultations since THIS engine was constructed — exact when
        # the engine is the only tracer (the usual serving process),
        # approximate when training or a second engine traces alongside
        self._pages_touched = 0
        self._last_pages_touched = 0
        # (the kernel-tune counter baseline _ktune_base is snapshotted
        # in the impl-resolution block above, before the construction-
        # time table lookup)
        import collections

        self._ttfts = collections.deque(maxlen=4096)
        # per-adapter ledgers (ISSUE 14 telemetry satellite): requests,
        # spec proposals/accepts — keyed by adapter label ("none" for
        # base-model traffic); bounded by the registry, not by traffic
        self._adapter_requests: Dict[str, int] = {}
        self._adapter_spec: Dict[str, List[int]] = {}
        self._sampled_requests = 0
        if self.lora is not None:
            # compile + run the one fixed-shape adapter writer NOW
            # (writing the null page's zeros is a no-op): every later
            # fault-in of a real adapter reuses this program, so tenant
            # churn never compiles — and recompile-flatness tests see
            # the build at construction, outside any warm window
            self._write_adapter_page(0, self._zero_payload, 0.0)

        # ---- unified telemetry plane (ISSUE 13) ----
        # the engine's latency histograms (TTFT / inter-token / queue
        # wait) are observed at the event sites below; everything
        # stats() already counts is exported by the scrape-time
        # collector (_tm_collect), so the ad-hoc dict and the registry
        # can never disagree — the dict IS the collector's source.
        # FFConfig.telemetry="off" skips every emit at one predicate.
        self._tm_on = getattr(cfg, "telemetry", "on") != "off"
        self._tm_labels = {"replica": f"engine{next(_ENGINE_IDS)}",
                           "role": "solo"}
        self._retrace.owner = self._tm_labels["replica"]
        self._tm_ch: Dict = {}
        # flight recorder + SLO plane adopt the config's knobs
        # UNCONDITIONALLY: configure() is how telemetry="off" reaches
        # the recorder's own gate — skipping it when off would leave an
        # env-configured FF_FLIGHT_DIR recorder live under an "off"
        # config
        flightrec.configure(cfg)
        if self._tm_on:
            if getattr(cfg, "metrics_port", 0):
                telemetry.start_http_server(cfg.metrics_port)
            self._tm_bind_children()
            telemetry.registry().add_collector(self._tm_collect)
            # ISSUE 15: register this engine as a post-mortem bundle
            # source (stats/health snapshot), an HBM-ledger source (KV
            # pool incl. host tier, adapter pool, quantized serving
            # weights), an SLO ratio source (prefix-hit / spec-accept
            # window floors) and a lock-free health probe for /healthz
            # — all weakly referenced, same off predicate
            flightrec.recorder().attach_source(self._flightrec_source)
            flightrec.hbm_ledger().add_source(self._hbm_source)
            flightrec.slo_monitor().add_source(self._slo_source)
            flightrec.register_health_source(self._health_probe)

    # ---- telemetry ----------------------------------------------------------

    def set_telemetry_identity(self, replica, role: str):
        """Fleet identity for this engine's metric labels and trace
        track (the router stamps replica index + role at construction;
        standalone engines keep their process-unique engine id). The
        scrape topology is one fleet per process — a second router's
        replica 0 shares the first's labeled series
        (docs/observability.md)."""
        self._tm_labels = {"replica": str(replica), "role": str(role)}
        if self._tm_on:
            self._tm_bind_children()

    def _tm_bind_children(self):
        """Resolve the hot-path histogram children ONCE per identity:
        per-token emits then cost a single predicate + one lock-cheap
        observe — no registry/family lookup, no label-tuple build."""
        reg = telemetry.registry()
        lab = (self._tm_labels["replica"], self._tm_labels["role"])
        self._tm_ch = {
            "ttft": reg.histogram(
                "ff_serving_ttft_seconds",
                "engine submit -> first token",
                labels=("replica", "role")).labels(*lab),
            "itl": reg.histogram(
                "ff_serving_intertoken_seconds",
                "gap between consecutive emitted tokens",
                labels=("replica", "role")).labels(*lab),
            "queue": reg.histogram(
                "ff_serving_queue_wait_seconds",
                "engine queue wait: submit -> admission",
                labels=("replica", "role")).labels(*lab),
        }
        # per-adapter families (ISSUE 14): children resolved lazily per
        # adapter label and cached (bounded by the adapter registry)
        self._tm_fam_req = reg.counter(
            "ff_serving_requests_total",
            "requests submitted, labeled by LoRA adapter "
            "('none' = base model)",
            labels=("replica", "role", "adapter"))
        self._tm_fam_attft = reg.histogram(
            "ff_serving_adapter_ttft_seconds",
            "engine submit -> first token, labeled by LoRA adapter",
            labels=("replica", "role", "adapter"))
        self._tm_adapter_ch = {}

    def _tm_adapter(self, adapter: Optional[str]):
        key = adapter or "none"
        ch = self._tm_adapter_ch.get(key)
        if ch is None:
            lab = (self._tm_labels["replica"], self._tm_labels["role"],
                   key)
            ch = self._tm_adapter_ch[key] = (
                self._tm_fam_req.labels(*lab),
                self._tm_fam_attft.labels(*lab))
        return ch

    @property
    def _tm_track(self) -> str:
        return f"replica{self._tm_labels['replica']}"

    def _tm_collect(self, reg):
        """Scrape-time collector: publish every numeric stats() key as
        a ``ff_serving_<key>`` gauge labeled (replica, role), plus one
        info series carrying the engine's dtype/impl identity. stats()
        serializes behind a running tick — scrapes are rare and the
        snapshot is exact."""
        st = self.stats()
        lab = (self._tm_labels["replica"], self._tm_labels["role"])
        for k, v in st.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            reg.gauge(f"ff_serving_{k}",
                      f"ServingEngine stats()['{k}']",
                      labels=("replica", "role")).labels(*lab).set(v)
        reg.gauge("ff_serving_engine_info",
                  "engine identity (value is always 1)",
                  labels=("replica", "role", "kv_cache_dtype",
                          "weight_dtype", "impl")).labels(
            *lab, st["kv_cache_dtype"], st["weight_dtype"],
            st["paged_attention_impl"]).set(1)
        # rolling-deploy identity (ISSUE 17): the string-valued version
        # and deploy state ride a labeled info gauge (value always 1) —
        # the numeric loop above only exports numbers
        reg.gauge("ff_replica_weight_version",
                  "weight version + deploy state per replica "
                  "(value is always 1)",
                  labels=("replica", "role", "version", "state")).labels(
            *lab, st["weight_version"], st["deploy_state"]).set(1)
        # per-adapter speculation accept rate (ISSUE 14): one labeled
        # series per adapter that has seen speculative traffic
        if self._adapter_spec:
            fam = reg.gauge(
                "ff_serving_spec_accept_rate_by_adapter",
                "speculative accept rate, labeled by LoRA adapter",
                labels=("replica", "role", "adapter"))
            with self._lock:
                rows = {k: (v[0], v[1])
                        for k, v in self._adapter_spec.items()}
            for name, (prop, acc) in rows.items():
                fam.labels(*lab, name).set(
                    round(acc / max(1, prop), 4))

    # ---- flight recorder / SLO / HBM sources (ISSUE 15) ---------------------

    def _flightrec_source(self):
        """Post-mortem bundle payload: the full stats/health snapshot.
        Takes the engine lock — the recorder collects sources with a
        per-source timeout, so a wedged replica yields an error row in
        its own incident's bundle instead of hanging the write."""
        return (f"engine-{self._tm_labels['replica']}",
                {"stats": self.stats(), "health": self.health()})

    def _slo_source(self):
        """Lock-free counter reads for the ratio-floor SLOs (windowed
        prefix hit rate / speculative accept rate). Plain int attribute
        reads racing the tick by design — a monitoring window tolerates
        one tick of skew; a monitor stalled behind the tick does not."""
        pc = self.prefix_cache
        return (self._tm_labels["replica"], {
            "prefix_hits": pc.hits if pc else 0,
            "prefix_lookups": pc.lookups if pc else 0,
            "spec_accepted": self._spec_accepted,
            "spec_proposed": self._spec_proposed})

    def _hbm_source(self):
        """HBM ledger row: what this engine holds in device (and pinned
        host) memory, per subsystem — the per-pool resolution the
        memory-objective search consumes. Geometry is fixed for the
        engine's life, so these are cheap nbytes sums."""
        import jax as _jax

        def _nbytes(tree):
            return sum(int(a.nbytes)
                       for a in _jax.tree_util.tree_leaves(tree))

        subs = {"kv_pool": self._pool_bytes}
        pc = self.prefix_cache
        if pc is not None and pc.host_pages:
            page_bytes = self._pool_bytes / max(1, self.num_pages)
            subs["kv_host_tier"] = int(pc.host_used * page_bytes)
        if self.draft_pool is not None:
            subs["kv_draft_pool"] = _nbytes(self.draft_pool)
        if self.lora_pool is not None:
            subs["adapter_pool"] = _nbytes(self.lora_pool)
        if self.gen.quantize:
            # a quantized serving copy is a SEPARATE device allocation
            # (native-weight serving reads the model params, which the
            # model's own ledger row counts — never double-book)
            subs["serve_weights"] = _nbytes(self.gen._quantized_params())
        dg = getattr(self, "draft_gen", None)
        if dg is not None and dg.quantize:
            subs["draft_weights"] = _nbytes(dg._quantized_params())
        return (f"engine-{self._tm_labels['replica']}", subs)

    def _health_probe(self):
        """Lock-free /healthz row: never compiles, never blocks behind
        a mid-tick replica (the load() discipline)."""
        return {"kind": "engine",
                "replica": self._tm_labels["replica"],
                "role": self._tm_labels["role"],
                "status": "draining" if self._draining else "up",
                "weight_version": self.weight_version,
                "deploy_state": self.deploy_state,
                **self.load()}

    # ---- request lifecycle --------------------------------------------------

    def _bucket(self, prompt_len: int) -> int:
        if self.buckets:
            for b in self.buckets:
                if b >= prompt_len:
                    return b
            raise ValueError(
                f"prompt length {prompt_len} exceeds the largest decode "
                f"bucket {self.buckets[-1]}")
        return _pow2_bucket(prompt_len)

    def submit(self, prompt, max_new_tokens: int,
               deadline: Optional[float] = None,
               trace_id: Optional[str] = None,
               temperature: Optional[float] = None,
               top_p: Optional[float] = None,
               top_k: Optional[int] = None,
               seed: Optional[int] = None,
               adapter: Optional[str] = None) -> Request:
        """Queue one request. ``deadline`` is an absolute
        ``time.perf_counter()`` instant: a request still queued past it
        retires as ``"timeout"`` without ever prefilling (an admitted
        request is never cancelled — see Request.deadline).
        ``trace_id`` threads an existing fleet trace through this
        engine's spans (the router passes its request id, so a
        resubmitted or handed-off request keeps ONE span tree); None
        mints an engine-local id."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens}: must be >= 1")
        bucket = self._bucket(prompt.size)
        if bucket + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"bucketed prompt ({bucket}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len {self.max_seq_len}")
        t, p, k = sampling_ops.validate_sampling(
            temperature if temperature is not None
            else self.default_temperature,
            top_p if top_p is not None else self.default_top_p,
            top_k if top_k is not None else self.default_top_k,
            "submit")
        if adapter is not None:
            if self.lora is None:
                raise ValueError(
                    f"adapter={adapter!r}: this engine has no adapter "
                    f"pool (build with adapter_pool_pages > 0 / "
                    f"--serve-adapter-pool-pages)")
            if adapter not in self.lora.registry:
                raise ValueError(
                    f"adapter {adapter!r} is not registered (known: "
                    f"{sorted(self.lora.registry)}) — register_adapter"
                    f" first")
        with self._lock:
            if self._draining:
                # the serving-side preemption notice: a draining engine is
                # on its way down (elastic restart / deploy) — callers
                # must route new traffic elsewhere, not queue behind a
                # shutdown
                raise RuntimeError(
                    "ServingEngine is draining: new requests are not "
                    "admitted (health()['status'] exposes this to the "
                    "router)")
            req = Request(rid=self._next_rid, prompt=prompt,
                          max_new_tokens=int(max_new_tokens), bucket=bucket,
                          deadline=deadline, t_submit=time.perf_counter(),
                          temperature=t, top_p=p, top_k=k,
                          seed=(int(seed) if seed is not None
                                else (self._seed_base + self._next_rid)
                                & 0x7FFFFFFF),
                          adapter=adapter)
            req.trace_id = trace_id or (
                f"{self._tm_labels['replica']}-r{req.rid}")
            self._next_rid += 1
            self._submitted += 1
            if t > 0.0:
                self._sampled_requests += 1
            akey = adapter or "none"
            self._adapter_requests[akey] = \
                self._adapter_requests.get(akey, 0) + 1
            if self._tm_on:
                self._tm_adapter(adapter)[0].inc()
            self._queue.append(req)
        return req

    def pending(self) -> bool:
        with self._lock:
            return bool(self._queue) or bool(self.active.any()) \
                or bool(self._partial)

    def _retire(self, slot: int, state: str, error: str = ""):
        req = self.slot_req[slot]
        req.state = state
        req.error = error
        req.t_done = time.perf_counter()
        if state == "done":
            self._completed += 1
        elif state == "timeout":
            # a mid-prefill slot whose deadline expired before its last
            # chunk ran (ISSUE 18) — never decoded, same bucket as
            # queue-expiry
            self._timeouts += 1
        else:
            self._failed += 1
        # drop any partial-prefill state (mid-prefill abort: the chunked
        # caches are device arrays — releasing the reference frees them)
        self._partial.pop(slot, None)
        if req.ttft:
            self._ttfts.append(req.ttft)
        # close the cross-thread decode span (0-handle = telemetry off)
        telemetry.tracer().end(req.decode_span, state=state,
                               tokens=len(req.tokens),
                               **({"error": error} if error else {}))
        req.decode_span = 0
        # COW teardown: pages the trie owns (matched prefix + the pages
        # this request published) are DECREF'd — they stay cached, warm
        # for the next hit, until the evictor needs them. Only the
        # request's private pages (partial prompt page, bucket padding,
        # decode appends) return to the free list.
        if req.trie_nodes:
            self.prefix_cache.release(req.trie_nodes)
            req.trie_nodes = []
        self._free_pages.extend(req.private_pages)
        req.private_pages = []
        # unpin the adapter page (it stays RESIDENT, warm for the
        # tenant's next request, until adapter-pool pressure evicts it)
        if req.adapter is not None and self.lora is not None:
            self.lora.release(req.adapter)
        req.slot = -1
        self.slot_req[slot] = None
        self.active[slot] = False
        self.poison[slot] = 0.0
        self.page_tables[slot, :] = 0   # scratch page: dead writes land there
        self.row_len[slot] = 0
        self.prompt_pad[slot] = 0
        self.emitted[slot] = 0
        # idle-slot sampling state back to the greedy defaults
        self.temps[slot] = 0.0
        self.top_ps[slot] = 1.0
        self.top_ks[slot] = 0
        self.seeds[slot] = 0
        self.lora_pages[slot] = 0

    def _record_token(self, slot: int, tok: int, ok: bool):
        """Append a sampled token to the slot's request and retire on
        non-finite logits, eos, or length — shared by prefill/decode."""
        req = self.slot_req[slot]
        if not ok:
            self._retire(slot, "failed", "non-finite logits")
            return
        req.tokens.append(int(tok))
        self._tokens_emitted += 1
        now = time.perf_counter()
        if not req.ttft:
            req.ttft = now - req.t_submit
            if self._tm_on:
                self._tm_ch["ttft"].observe(req.ttft)
                self._tm_adapter(req.adapter)[1].observe(req.ttft)
        elif self._tm_on:
            # host-observed inter-token latency: tokens inside one
            # decode_chunk dispatch arrive together, so sub-chunk gaps
            # read ~0 and the chunk boundary carries the dispatch time —
            # the histogram measures what a streaming caller would see
            self._tm_ch["itl"].observe(now - req.t_last_tok)
        req.t_last_tok = now
        self.emitted[slot] += 1
        self.last_tok[slot] = tok
        if (self.eos_id is not None and tok == self.eos_id) \
                or len(req.tokens) >= req.max_new_tokens:
            self._retire(slot, "done")

    # ---- compiled programs --------------------------------------------------

    def _compiled_call(self, key, build, *args):
        """Program-cache lookup; a miss builds + runs the program and
        bumps recompile_count, logging whether jax's persistent
        compilation cache (FFConfig.compilation_cache_dir) absorbed the
        compile. Every shape-affecting datum is part of `key`, so this
        counter is exactly the number of XLA compiles the engine caused."""
        fn = self._programs.get(key)
        if fn is not None:
            # armed sentinel: bracket the dispatch with the jitted
            # callable's trace-cache size — growth means a WARM
            # program silently retraced (the PR-3/7/10/11 bug class)
            return self._retrace.call(key, fn, args)
        self._retrace.note_miss(key, args)
        fn = self._programs[key] = build()
        self.recompile_count += 1
        cache_dir = getattr(self.model.config, "compilation_cache_dir", "")
        before = compilation_cache_entries(cache_dir) if cache_dir else 0
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        if cache_dir:
            grew = compilation_cache_entries(cache_dir) - before
            fflogger.info(
                "serving: compiled %r in %.2fs — persistent cache %s",
                key, dt, f"MISS (+{grew} entries)" if grew > 0 else "HIT")
        else:
            fflogger.info("serving: compiled %r in %.2fs", key, dt)
        return out

    @staticmethod
    def _seed_prefix_caches(gen, bucket: int, p0: int, pool, prefix_pages):
        """Gather ``p0`` positions of cached prefix KV READ-ONLY into
        the front of a fresh contiguous per-request cache for every
        attention op — the shared half of every hit prefill. Quantized
        pools dequantize in the gather (op.gather_paged_kv), so the
        borrower attends exactly the lossy values the donor's decode
        sees. Target and draft builders use this one helper so the two
        pools (which share page ids) can never drift apart."""
        cdtype = gen._compute_dtype()
        caches = {}
        for op in gen.attn_ops:
            c = op.init_cache(1, bucket, cdtype)
            g = op.gather_paged_kv(pool[op.name], prefix_pages)
            caches[op.name] = {
                name: c[name].at[:, :p0].set(g[name].astype(c[name].dtype))
                for name in ("k", "v")}
        return caches

    def _scatter_tail(self, gen, pool, caches, pages, p0: int = 0):
        """COW scatter: write the contiguous cache's positions past
        ``p0`` into ``pages`` — the request's own fresh pages, never the
        shared ones. ``p0=0`` is the cold (whole-bucket) case. Routed
        through the engine's resolved prefill impl: 'einsum' is the
        big-scatter oracle, 'pallas' the page-at-a-time VMEM kernel
        (ISSUE 18); both are bitwise-identical so the choice is purely
        a perf knob — resolution happens at TRACE time inside the
        prefill builders, warm programs pay nothing."""
        impl = getattr(self, "paged_prefill_impl", "einsum")
        return {
            op.name: op.paged_prefill_write(
                pool[op.name], caches[op.name]["k"][:, p0:],
                caches[op.name]["v"][:, p0:], pages, impl=impl)
            for op in gen.attn_ops}

    # ---- page migration primitives (tier + fleet handoff, ISSUE 12) ------

    def _page_d2h(self, pages):
        """Start the async D2H snapshot of a LIST of pool pages — ONE
        gather per pool array covers a whole demotion sweep or slab
        export; target AND draft pools (they share page ids), quantized
        scales included. Returns the resolver the ordered publisher (or
        a synchronous export) calls for the per-page payload list. The
        gathers are enqueued BEFORE any page can be reused, and device
        programs execute in order (the PR-9 snapshot-before-donate
        rule), so the HBM pages free immediately."""
        # FIXED gather width: eager jax ops compile per shape, so a
        # per-sweep-sized index would compile a fresh gather executable
        # every time the eviction need changes (~100 ms each on CPU —
        # measured as the whole tier overhead). Chunk to pages_per_slot
        # rows padded with scratch page 0; the pad payloads are dropped
        # at resolve.
        cap = self.pages_per_slot
        n = len(pages)
        chunks = []
        for i in range(0, n, cap):
            idx = np.zeros((cap,), np.int32)
            part = pages[i:i + cap]
            idx[:len(part)] = part
            chunks.append(idx)
        parts = []
        for idx in chunks:
            sub = {}
            for op in self.gen.attn_ops:
                sub[("t", op.name)] = op.export_page(
                    self.pool[op.name], idx)
            if self.draft_pool is not None:
                for op in self.draft_gen.attn_ops:
                    sub[("d", op.name)] = op.export_page(
                        self.draft_pool[op.name], idx)
            parts.append(sub)
        for sub in parts:
            for arrs in sub.values():
                for a in arrs.values():
                    try:
                        a.copy_to_host_async()
                    except (AttributeError, RuntimeError):
                        pass    # no async copy: resolve() blocks

        def resolve():
            out = []
            for ci, sub in enumerate(parts):
                host = {key: {name: np.asarray(a)
                              for name, a in arrs.items()}
                        for key, arrs in sub.items()}
                rows = min(cap, n - ci * cap)
                out.extend(
                    {key: {name: arr[i] for name, arr in arrs.items()}
                     for key, arrs in host.items()}
                    for i in range(rows))
            return out

        return resolve

    def _page_h2d(self, pages, payloads):
        """Write migrated/handed-off page payloads back into the pools —
        ONE fixed-shape compiled writer serves EVERY promotion and
        handoff import: batches are padded to ``pages_per_slot`` rows
        with scratch page 0 (+ zero payload — the pool's designated
        garbage page absorbs the pad writes), so the program is
        count-independent and the tier/handoff hot paths compile nothing
        per page. Payload bytes land verbatim (scales ride along): the
        imported pages are BITWISE the donor's."""
        cap = self.pages_per_slot
        for i in range(0, len(pages), cap):
            self._page_h2d_chunk(pages[i:i + cap], payloads[i:i + cap])

    def _page_h2d_chunk(self, pages, payloads):
        have_draft = self.draft_pool is not None
        cap = self.pages_per_slot
        n = len(pages)
        idx = np.zeros((cap,), np.int32)
        idx[:n] = pages
        stacked = {
            key: {name: np.stack(
                [p[key][name] for p in payloads]
                + [np.zeros_like(payloads[0][key][name])] * (cap - n))
                for name in payloads[0][key]}
            for key in payloads[0]}

        def build():
            def write(pool, dpool, payload, pages):
                out = {op.name: op.import_page(pool[op.name], pages,
                                               payload[("t", op.name)])
                       for op in self.gen.attn_ops}
                dout = dpool
                if have_draft:
                    dout = {op.name: op.import_page(
                        dpool[op.name], pages, payload[("d", op.name)])
                        for op in self.draft_gen.attn_ops}
                return out, dout

            return jax.jit(write, donate_argnums=(0, 1))

        self.pool, dp = self._compiled_call(
            ("page_import",), build, self.pool, self.draft_pool, stacked,
            idx)
        if have_draft:
            self.draft_pool = dp

    def _promote_matched(self, matched):
        """Promote the host-resident tail of a matched path HBM-ward,
        root-down (parents first keeps the hbm*-then-host* invariant)
        through ONE batched H2D. The caller has already reserved enough
        free pages. A failed promotion truncates the path there —
        everything past it prefills cold — and unused pages return to
        the free list."""
        host = [n for n in matched if n.tier != "hbm"]
        if not host:
            return matched
        n_hbm = len(matched) - len(host)
        pages = [self._free_pages.pop() for _ in host]
        k = self.prefix_cache.promote_path(host, pages)
        self._free_pages.extend(pages[k:])
        return matched[:n_hbm + k]

    def _build_prefill(self, bucket: int, n_pages: int):
        gen = self.gen
        cdtype = gen._compute_dtype()
        has_lora = self.lora_pool is not None

        def prefill(params, state, tokens, length, pool, pages, poison,
                    temps, top_ps, top_ks, seeds, lora_pool, lora_pages):
            caches = {op.name: op.init_cache(1, bucket, cdtype)
                      for op in gen.attn_ops}
            lora = ({"pool": lora_pool, "pages": lora_pages}
                    if has_lora else None)
            logits, caches = gen._prefill(params, state, tokens, caches,
                                          length, self.prefill_chunk,
                                          lora=lora)
            logits = logits[:, -1] + poison            # (1, V)
            ok = jnp.isfinite(logits).all(axis=-1)
            # the request's first emitted token is TARGET-stream draw 0
            tok = sampling_ops.sample_tokens(
                logits, temps, top_ps, top_ks, seeds,
                jnp.zeros_like(seeds))
            return tok, ok, self._scatter_tail(gen, pool, caches, pages)

        return jax.jit(prefill, donate_argnums=(4,))

    def _build_prefill_hit(self, bucket: int, full: int):
        """Prefix-hit prefill: ``full`` cached pages are gathered
        READ-ONLY into the front of a contiguous per-request cache, the
        tail slab [full*ps, bucket) runs as one chunk_forward pass (each
        tail position attends the gathered prefix + the tail's own causal
        window — bitwise the whole-prompt einsum, runtime/generation.py),
        a gather-last query scores the prompt's true last position, and
        ONLY the tail k/v scatters out — into the request's fresh pages,
        never the shared ones (the copy-on-write rule; the matched
        prefix's partial last page is re-materialized here too)."""
        gen = self.gen
        p0 = full * self.page_size
        has_lora = self.lora_pool is not None

        def prefill(params, state, tokens_tail, tok_last, length, pool,
                    prefix_pages, tail_pages, poison,
                    temps, top_ps, top_ks, seeds, lora_pool, lora_pages):
            lora = ({"pool": lora_pool, "pages": lora_pages}
                    if has_lora else None)
            caches = self._seed_prefix_caches(gen, bucket, p0, pool,
                                              prefix_pages)
            _, caches = gen._walk(params, state, tokens_tail, caches,
                                  None, chunk_start=p0, skip_tail=True,
                                  lora=lora)
            logits, caches = gen._walk(params, state, tok_last, caches,
                                       None, last_only=True,
                                       row_lengths=length,
                                       gather_last=True, lora=lora)
            logits = logits[:, -1] + poison            # (1, V)
            ok = jnp.isfinite(logits).all(axis=-1)
            tok = sampling_ops.sample_tokens(
                logits, temps, top_ps, top_ks, seeds,
                jnp.zeros_like(seeds))
            return tok, ok, self._scatter_tail(gen, pool, caches,
                                               tail_pages, p0)

        return jax.jit(prefill, donate_argnums=(5,))

    def _build_draft_prefill(self, bucket: int, n_pages: int):
        """Cold draft prefill: fill the draft pool's pages for the whole
        bucket. Cache-only (skip_tail) — the draft's first proposal is
        sampled by the draft-decode scan, so its prefill logits are
        never needed."""
        gen = self.draft_gen
        cdtype = gen._compute_dtype()

        def prefill(params, state, tokens, pool, pages):
            caches = {op.name: op.init_cache(1, bucket, cdtype)
                      for op in gen.attn_ops}
            _, caches = gen._walk(params, state, tokens, caches, None,
                                  skip_tail=True)
            return self._scatter_tail(gen, pool, caches, pages)

        return jax.jit(prefill, donate_argnums=(3,))

    def _build_draft_prefill_hit(self, bucket: int, full: int):
        """Prefix-hit draft prefill: same gather + tail-chunk + COW
        scatter as the target's hit program (the shared helpers), minus
        the logits tail."""
        gen = self.draft_gen
        p0 = full * self.page_size

        def prefill(params, state, tokens_tail, pool, prefix_pages,
                    tail_pages):
            caches = self._seed_prefix_caches(gen, bucket, p0, pool,
                                              prefix_pages)
            _, caches = gen._walk(params, state, tokens_tail, caches,
                                  None, chunk_start=p0, skip_tail=True)
            return self._scatter_tail(gen, pool, caches, tail_pages, p0)

        return jax.jit(prefill, donate_argnums=(3,))

    # ---- chunk-interleaved admission programs (ISSUE 18) ------------------

    def _build_prefill_ichunk(self, bucket: int, st: int):
        """ONE schedulable prefill chunk of a cold bucket-shaped prompt:
        positions [st, st+prefill_chunk) write their k/v into the
        contiguous per-request cache, cache-only (skip_tail) — exactly
        iteration ``st`` of Generator._prefill's ragged chunked loop, so
        the chunk sequence is bitwise the run-to-completion prefill. The
        FULL padded (1, bucket) prompt is the input and the chunk slice
        is static, so every chunk of a bucket shares one argument
        signature; st=0 creates the caches, later chunks take + donate
        them (the cursor state the scheduler carries between ticks)."""
        gen = self.gen
        cdtype = gen._compute_dtype()
        has_lora = self.lora_pool is not None
        chunk = self.prefill_chunk

        if st == 0:
            def chunk0(params, state, tokens, lora_pool, lora_pages):
                caches = {op.name: op.init_cache(1, bucket, cdtype)
                          for op in gen.attn_ops}
                lora = ({"pool": lora_pool, "pages": lora_pages}
                        if has_lora else None)
                _, caches = gen._walk(
                    params, state, tokens[:, :chunk], caches, None,
                    chunk_start=0, skip_tail=True, lora=lora)
                return caches

            return jax.jit(chunk0)

        def chunk_fn(params, state, tokens, caches, lora_pool,
                     lora_pages):
            lora = ({"pool": lora_pool, "pages": lora_pages}
                    if has_lora else None)
            _, caches = gen._walk(
                params, state, tokens[:, st:st + chunk], caches, None,
                chunk_start=st, skip_tail=True, lora=lora)
            return caches

        return jax.jit(chunk_fn, donate_argnums=(3,))

    def _build_prefill_ifinal(self, bucket: int, n_pages: int):
        """The last quantum of an interleaved prefill: the ragged
        gather-last pass over the filled chunk caches (the prompt's true
        last position scores the first emitted token), then the COW
        scatter of the whole bucket's k/v into the request's pages —
        Generator._prefill's final _walk plus _build_prefill's sampling
        tail, so (tok, ok, pool) match run-to-completion admission
        bitwise."""
        gen = self.gen
        has_lora = self.lora_pool is not None

        def final(params, state, tokens, length, caches, pool, pages,
                  poison, temps, top_ps, top_ks, seeds, lora_pool,
                  lora_pages):
            lora = ({"pool": lora_pool, "pages": lora_pages}
                    if has_lora else None)
            tok_last = jnp.take_along_axis(
                tokens, (length - 1)[:, None], axis=1)       # (1, 1)
            logits, caches = gen._walk(params, state, tok_last, caches,
                                       None, last_only=True,
                                       row_lengths=length,
                                       gather_last=True, lora=lora)
            logits = logits[:, -1] + poison                  # (1, V)
            ok = jnp.isfinite(logits).all(axis=-1)
            tok = sampling_ops.sample_tokens(
                logits, temps, top_ps, top_ks, seeds,
                jnp.zeros_like(seeds))
            return tok, ok, self._scatter_tail(gen, pool, caches, pages)

        # donate the pool only: the chunk caches feed the scatter but
        # back no output (tok/ok are tiny, pool aliases the pool input),
        # so donating them just trips jax's unusable-donation warning
        return jax.jit(final, donate_argnums=(5,))

    def _build_verify(self, k: int):
        """Speculative verify: ONE dispatch scores all K+1 candidate
        positions per slot — the slab [last_tok, d_1..d_K] flows through
        the target graph with paged_verify_forward writing each
        position's k/v at its own (host-clamped) slot and attending at
        its own frontier. Returns the target's greedy argmax at every
        position, the per-slot WARPED sampling distribution at every
        position (the rejection-sampling ``p`` — one-hot at argmax for
        greedy slots), and per-position finiteness. Acceptance stays
        host-side: greedy slots compare proposals to argmax, sampled
        slots run the accept/resample rule (_spec_step)."""
        gen = self.gen
        has_lora = self.lora_pool is not None

        def verify(params, state, pool, page_table, slab, write_pos,
                   rope_pos0, row_len, prompt_pad, poison,
                   temps, top_ps, top_ks, lora_pool, lora_pages):
            paged = {"page_table": page_table, "write_pos": write_pos,
                     "rope_pos": rope_pos0, "row_len": row_len,
                     "prompt_pad": prompt_pad,
                     "impl": self.paged_attention_impl}
            lora = ({"pool": lora_pool, "pages": lora_pages}
                    if has_lora else None)
            logits, pool = gen._walk(params, state, slab, pool, None,
                                     paged=paged, lora=lora)
            logits = logits.astype(jnp.float32) \
                + poison[:, None, None]                # (B, K+1, V)
            ok = jnp.isfinite(logits).all(axis=-1)     # (B, K+1)
            toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            b, s, v = logits.shape
            probs = sampling_ops.sampling_probs(
                logits.reshape(b * s, v),
                jnp.repeat(temps, s), jnp.repeat(top_ps, s),
                jnp.repeat(top_ks, s)).reshape(b, s, v)
            return toks, probs, ok, pool

        return jax.jit(verify, donate_argnums=(2,))

    def _build_decode(self, n_steps: int):
        gen = self.gen
        has_lora = self.lora_pool is not None

        def decode(params, state, pool, page_table, last_tok, write_pos0,
                   rope_pos0, row_len, prompt_pad, budget, poison,
                   temps, top_ps, top_ks, seeds, ctr0,
                   lora_pool, lora_pages):
            """`n_steps` slot-decode steps as ONE in-graph scan. Past a
            slot's own budget (prompt_pad + its max_new_tokens) the write
            position and RoPE clamp to the final allocated slot — those
            steps only produce tokens the host truncates, and the
            repeated overwrite stays inside the slot's own pages. Step i
            samples TARGET-stream draw ctr0 + i per slot (counter-based:
            no engine key state) and applies each slot's own
            temperature/top-p/top-k — temperature-0 slots take argmax,
            bitwise the greedy program this replaced."""
            rope_cap = budget - prompt_pad + row_len - 1
            lora = ({"pool": lora_pool, "pages": lora_pages}
                    if has_lora else None)

            def body(carry, i):
                pool, tok = carry
                paged = {
                    "page_table": page_table,
                    "write_pos": jnp.minimum(write_pos0 + i, budget - 1),
                    "rope_pos": jnp.minimum(rope_pos0 + i, rope_cap),
                    "row_len": row_len, "prompt_pad": prompt_pad,
                    "impl": self.paged_attention_impl}
                logits, pool = gen._walk(params, state, tok[:, None],
                                         pool, None, paged=paged,
                                         lora=lora)
                logits = logits[:, 0] + poison[:, None]  # (B_slots, V)
                ok = jnp.isfinite(logits).all(axis=-1)
                nxt = sampling_ops.sample_tokens(
                    logits, temps, top_ps, top_ks, seeds, ctr0 + i)
                return (pool, nxt), (nxt, ok)

            (pool, _), (toks, oks) = jax.lax.scan(
                body, (pool, last_tok),
                jnp.arange(n_steps, dtype=jnp.int32))
            return toks, oks, pool                     # (n_steps, B_slots)

        return jax.jit(decode, donate_argnums=(2,))

    def _build_draft_propose(self, n_steps: int):
        """Speculative draft proposals: the draft's own K-step paged
        decode scan, sampling each proposal from the DRAFT stream under
        the REQUEST's sampling config (greedy slots propose argmax —
        the pre-sampling draft decode bitwise), and returning the
        draft's per-step sampling distribution ``q`` — the denominator
        of the host accept rule and the subtrahend of the residual
        resample."""
        gen = self.draft_gen

        def propose(params, state, pool, page_table, last_tok,
                    write_pos0, rope_pos0, row_len, prompt_pad, budget,
                    temps, top_ps, top_ks, seeds, ctr0):
            rope_cap = budget - prompt_pad + row_len - 1

            def body(carry, i):
                pool, tok = carry
                paged = {
                    "page_table": page_table,
                    "write_pos": jnp.minimum(write_pos0 + i, budget - 1),
                    "rope_pos": jnp.minimum(rope_pos0 + i, rope_cap),
                    "row_len": row_len, "prompt_pad": prompt_pad,
                    "impl": self.paged_attention_impl}
                logits, pool = gen._walk(params, state, tok[:, None],
                                         pool, None, paged=paged)
                logits = logits[:, 0].astype(jnp.float32)  # (B, V)
                nxt = sampling_ops.sample_tokens(
                    logits, temps, top_ps, top_ks, seeds, ctr0 + i,
                    tag=sampling_ops.TAG_DRAFT)
                probs = sampling_ops.sampling_probs(
                    logits, temps, top_ps, top_ks)
                return (pool, nxt), (nxt, probs)

            (pool, _), (toks, probs) = jax.lax.scan(
                body, (pool, last_tok),
                jnp.arange(n_steps, dtype=jnp.int32))
            return toks, probs, pool        # (k, B), (k, B, V)

        return jax.jit(propose, donate_argnums=(2,))

    def _split_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    # ---- per-request sampling / adapter plumbing (ISSUE 14) ---------------

    def _sampling_args_1(self, req: Request):
        """(1,)-shaped sampling-state arrays for the prefill programs."""
        return (np.asarray([req.temperature], np.float32),
                np.asarray([req.top_p], np.float32),
                np.asarray([req.top_k], np.int32),
                np.asarray([req.seed], np.int32))

    def _lora_args_1(self, adapter_page: int):
        """(lora_pool, (1,) page) prefill args; (None, None) — empty
        pytrees to jit — when the engine has no adapter pool."""
        if self.lora_pool is None:
            return (None, None)
        return (self.lora_pool, np.asarray([adapter_page], np.int32))

    def _lora_args_slots(self):
        if self.lora_pool is None:
            return (None, None)
        return (self.lora_pool, self.lora_pages)

    def register_adapter(self, name: str, weights: Dict,
                         alpha: Optional[float] = None) -> None:
        """Register a LoRA adapter for multi-tenant serving: host-RAM
        weights ({Linear op name -> {"a": (in, rank), "b": (rank,
        out)}}, ops omitted get a zero delta; scale = alpha / rank,
        alpha defaults to rank). Registration is host-only — the
        adapter faults into a device pool page on its first
        ``submit(adapter=name)`` and stays resident (LRU at refcount 0)
        until pool pressure evicts it. Re-registering REPLACES the
        adapter (rejected while live slots are pinned to it): the old
        device copy is dropped and the adapter's prefix-cache namespace
        is flushed — KV computed under the old weights must never serve
        a hit for the new ones."""
        if self.lora is None:
            raise RuntimeError(
                "this engine has no adapter pool: build with "
                "adapter_pool_pages > 0 (--serve-adapter-pool-pages)")
        with self._lock:
            replacing = name in self.lora.registry
            self.lora.register(name, weights, alpha)
            if replacing and self.prefix_cache is not None:
                self._free_pages.extend(
                    self.prefix_cache.flush_namespace(name))

    def _write_adapter_page(self, page: int, payload: Dict, scale: float):
        """Fault an adapter into pool ``page`` through the ONE
        fixed-shape writer program (``page`` is traced data, so tenant
        churn never compiles; the null-page write at construction
        compiles it once)."""
        buf = {}
        for op in self._lora_targets:
            sub = payload.get(op.name)
            if sub is None:
                sub = self._zero_payload[op.name]
            buf[op.name] = {"a": np.asarray(sub["a"], np.float32),
                            "b": np.asarray(sub["b"], np.float32)}
        lora_ops = self._lora_ops

        def build():
            def write(pool, page, payload, scale):
                return lora_ops.write_adapter_page(pool, page, payload,
                                                   scale)

            return jax.jit(write, donate_argnums=(0,))

        self.lora_pool = self._compiled_call(
            ("adapter_write",), build, self.lora_pool,
            np.int32(page), buf, np.float32(scale))

    # ---- the scheduler loop -------------------------------------------------

    def _expire_queued(self):
        """Retire queued requests whose deadline has passed as "timeout"
        — they never prefill, hold no pages and cost no dispatch (the
        per-request-deadline half of the fleet-router contract: expiring
        work is dropped at the cheapest possible point)."""
        now = time.perf_counter()
        kept: List[Request] = []
        for req in self._queue:
            if req.deadline is not None and now >= req.deadline:
                req.state = "timeout"
                req.error = "deadline expired while queued"
                req.t_done = now
                self._timeouts += 1
                if self._tm_on:
                    telemetry.tracer().instant(
                        "timeout", trace_id=req.trace_id,
                        track=self._tm_track, where="engine_queue")
            else:
                kept.append(req)
        self._queue = kept

    def _cache_ns(self, adapter):
        """The trie namespace this engine files prefixes under: the
        adapter salt (ISSUE 14) plus this engine's weight-version salt
        (ISSUE 17, rolling deploy) — ``version_ns`` keeps the default
        version bit-identical to the bare adapter key."""
        return version_ns(self.weight_version, adapter)

    def _admit(self):
        """Move queued requests into free slots: look up the longest
        cached prompt prefix, allocate fresh pages for everything past it
        (copy-on-write — shared pages are never written), prefill the
        tail (bucket-shaped program) and seed the slot."""
        self._expire_queued()
        while self._queue:
            try:
                # a mid-prefill slot is inactive but HELD (slot_req set)
                slot = next(i for i in range(self.slots)
                            if not self.active[i]
                            and self.slot_req[i] is None)
            except StopIteration:
                return
            req = self._queue[0]
            total = req.bucket + req.max_new_tokens
            n_total = math.ceil(total / self.page_size)
            # longest cached page-aligned prefix, capped so at least the
            # prompt's LAST token is always prefilled (its logits seed
            # the first emitted token). No refcounts move until the
            # admission is certain.
            matched: List[_TrieNode] = []
            if self.prefix_cache is not None:
                cap = (req.prompt.size - 1) // self.page_size
                # the trie is namespaced per (weight version, adapter):
                # KV depends on both the adapter's deltas and the
                # weights that produced it — tenants never share prefix
                # pages, and neither do weight versions mid-roll
                matched = self.prefix_cache.match(
                    req.prompt, cap, ns=self._cache_ns(req.adapter))
            full = len(matched)
            # host-resident matched pages each need a fresh HBM page to
            # promote into before they can be mounted read-only
            n_host = sum(1 for n in matched if n.tier != "hbm")
            need = n_total - full + n_host
            if len(self._free_pages) < need:
                if self.prefix_cache is not None:
                    # pool pressure: reclaim cold cached pages (LRU,
                    # refcount-0 only; with a host tier they demote
                    # instead of dying; the just-matched path is
                    # protected — it is about to be mounted)
                    self._free_pages.extend(self.prefix_cache.evict(
                        need - len(self._free_pages), protect=matched))
                if len(self._free_pages) < need:
                    # still short: wait for a retirement to free pages.
                    # Head-of-line blocking is deliberate — FIFO
                    # admission keeps TTFT fairness; submit() already
                    # guarantees the request fits an EMPTY pool (the
                    # trie is fully evictable once its users retire),
                    # so progress is always possible. The request stays
                    # QUEUED with no refcounts or pages held.
                    return
            if n_host:
                # H2D the host-tier part of the match; a failed
                # promotion truncates the path (cold prefill past it)
                matched = self._promote_matched(matched)
                full = len(matched)
                need = n_total - full   # promoted pages left the free
                #                         list; the rest is fresh pages
                if len(self._free_pages) < need:
                    return  # raced shortfall after a failed promotion
            adapter_page = 0
            if req.adapter is not None:
                # pin the tenant's adapter page; a miss FAULTS it in
                # through the one fixed-shape writer (compiled at
                # construction). A pool full of pinned pages leaves the
                # request queued — the KV-pool-pressure rule: progress
                # resumes when a retirement releases a page.
                got = self.lora.checkout(req.adapter)
                if got is None:
                    return
                adapter_page, ent = got
                if ent is not None:
                    self._write_adapter_page(adapter_page,
                                             ent["payload"],
                                             ent["scale"])
            self._queue.pop(0)
            # telemetry: the engine queue wait ends here (admission
            # starts); the prefill span opens here and closes after the
            # dispatch below, tagged cold vs hit (a handoff-import shows
            # as a preceding handoff_import span on the same trace id)
            tm = self._tm_on and telemetry.enabled()
            t_adm = time.perf_counter() if tm else 0.0
            if tm:
                wait = t_adm - req.t_submit
                self._tm_ch["queue"].observe(wait)
                telemetry.tracer().complete(
                    "queue_wait", req.t_submit, wait,
                    trace_id=req.trace_id, track=self._tm_track)
            # fault injection: FF_FAULT=slow(<ms>)@serve:<n> stalls the
            # n-th admission host-side — the deterministic slow-replica
            # drill (a deadline set tighter than <ms> expires while this
            # request is in flight; the router must NOT resubmit it)
            if faultinject.active_plan().fire("slow", "serve"):
                # ffsan: allow(lock-across-blocking) — stalling
                # this replica's tick IS the slow() drill's point
                time.sleep((faultinject.active_plan().last_value or 0)
                           / 1000.0)
            # FF_FAULT=slow(<ms>)@canary:<n> — the deterministic canary
            # SLO-breach drill (ISSUE 17): stall admissions ONLY while
            # this engine is the deploy canary, inflating its TTFT past
            # the slo_ttft_p99_s bound so the RollingDeployer's soak
            # judges a breach and rolls back. Non-canary replicas never
            # consume from the plan (fire() checks deploy_state first).
            if (self.deploy_state == "canary"
                    and faultinject.active_plan().fire("slow", "canary")):
                # ffsan: allow(lock-across-blocking) — the stall is
                # the injected breach itself
                time.sleep((faultinject.active_plan().last_value or 0)
                           / 1000.0)
            fresh = [self._free_pages.pop() for _ in range(need)]
            if self.prefix_cache is not None:
                self.prefix_cache.note_admitted(full)
            if matched:
                self.prefix_cache.acquire(matched)
                req.trie_nodes = list(matched)
                req.prefix_tokens = full * self.page_size
            req.private_pages = list(fresh)
            req.pages = [n.page for n in matched] + fresh
            req.slot = slot
            req.state = "running"
            req.adapter_page = adapter_page
            self.slot_req[slot] = req
            n_prefill = math.ceil(req.bucket / self.page_size)
            # fault injection: FF_FAULT=nan_loss@serve:<n> poisons the
            # n-th ADMITTED request in-graph (NaN added to its logits), so
            # the detect-and-retire path runs end to end, not a host
            # stub. Consumed HERE — in admission order — so the drill's
            # index is independent of how the prefill is scheduled; an
            # interleaved admission carries the poison in its partial
            # state until the final chunk's program applies it.
            poison = (np.float32(np.nan)
                      if faultinject.active_plan().fire("nan_loss",
                                                        "serve")
                      else np.float32(0.0))
            if (self.prefill_interleave_chunks > 0 and full == 0
                    and req.bucket > self.prefill_chunk):
                # chunk-interleaved admission (ISSUE 18): don't run the
                # prefill here — park the slot mid-prefill and let
                # _prefill_tick spend the per-tick chunk budget on it
                # between decode dispatches. The slot's decode-state
                # arrays stay ZEROED (decode writes clamp to scratch
                # page 0, budget stays 1 — indistinguishable from an
                # idle slot to the fixed-shape programs) until
                # _finish_prefill seeds and activates it. Prefix HITS
                # keep the run-to-completion path: the hit already
                # removed the long prefill this knob exists to split.
                padded = np.full((1, req.bucket), self.pad_id, np.int32)
                padded[0, :req.prompt.size] = req.prompt
                self._partial[slot] = {
                    "req": req, "caches": None, "next": 0,
                    "padded": padded, "n_prefill": n_prefill,
                    "t_adm": t_adm, "tm": tm, "poison": poison,
                    "adapter_page": adapter_page}
                continue
            # slot-resident sampling + adapter state: the fixed-shape
            # programs read these arrays every dispatch
            self.temps[slot] = req.temperature
            self.top_ps[slot] = req.top_p
            self.top_ks[slot] = req.top_k
            self.seeds[slot] = req.seed
            self.lora_pages[slot] = adapter_page
            self.poison[slot] = poison
            table = np.zeros((self.pages_per_slot,), np.int32)
            table[:n_total] = req.pages
            self.page_tables[slot] = table
            self.row_len[slot] = req.prompt.size
            self.prompt_pad[slot] = req.bucket
            self.emitted[slot] = 0

            if full:
                # prefix hit: gather the matched pages read-only, prefill
                # only the tail slab [full*ps, bucket) into FRESH pages —
                # the matched prefix's partial last page (tokens past
                # full*ps) is re-materialized into the request's own
                # first tail page, never written in the donor's (the COW
                # rule). One program per (bucket, full): bounded like the
                # buckets themselves, flat after warmup.
                p0 = full * self.page_size
                padded_tail = np.full((1, req.bucket - p0), self.pad_id,
                                      np.int32)
                tail = req.prompt[p0:]
                padded_tail[0, :tail.size] = tail
                tok_last = np.asarray([[req.prompt[-1]]], np.int32)
                tok, ok, self.pool = self._compiled_call(
                    ("prefill_hit", req.bucket, full),
                    lambda: self._build_prefill_hit(req.bucket, full),
                    self.gen._params(), self.model.bn_state, padded_tail,
                    tok_last, np.asarray([req.prompt.size], np.int32),
                    self.pool, np.asarray(req.pages[:full], np.int32),
                    np.asarray(req.pages[full:n_prefill], np.int32),
                    np.float32(self.poison[slot]),
                    *self._sampling_args_1(req),
                    *self._lora_args_1(adapter_page))
            else:
                padded = np.full((1, req.bucket), self.pad_id, np.int32)
                padded[0, :req.prompt.size] = req.prompt
                tok, ok, self.pool = self._compiled_call(
                    ("prefill", req.bucket, n_prefill, self.prefill_chunk),
                    lambda: self._build_prefill(req.bucket, n_prefill),
                    self.gen._params(), self.model.bn_state, padded,
                    np.asarray([req.prompt.size], np.int32), self.pool,
                    np.asarray(req.pages[:n_prefill], np.int32),
                    np.float32(self.poison[slot]),
                    *self._sampling_args_1(req),
                    *self._lora_args_1(adapter_page))
            if self.draft_gen is not None:
                # the draft model's prefix KV rides the same page ids, so
                # its prefill mirrors the target's hit/cold split exactly
                if full:
                    self.draft_pool = self._compiled_call(
                        ("draft_prefill_hit", req.bucket, full),
                        lambda: self._build_draft_prefill_hit(req.bucket,
                                                              full),
                        self.draft_gen._params(), self.draft_model.bn_state,
                        padded_tail, self.draft_pool,
                        np.asarray(req.pages[:full], np.int32),
                        np.asarray(req.pages[full:n_prefill], np.int32))
                else:
                    self.draft_pool = self._compiled_call(
                        ("draft_prefill", req.bucket, n_prefill),
                        lambda: self._build_draft_prefill(req.bucket,
                                                          n_prefill),
                        self.draft_gen._params(), self.draft_model.bn_state,
                        padded, self.draft_pool,
                        np.asarray(req.pages[:n_prefill], np.int32))
            ok_host = bool(np.asarray(ok)[0])
            if tm:
                telemetry.tracer().complete(
                    "prefill", t_adm, time.perf_counter() - t_adm,
                    trace_id=req.trace_id, track=self._tm_track,
                    kind="hit" if full else "cold", bucket=req.bucket,
                    matched_pages=full, ok=ok_host)
                req.decode_span = telemetry.tracer().begin(
                    "decode", trace_id=req.trace_id,
                    track=self._tm_track)
            if self.prefix_cache is not None and ok_host:
                # publish this prompt's FULL pages beyond the matched
                # prefix for future sharing (poisoned/non-finite prefills
                # are never published — a NaN prompt cache must not
                # infect later requests). Published pages move from
                # private to trie-owned: decref'd at retirement, freed
                # only by eviction.
                last = req.prompt.size // self.page_size
                if last > full:
                    created = self.prefix_cache.insert(
                        req.prompt, matched, full, req.pages[full:last],
                        ns=self._cache_ns(req.adapter))
                    if created:
                        adopted = {n.page for n in created}
                        req.trie_nodes.extend(created)
                        req.private_pages = [p for p in req.private_pages
                                             if p not in adopted]
            self.active[slot] = True
            self._record_token(slot, int(np.asarray(tok)[0]), ok_host)

    # ---- chunk-interleaved prefill scheduling (ISSUE 18) ------------------

    def _prefill_tick(self):
        """Spend up to ``prefill_interleave_chunks`` prefill chunks this
        tick, round-robined across mid-prefill slots so concurrent long
        prompts make equal progress; a slot whose last chunk lands is
        finished (sampled + activated) inline, mid-tick. Deadlines are
        swept FIRST so an expired mid-prefill request costs no further
        dispatches — it retires as "timeout" and frees its pages without
        ever decoding."""
        if not self._partial:
            return
        now = time.perf_counter()
        for slot in sorted(self._partial):
            req = self._partial[slot]["req"]
            if req.deadline is not None and now >= req.deadline:
                self._abort_partial(slot, "timeout",
                                    "deadline expired mid-prefill")
        budget = self.prefill_interleave_chunks
        while budget > 0 and self._partial:
            slots = sorted(self._partial)
            slot = slots[self._prefill_rr % len(slots)]
            self._prefill_rr += 1
            self._run_prefill_chunk(slot)
            budget -= 1
        if self._partial:
            # chunks remained when the tick's budget ran out — the
            # decode streams get the device back; this counter is the
            # proof the knob actually preempted a long prefill
            self._prefill_preempted_ticks += 1

    def _run_prefill_chunk(self, slot: int):
        """One prefill quantum: run the slot's next chunk program,
        advancing the slot-resident cache cursor."""
        ps = self._partial[slot]
        req = ps["req"]
        st = ps["next"]
        if st == 0:
            ps["caches"] = self._compiled_call(
                ("prefill_ichunk", req.bucket, 0),
                lambda: self._build_prefill_ichunk(req.bucket, 0),
                self.gen._params(), self.model.bn_state, ps["padded"],
                *self._lora_args_1(ps["adapter_page"]))
        else:
            ps["caches"] = self._compiled_call(
                ("prefill_ichunk", req.bucket, st),
                lambda: self._build_prefill_ichunk(req.bucket, st),
                self.gen._params(), self.model.bn_state, ps["padded"],
                ps["caches"], *self._lora_args_1(ps["adapter_page"]))
        ps["next"] = st + self.prefill_chunk
        self._prefill_chunks_interleaved += 1
        if ps["next"] >= req.bucket:
            self._finish_prefill(slot)

    def _finish_prefill(self, slot: int):
        """The last interleaved quantum: run the gather-last + COW
        scatter program, seed the slot's decode-state arrays and
        activate it — from here on the request is indistinguishable
        from a run-to-completion admission (same pages, same sampled
        first token, same published prefix)."""
        ps = self._partial.pop(slot)
        req = ps["req"]
        n_prefill = ps["n_prefill"]
        tok, ok, self.pool = self._compiled_call(
            ("prefill_ifinal", req.bucket, n_prefill),
            lambda: self._build_prefill_ifinal(req.bucket, n_prefill),
            self.gen._params(), self.model.bn_state, ps["padded"],
            np.asarray([req.prompt.size], np.int32), ps["caches"],
            self.pool, np.asarray(req.pages[:n_prefill], np.int32),
            ps["poison"], *self._sampling_args_1(req),
            *self._lora_args_1(ps["adapter_page"]))
        if self.draft_gen is not None:
            # the draft pool rides the same page ids; its cold prefill
            # program (shared with run-to-completion admission) fills
            # them in one pass — the TARGET's prefill is the
            # head-of-line blocker this path splits, not the draft's
            self.draft_pool = self._compiled_call(
                ("draft_prefill", req.bucket, n_prefill),
                lambda: self._build_draft_prefill(req.bucket, n_prefill),
                self.draft_gen._params(), self.draft_model.bn_state,
                ps["padded"], self.draft_pool,
                np.asarray(req.pages[:n_prefill], np.int32))
        ok_host = bool(np.asarray(ok)[0])
        # decode-state arrays applied only NOW: until this instant every
        # decode dispatch saw this slot as idle
        self.temps[slot] = req.temperature
        self.top_ps[slot] = req.top_p
        self.top_ks[slot] = req.top_k
        self.seeds[slot] = req.seed
        self.lora_pages[slot] = ps["adapter_page"]
        self.poison[slot] = ps["poison"]
        n_total = math.ceil((req.bucket + req.max_new_tokens)
                            / self.page_size)
        table = np.zeros((self.pages_per_slot,), np.int32)
        table[:n_total] = req.pages
        self.page_tables[slot] = table
        self.row_len[slot] = req.prompt.size
        self.prompt_pad[slot] = req.bucket
        self.emitted[slot] = 0
        if ps["tm"]:
            telemetry.tracer().complete(
                "prefill", ps["t_adm"],
                time.perf_counter() - ps["t_adm"],
                trace_id=req.trace_id, track=self._tm_track,
                kind="interleaved", bucket=req.bucket,
                matched_pages=0, ok=ok_host)
            req.decode_span = telemetry.tracer().begin(
                "decode", trace_id=req.trace_id, track=self._tm_track)
        if self.prefix_cache is not None and ok_host:
            # publish the prompt's full pages for future sharing —
            # the same rule (and the same insert) as _admit's cold leg
            last = req.prompt.size // self.page_size
            if last > 0:
                created = self.prefix_cache.insert(
                    req.prompt, [], 0, req.pages[:last],
                    ns=self._cache_ns(req.adapter))
                if created:
                    adopted = {n.page for n in created}
                    req.trie_nodes.extend(created)
                    req.private_pages = [p for p in req.private_pages
                                         if p not in adopted]
        self.active[slot] = True
        self._record_token(slot, int(np.asarray(tok)[0]), ok_host)

    def _abort_partial(self, slot: int, state: str, error: str):
        """Retire a mid-prefill slot (deadline/poison/fault paths): the
        chunked caches are dropped, pages freed, and the request retires
        without ever decoding. _retire clears the partial state."""
        ps = self._partial[slot]
        if ps["tm"]:
            telemetry.tracer().complete(
                "prefill", ps["t_adm"],
                time.perf_counter() - ps["t_adm"],
                trace_id=ps["req"].trace_id, track=self._tm_track,
                kind="interleaved", aborted=state)
        self._retire(slot, state, error)

    # ---- disaggregated fleet: prefill-only + page-slab handoff -----------

    def _sampling_args_greedy(self):
        """Dummy (1,) greedy sampling args for prefill-only admissions
        (the sampled token is discarded — no slot is seeded)."""
        return (np.zeros((1,), np.float32), np.ones((1,), np.float32),
                np.zeros((1,), np.int32), np.zeros((1,), np.int32))

    def prefill_into_cache(self, prompt,
                           adapter: Optional[str] = None) -> Optional[int]:
        """Prefill-only admission — the prefill half of the
        disaggregated fleet (runtime/router.py): run the prompt's (cold
        or prefix-hit) prefill through the NORMAL bucket-shaped programs
        — same compile keys, so a warmed engine compiles nothing — and
        publish its full pages into the radix trie at refcount 0. No
        slot is held and no token emitted; the pages are then
        ``export_prefix_slab()``'s payload for the handoff to a decode
        replica, or simply a warm local cache (the reference-seeding
        primitive the identity tests use). Returns the number of full
        pages now cached for this prompt, or None when pool pressure or
        a non-finite prefill prevented publishing — the caller falls
        back to the cold path."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if self.prefix_cache is None:
            raise RuntimeError(
                "prefill_into_cache needs the radix prefix cache "
                "(prefix_cache=False engines cannot publish pages)")
        bucket = self._bucket(prompt.size)
        if bucket > self.max_seq_len:
            raise ValueError(
                f"bucketed prompt ({bucket}) exceeds max_seq_len "
                f"{self.max_seq_len}")
        with self._lock:
            apage = 0
            if adapter is not None:
                if self.lora is None or adapter not in self.lora.registry:
                    raise ValueError(
                        f"adapter {adapter!r} is not registered on this "
                        f"engine")
                got = self.lora.checkout(adapter)
                if got is None:
                    return None     # adapter-pool pressure: fall back
                apage, ent = got
                if ent is not None:
                    self._write_adapter_page(apage, ent["payload"],
                                             ent["scale"])
            try:
                # the checkout pins the adapter only for the duration of
                # the prefill (no slot holds it afterwards)
                return self._prefill_into_cache_locked(prompt, bucket,
                                                       adapter, apage)
            finally:
                if adapter is not None:
                    self.lora.release(adapter)

    def _prefill_into_cache_locked(self, prompt, bucket: int,
                                   adapter: Optional[str], apage: int):
            ps_sz = self.page_size
            last = prompt.size // ps_sz     # publishable full pages
            cap = (prompt.size - 1) // ps_sz
            matched = self.prefix_cache.match(
                prompt, cap, ns=self._cache_ns(adapter))
            full = len(matched)
            if last <= full:
                return last                 # already fully published
            n_prefill = math.ceil(bucket / ps_sz)
            n_host = sum(1 for n in matched if n.tier != "hbm")
            need = n_prefill - full + n_host
            if len(self._free_pages) < need:
                self._free_pages.extend(self.prefix_cache.evict(
                    need - len(self._free_pages), protect=matched))
                if len(self._free_pages) < need:
                    return None
            if n_host:
                matched = self._promote_matched(matched)
                full = len(matched)
                if last <= full:
                    return last
                if len(self._free_pages) < n_prefill - full:
                    return None
            fresh = [self._free_pages.pop()
                     for _ in range(n_prefill - full)]
            prefix_pages = np.asarray([n.page for n in matched], np.int32)
            if full:
                p0 = full * ps_sz
                padded_tail = np.full((1, bucket - p0), self.pad_id,
                                      np.int32)
                tail = prompt[p0:]
                padded_tail[0, :tail.size] = tail
                tok_last = np.asarray([[prompt[-1]]], np.int32)
                _, ok, self.pool = self._compiled_call(
                    ("prefill_hit", bucket, full),
                    lambda: self._build_prefill_hit(bucket, full),
                    self.gen._params(), self.model.bn_state, padded_tail,
                    tok_last, np.asarray([prompt.size], np.int32),
                    self.pool, prefix_pages,
                    np.asarray(fresh, np.int32), np.float32(0.0),
                    *self._sampling_args_greedy(),
                    *self._lora_args_1(apage))
            else:
                padded = np.full((1, bucket), self.pad_id, np.int32)
                padded[0, :prompt.size] = prompt
                _, ok, self.pool = self._compiled_call(
                    ("prefill", bucket, n_prefill, self.prefill_chunk),
                    lambda: self._build_prefill(bucket, n_prefill),
                    self.gen._params(), self.model.bn_state, padded,
                    np.asarray([prompt.size], np.int32), self.pool,
                    np.asarray(fresh, np.int32), np.float32(0.0),
                    *self._sampling_args_greedy(),
                    *self._lora_args_1(apage))
            if self.draft_gen is not None:
                # the slab must carry the draft pool's prefix KV too —
                # it rides the same page ids on the decode replica
                if full:
                    self.draft_pool = self._compiled_call(
                        ("draft_prefill_hit", bucket, full),
                        lambda: self._build_draft_prefill_hit(bucket,
                                                              full),
                        self.draft_gen._params(),
                        self.draft_model.bn_state, padded_tail,
                        self.draft_pool, prefix_pages,
                        np.asarray(fresh, np.int32))
                else:
                    self.draft_pool = self._compiled_call(
                        ("draft_prefill", bucket, n_prefill),
                        lambda: self._build_draft_prefill(bucket,
                                                          n_prefill),
                        self.draft_gen._params(),
                        self.draft_model.bn_state, padded,
                        self.draft_pool, np.asarray(fresh, np.int32))
            if not bool(np.asarray(ok)[0]):
                # a non-finite prefill must never publish (the PR-6
                # rule): the pages return to the pool untracked
                self._free_pages.extend(fresh)
                return None
            pages = [n.page for n in matched] + fresh
            created = self.prefix_cache.insert(
                prompt, matched, full, pages[full:last],
                ns=self._cache_ns(adapter))
            # the publisher holds no mount: published pages sit warm at
            # refcount 0, exportable and evictable like any cached page
            self.prefix_cache.release(created)
            adopted = {n.page for n in created}
            self._free_pages.extend(p for p in fresh if p not in adopted)
            self._prefill_only += 1
            return last

    def export_prefix_slab(self, prompt,
                           adapter: Optional[str] = None,
                           start_page: int = 0) -> Optional[Dict]:
        """Serialize the prompt's cached full-page prefix as a
        host-memory page slab — the bytes a prefill->decode handoff
        moves: per page, every attention op's pool storage (target and
        draft pools) plus quantized scales, verbatim. Host-tier pages
        export straight from their pinned host payload (no promotion);
        HBM pages D2H on the spot. None when the prefix is not fully
        cached — the caller falls back cold.

        ``start_page`` > 0 exports a PARTIAL-PREFIX slab (ISSUE 18,
        sequence-parallel prefill): only pages [start_page, last) ride
        the payload — the shard this replica computed — while
        ``tokens`` still names the whole prefix, so the importer can
        verify the pages extend an already-merged path. The whole
        prefix must still be cached HERE (shards import their
        predecessors' slabs before prefilling), so the exported pages'
        KV attends the true full prefix."""
        return self._export_slab_ns(prompt, self._cache_ns(adapter),
                                    start_page)

    def _export_slab_ns(self, prompt, ns, start_page: int = 0) \
            -> Optional[Dict]:
        """export_prefix_slab against an EXPLICIT salted namespace — the
        evacuation path (ISSUE 20) re-exports trie paths whose
        (version, adapter) salt was read back off the trie itself, so a
        retiring replica's A/B-versioned and per-adapter prefixes land
        on survivors under the exact key they were cached under."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            if self.prefix_cache is None:
                return None
            last = prompt.size // self.page_size
            if start_page < 0 or start_page >= last:
                if start_page == 0:
                    return None     # last < 1: nothing page-aligned
                raise ValueError(
                    f"start_page={start_page}: must be in [0, {last}) "
                    f"for this prompt's {last} full prefix pages")
            path = self.prefix_cache.match(prompt, last, ns=ns)
            if len(path) < last:
                return None
            tail = path[start_page:]
            # host-tier pages export from their pinned payloads; the
            # HBM part D2Hs in ONE batched gather
            hbm = [n for n in tail if n.tier == "hbm"]
            hbm_payloads = (self._page_d2h([n.page for n in hbm])()
                            if hbm else [])
            by_node = {id(n): p for n, p in zip(hbm, hbm_payloads)}
            payloads = []
            for node in tail:
                if node.tier == "host":
                    payload = self.prefix_cache.host_payload(node)
                    if payload is None:
                        return None
                else:
                    payload = by_node[id(node)]
                payloads.append(payload)
            self._slab_exports += 1
            # the slab carries the exporter's SALTED namespace: an
            # importer on a different weight version files it under the
            # exporter's version key, so its own traffic can never hit
            # cross-version KV (zero stale hits by construction)
            return {"page_size": self.page_size,
                    "tokens": prompt[:last * self.page_size].copy(),
                    "ns": ns,
                    "start_page": int(start_page),
                    "payload": payloads}

    def import_prefix_slab(self, slab) -> int:
        """Decode-side handoff ingestion: scatter a peer replica's page
        slab into this engine's pools (ONE fixed-shape writer program —
        no per-page compiles) and publish the chunks into the radix trie
        at refcount 0, so the subsequent ``submit()`` of the same prompt
        admits as a prefix HIT. Chunks already cached are skipped;
        returns the number of pages written. Partial imports are safe
        (the trie path stays a valid prefix).

        Partial-prefix slabs (``start_page`` > 0, ISSUE 18) compose
        MID-prefix: the slab's pages extend an already-imported path —
        sequence-parallel prefill merges its shards by importing them
        in order. A slab whose predecessors have not merged yet is
        refused (return 0, no pages written): publishing pages past a
        gap would cache a prefix whose middle was never written."""
        with self._lock:
            if self.prefix_cache is None:
                return 0
            if int(slab["page_size"]) != self.page_size:
                raise ValueError(
                    f"slab page_size {slab['page_size']} != engine "
                    f"page_size {self.page_size}: fleet replicas must "
                    f"share the pool geometry")
            if not slab["payload"]:
                return 0
            have_draft = any(k[0] == "d" for k in slab["payload"][0])
            if have_draft != (self.draft_pool is not None):
                raise ValueError(
                    "slab draft-pool payload mismatch: exporter and "
                    "importer must agree on speculation")
            # the payload must match THIS pool's storage exactly:
            # import_page casts silently, so a dtype/geometry mismatch
            # (e.g. a bf16 slab into an int8 engine) would publish
            # saturating-cast garbage served as a prefix hit — reject
            # loudly instead, like the page_size check above
            p0 = slab["payload"][0]
            for op in self.gen.attn_ops:
                sub = p0.get(("t", op.name))
                if sub is None:
                    raise ValueError(
                        f"slab payload missing attention op {op.name!r}:"
                        f" exporter and importer must run the same "
                        f"model")
                pool = self.pool[op.name]
                pk = np.asarray(sub["k"])
                if pk.dtype != pool["k"].dtype \
                        or pk.shape != pool["k"].shape[1:]:
                    raise ValueError(
                        f"slab payload for {op.name!r} is {pk.dtype}"
                        f"{pk.shape} but this engine's pool stores "
                        f"{pool['k'].dtype}{pool['k'].shape[1:]}: fleet "
                        f"replicas must share kv_cache_dtype and pool "
                        f"geometry")
                if ("k_scale" in pool) != ("k_scale" in sub):
                    raise ValueError(
                        f"slab scale presence mismatch for {op.name!r}: "
                        f"quantized and full-width pools cannot exchange"
                        f" pages")
            tokens = np.asarray(slab["tokens"], np.int32).reshape(-1)
            ns = slab.get("ns")
            sp = int(slab.get("start_page", 0))
            n = sp + len(slab["payload"])
            path = self.prefix_cache.match(tokens, n, ns=ns)
            if len(path) < sp:
                # a partial slab landing before its predecessors: pages
                # [len(path), sp) are neither cached here nor in this
                # payload — importing would publish a gapped prefix
                return 0
            # only extend under a fully HBM-resident prefix: inserting
            # fresh hbm nodes below a host-tier tail would break the
            # hbm*-then-host* path invariant that promotion truncation
            # and freed-page accounting depend on. A host-resident tail
            # means the prefix IS cached — the next submit promotes it;
            # there is nothing to import here.
            if any(nd.tier != "hbm" for nd in path):
                return 0
            start = len(path)
            missing = n - start
            if missing <= 0:
                return 0
            if len(self._free_pages) < missing:
                self._free_pages.extend(self.prefix_cache.evict(
                    missing - len(self._free_pages), protect=path))
            take = min(missing, len(self._free_pages))
            if take <= 0:
                return 0
            pages = [self._free_pages.pop() for _ in range(take)]
            # ONE batched writer dispatch (padded to pages_per_slot
            # chunks) scatters the whole slab in; a partial slab's
            # payload list starts at page ``sp``, so index relative
            self._page_h2d(pages,
                           slab["payload"][start - sp:start - sp + take])
            imported = 0
            node_path = list(path)
            for j, page in enumerate(pages, start=start):
                created = self.prefix_cache.insert(
                    tokens, node_path, j, [page], ns=ns)
                if not created:
                    break
                self.prefix_cache.release(created)
                node_path.extend(created)
                imported += 1
            # partial import (an insert collision) keeps a valid prefix;
            # any unpublished written pages simply return to the pool
            self._free_pages.extend(pages[imported:])
            if imported:
                self._slab_imports += 1
                self._import_pages += imported
                if sp > 0:
                    self._partial_slab_imports += 1
            return imported

    def cached_prefix_manifest(self) -> List[Tuple[np.ndarray, object]]:
        """Evacuation manifest (ISSUE 20): ``(tokens, ns)`` per cached
        root-to-leaf prefix path on this engine, hottest first, each
        under its original salted namespace. A preempted or retiring
        replica walks this in heat order, re-exporting each entry with
        export_prefix_path() — checking its evacuation deadline BETWEEN
        slabs — so the hottest state lands on survivors first."""
        with self._lock:
            if self.prefix_cache is None:
                return []
            return [(t, ns) for t, ns, _ in self.prefix_cache
                    .cached_paths()]

    def export_prefix_path(self, tokens, ns) -> Optional[Dict]:
        """One evacuation slab: a cached_prefix_manifest() entry
        re-exported verbatim under its original namespace. None when the
        path's pages were evicted since the manifest walk — the entry
        simply drops out of the evacuation."""
        return self._export_slab_ns(tokens, ns)

    def warm_page_import(self, prompt) -> bool:
        """Compile and run the shared page-import writer once (H2D tier
        promotion and fleet-handoff ingestion both ride it): publish the
        prompt's prefix, export it, forget it, re-import it — the trie
        ends bit-identical to where it started, with the writer program
        warm. Router/engine ``warmup()`` call this so the first real
        promotion or handoff never compiles mid-traffic."""
        with self._lock, self._retrace.suspended():
            if self.prefix_cache is None:
                return False
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            if prompt.size < self.page_size:
                return False
            if self.prefill_into_cache(prompt) is None:
                return False
            slab = self.export_prefix_slab(prompt)
            if slab is None:
                return False
            self._free_pages.extend(self.prefix_cache.forget(prompt))
            return self.import_prefix_slab(slab) > 0

    def warmup(self, prompts, max_new_tokens: int = 4) -> Dict:
        """Warm EVERY program this prompt set can reach — the bench
        gotcha relearned in PRs 7, 8 and 10, promoted to an API: a
        prompt REPEATED after its first run reaches (bucket,
        matched_pages) hit-prefill variants the first pass never
        compiled, so any timed window that repeats prompts (best-of-N
        rounds!) compiles mid-measurement unless every variant was
        driven. Pass 1 runs every prompt (cold prefill per bucket, the
        partial-prefix hits submission order reaches, decode/verify
        programs); pass 2 repeats them against the now-published trie
        (the SATURATED matches that repeat traffic reaches). With a host
        tier the shared page-import writer is warmed too. Returns
        {"programs": compiles this warmup caused, "requests", and the
        warmed program "variants"}."""
        plist = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
        self._retrace.armed = False   # re-warming reopens the set
        before = self.recompile_count
        req0 = self._submitted
        self.run(list(plist), max_new_tokens=max_new_tokens)
        if self.speculate_k > 0 and self.draft_gen is not None:
            # force-build the sampled-speculation helpers (accept
            # uniforms + residual resample): they only dispatch when a
            # sampled slot is live, so a greedy-only warmup would leave
            # them cold and the first sampled tenant mid-traffic would
            # compile. Both are pure functions — running them mutates no
            # engine state.
            k = self.speculate_k
            self._compiled_call(
                ("spec_uniforms", k),
                lambda: jax.jit(
                    lambda s, c: sampling_ops.accept_uniforms(s, c, k)),
                self.seeds, self.emitted.astype(np.int32))
            self._compiled_call(
                ("spec_resample",),
                lambda: jax.jit(sampling_ops.residual_sample),
                np.full((self.slots, self._vocab), 1.0 / self._vocab,
                        np.float32),
                np.zeros((self.slots, self._vocab), np.float32),
                self.seeds, self.emitted.astype(np.int32))
        if self.prefix_cache is not None:
            self.run(list(plist), max_new_tokens=max_new_tokens)
            if self.host_kv_pages:
                cand = max((p for p in plist
                            if p.size >= self.page_size),
                           key=lambda p: p.size, default=None)
                if cand is None or not self.warm_page_import(cand):
                    fflogger.warning(
                        "serving: warmup could not warm the page-import"
                        " writer (no full-page prompt, pool pressure, "
                        "or nothing to re-import) — the first real "
                        "promotion/handoff will compile it")
        if self._tm_on:
            # restart the SLO window clock past the warmup: a
            # compile-inflated warmup TTFT must never be judged as a
            # breach (the bench's warm-window discipline, applied to
            # the health plane)
            flightrec.slo_monitor().rebaseline()
        self._retrace.arm()
        return {"programs": self.recompile_count - before,
                "requests": self._submitted - req0,
                "variants": sorted(self._programs.keys(), key=repr)}

    def drain_tier_events(self) -> List:
        """Pop the trie's depth-1 tier transitions — the router's
        tier-aware affinity feed (key = the prompt's first full-page
        chunk, exactly the affinity hash)."""
        if self.prefix_cache is None:
            return []
        with self._lock:
            return self.prefix_cache.drain_tier_events()

    def _slot_decode_state(self):
        """(write_pos, rope_pos, budget) for one decode/speculate
        dispatch. Inactive slots: state arrays are zeroed, so write_pos
        = -1 would index page -1 — clamp to 0 (the write lands in
        scratch page 0) and give them budget 1, clamping every later
        step there too. Budget is the last legal write position + 1
        (bucket + the request's own max_new_tokens)."""
        write_pos = np.maximum(self.prompt_pad + self.emitted - 1,
                               0).astype(np.int32)
        rope_pos = np.maximum(self.row_len + self.emitted - 1,
                              0).astype(np.int32)
        budget = np.ones((self.slots,), np.int32)
        for slot in range(self.slots):
            req = self.slot_req[slot]
            # mid-prefill slots (slot_req set, inactive) keep budget 1:
            # their state arrays are still zeroed, so decode writes
            # clamp to scratch page 0 exactly like an idle slot's
            if req is not None and self.active[slot]:
                budget[slot] = req.bucket + req.max_new_tokens
        return write_pos, rope_pos, budget

    def _note_pages_touched(self, frontier, budget):
        """Record the pool pages this dispatch's attention READS: per
        active slot, pages up to its final-step write frontier (what the
        pallas kernel streams through VMEM — the einsum path gathers the
        whole table width regardless, which is exactly the delta the
        kernel exists to remove)."""
        fr = np.minimum(frontier, budget - 1)
        touched = int(np.sum((fr // self.page_size + 1)[self.active])) \
            if self.active.any() else 0
        self._last_pages_touched = touched
        self._pages_touched += touched

    def _decode_step(self):
        k = self.decode_chunk
        write_pos, rope_pos, budget = self._slot_decode_state()
        self._note_pages_touched(write_pos + k - 1, budget)
        # per-slot draw counters: the next token's index is exactly the
        # count already emitted — slot- and replica-invariant, so a
        # failover replay reproduces the stream
        toks, oks, self.pool = self._compiled_call(
            ("decode", k), lambda: self._build_decode(k),
            self.gen._params(), self.model.bn_state, self.pool,
            self.page_tables, self.last_tok, write_pos, rope_pos,
            self.row_len, self.prompt_pad, budget, self.poison,
            self.temps, self.top_ps, self.top_ks, self.seeds,
            self.emitted.copy(), *self._lora_args_slots())
        toks = np.asarray(toks)                        # (k, B_slots)
        oks = np.asarray(oks)
        self.decode_steps += k
        for slot in range(self.slots):
            for t in range(k):
                if not self.active[slot]:
                    break  # retired mid-chunk: later tokens are truncated
                # occupancy counts USEFUL slot-steps only — a slot that
                # retires mid-chunk stops counting, so the metric is not
                # inflated by the truncated past-retirement steps
                self._occupancy_sum += 1
                self._record_token(slot, int(toks[t, slot]),
                                   bool(oks[t, slot]))

    def _spec_step(self):
        """One speculative iteration: the draft proposes K tokens per
        slot from its OWN sampling distribution ``q`` (greedy slots:
        argmax — the pre-sampling path bitwise), the target scores all
        K+1 candidate positions in ONE verify dispatch (argmax + the
        warped sampling distribution ``p``), and the host applies the
        accept rule per slot:

          * greedy (temperature 0): emit the longest proposal prefix
            matching the target's argmax, plus the target's own next
            token — every emitted token is the TARGET's argmax, so the
            stream is token-identical to non-speculative greedy decode
            at any K (unchanged from PR 6);
          * sampled: REJECTION-SAMPLED — proposal i is accepted with
            probability min(1, p_i(d_i) / q_i(d_i)) against an
            ACCEPT-stream uniform; the first rejection re-draws from
            the residual distribution norm(max(p - q, 0)) in-graph
            (ops/sampling.py residual_sample), and a fully-accepted
            window draws its bonus token from ``p_K`` (q = 0 residual).
            Emitted tokens are then EXACTLY distributed as the
            non-speculative sampler's (the classic rejection-sampling
            identity) — property-tested in tests/test_sampled_spec.py.

        All draws are counter-based on the request's seed (draw index =
        the emitted token's position), so the whole trajectory replays
        bit-for-bit after failover resubmission. k/v written for
        rejected positions sit past the slot's new write frontier and
        are overwritten by the next dispatch before anything can attend
        them — the resampled token's k/v is written by the NEXT
        iteration's slab position 0, exactly like the greedy path's
        mismatch token."""
        k = self.speculate_k
        write_pos, rope_pos, budget = self._slot_decode_state()
        ctr0 = self.emitted.copy().astype(np.int32)
        # greedy-only iterations never read the p/q probability tensors
        # — skip their device-to-host transfers (B*(K+1)*V floats per
        # dispatch at real vocab sizes) and the uniforms/resample
        # dispatches; the device arrays themselves are cheap (softmax
        # over logits the walk already materialized)
        sampled_live = bool(self.active.any()) and bool(
            np.any(self.temps[self.active] > 0.0))
        # verify-slab frontier (the draft's decode mirrors the same pages)
        self._note_pages_touched(write_pos + k, budget)
        d_toks, d_probs, self.draft_pool = self._compiled_call(
            ("draft_propose", k),
            lambda: self._build_draft_propose(k),
            self.draft_gen._params(), self.draft_model.bn_state,
            self.draft_pool, self.page_tables, self.last_tok, write_pos,
            rope_pos, self.row_len, self.prompt_pad, budget,
            self.temps, self.top_ps, self.top_ks, self.seeds, ctr0)
        d_toks = np.asarray(d_toks)                    # (k, B_slots)
        if sampled_live:
            d_probs = np.asarray(d_probs)              # (k, B_slots, V)
        slab = np.concatenate(
            [self.last_tok[:, None].astype(np.int32), d_toks.T], axis=1)
        # per-position write slots, clamped to each request's own budget
        # (positions an emitted token can attend never reach the clamp —
        # emission stops at max_new first, so clamp-duplicated writes are
        # only ever visible to host-truncated tokens)
        pos = np.minimum(
            write_pos[:, None] + np.arange(k + 1, dtype=np.int32)[None, :],
            (budget - 1)[:, None]).astype(np.int32)
        t_toks, t_probs, t_oks, self.pool = self._compiled_call(
            ("verify", k), lambda: self._build_verify(k),
            self.gen._params(), self.model.bn_state, self.pool,
            self.page_tables, slab, pos, rope_pos, self.row_len,
            self.prompt_pad, self.poison,
            self.temps, self.top_ps, self.top_ks,
            *self._lora_args_slots())
        t_toks = np.asarray(t_toks)                    # (B_slots, k+1)
        if sampled_live:
            t_probs = np.asarray(t_probs)              # (B, k+1, V)
        t_oks = np.asarray(t_oks)
        self.decode_steps += k + 1
        self._spec_dispatches += 1
        # sampled slots need the accept uniforms; greedy-only iterations
        # skip the dispatch (warmup() force-builds the programs so a
        # first sampled request mid-traffic compiles nothing)
        u = None
        if sampled_live:
            u = np.asarray(self._compiled_call(
                ("spec_uniforms", k),
                lambda: jax.jit(
                    lambda s, c: sampling_ops.accept_uniforms(s, c, k)),
                self.seeds, ctr0))                     # (B_slots, k)
        # ---- the HOST-side accept rule --------------------------------
        accepts = np.zeros((self.slots,), np.int32)
        p_rows = np.zeros((self.slots, self._vocab), np.float32)
        q_rows = np.zeros((self.slots, self._vocab), np.float32)
        for slot in range(self.slots):
            if not self.active[slot]:
                continue
            accepted = 0
            if self.temps[slot] <= 0.0:
                while accepted < k \
                        and d_toks[accepted, slot] == t_toks[slot,
                                                             accepted]:
                    accepted += 1
            else:
                while accepted < k:
                    d = int(d_toks[accepted, slot])
                    pd = float(t_probs[slot, accepted, d])
                    qd = float(d_probs[accepted, slot, d])
                    # accept w.p. min(1, p/q): u*q < p, STRICT — u is
                    # uniform over [0, 1), so strictness leaves the
                    # accept probability unchanged for p > 0 but
                    # guarantees a proposal OUTSIDE the target's
                    # top-k/top-p keep-set (p == 0 exactly) is always
                    # rejected, even on a u == 0.0 draw (q > 0 always —
                    # the draft just sampled d from q)
                    if u[slot, accepted] * qd < pd:
                        accepted += 1
                    else:
                        break
                p_rows[slot] = t_probs[slot, accepted]
                if accepted < k:   # bonus draw after a clean window
                    #                keeps q = 0 (residual == p)
                    q_rows[slot] = d_probs[accepted, slot]
            accepts[slot] = accepted
        res = None
        if sampled_live:
            # the in-graph residual re-draw (one fixed-shape dispatch
            # covers every sampled slot's rejection OR bonus draw; the
            # draw index is the emitted token's position)
            res = np.asarray(self._compiled_call(
                ("spec_resample",),
                lambda: jax.jit(sampling_ops.residual_sample),
                p_rows, q_rows, self.seeds,
                (ctr0 + accepts).astype(np.int32)))
        # ---- emit -----------------------------------------------------
        for slot in range(self.slots):
            if not self.active[slot]:
                continue
            req = self.slot_req[slot]
            arow = self._adapter_spec.setdefault(
                (req.adapter or "none") if req else "none", [0, 0])
            accepted = int(accepts[slot])
            self._spec_proposed += k
            self._spec_accepted += accepted
            arow[0] += k
            arow[1] += accepted
            sampled = self.temps[slot] > 0.0
            for m in range(accepted + 1):
                if not self.active[slot]:
                    break  # retired mid-window: the rest is truncated
                self._occupancy_sum += 1
                if sampled:
                    tok = (int(d_toks[m, slot]) if m < accepted
                           else int(res[slot]))
                else:
                    tok = int(t_toks[slot, m])
                self._record_token(slot, tok, bool(t_oks[slot, m]))

    def _decode_tick(self):
        tm = self._tm_on and telemetry.enabled()
        if tm:
            t0 = time.perf_counter()
            slots = int(self.active.sum())
            toks0 = self._tokens_emitted
        if self.speculate_k > 0 and self.draft_gen is not None:
            self._spec_step()
        else:
            self._decode_step()
        if tm:
            # one engine-track span per decode dispatch: the fleet
            # timeline shows each replica's chunk cadence without
            # per-token events
            telemetry.tracer().complete(
                "decode_chunk", t0, time.perf_counter() - t0,
                track=self._tm_track, slots=slots,
                tokens=self._tokens_emitted - toks0)

    def step(self) -> bool:
        """One scheduler tick: admit what fits (unless draining), then one
        slot-decode step if any slot is live. Returns whether
        PROGRESSABLE work remains — on a draining engine only live slots
        count (the frozen queue can never be admitted here), so a
        while-step loop always terminates. Holds the engine lock for the
        whole tick: concurrent submit()/stats() callers serialize behind
        it (thread-per-replica routers drive step from one thread, so
        the tick itself never contends)."""
        try:
            with self._lock:
                if not self._draining:
                    self._admit()
                # mid-prefill slots spend their per-tick chunk budget
                # between admit and the decode dispatch — draining
                # included (an admitted request is never cancelled, so
                # a drain must finish its prefill to retire it)
                self._prefill_tick()
                if self.active.any():
                    self._decode_tick()
                if self._draining:
                    out = bool(self.active.any()) or bool(self._partial)
                else:
                    out = self.pending()
        except Exception as e:  # noqa: BLE001 — an uncaught engine
            #   exception is a flight-recorder trigger (the lock is
            #   released by the time we get here; trip() only schedules,
            #   so the bundle's stats source cannot deadlock)
            if self._tm_on:
                flightrec.trip(
                    "engine_exception", exc=e,
                    replica=self._tm_labels["replica"],
                    error=f"{type(e).__name__}: {e}")
            raise
        if self._tm_on:
            # serving-side SLO tick: one predicate + one time compare
            # until a full window has elapsed
            flightrec.slo_monitor().maybe_evaluate()
        return out

    def run(self, prompts=None, max_new_tokens: int = 32,
            **submit_kw) -> List[Request]:
        """Submit `prompts` (list of 1-D int32 arrays) and drive the
        scheduler until the engine is idle; returns THIS call's requests
        in submission order (with prompts=None: whatever was pending at
        entry). Extra kwargs (temperature/top_p/top_k/seed/adapter)
        forward to submit(). The engine holds no reference to retired
        requests."""
        if prompts is not None:
            batch = [self.submit(p, max_new_tokens, **submit_kw)
                     for p in prompts]
        else:
            batch = [r for r in self.slot_req if r is not None] \
                + list(self._queue)
        while self.step():
            pass
        return batch

    # ---- graceful shutdown --------------------------------------------------

    def drain(self) -> Dict:
        """Graceful shutdown (the serving half of elastic recovery: a
        preemption notice or planned restart must not drop tokens already
        being decoded): stop admitting new requests, run the decode loop
        until every in-flight slot retires on eos/length/failure, and
        return a final stats snapshot. Requests still QUEUED (never
        admitted) stay queued untouched — the caller re-submits them to
        the replacement engine; their count rides the snapshot. Idempotent
        — a second drain() finds no live slots and returns the snapshot
        again."""
        with self._lock:
            self._draining = True
        while True:
            # lock per tick, not across the drain: submit() callers get a
            # prompt RuntimeError instead of blocking on the whole drain
            with self._lock:
                if not self.active.any() and not self._partial:
                    break
                # a mid-prefill slot is in-flight work too: finish its
                # chunks (deadline sweep included) so it can decode and
                # retire — drain never strands a half-prefilled request
                self._prefill_tick()
                if self.active.any():
                    self._decode_tick()
        if self.prefix_cache is not None:
            # quiesce the ordered tier publisher: a drained engine owes
            # no in-flight D2H migrations (and the leak check below must
            # see final tier state)
            self.prefix_cache.wait_migrations()
        with self._lock:
            snap = self.stats()
            snap["drained"] = True
            snap["queued"] = len(self._queue)
        fflogger.info(
            "serving: drained — %d completed, %d failed, %d still queued "
            "(re-submit to the replacement engine), occupancy %.2f, "
            "%d recompiles", snap["completed"], snap["failed"],
            snap["queued"], snap["occupancy"], snap["recompiles"])
        return snap

    def reclaim_queued(self) -> List["Request"]:
        """Pull every queued-never-admitted request OUT of this engine
        and return it — the missing half of the drain() contract
        (ISSUE 20 bugfix): drain() deliberately parks queued requests
        for the caller to re-submit, but the fleet's scale-in path never
        collected them, stranding work on a retiring engine. A retiring
        or preempted replica's owner calls this (before or after the
        drain — the queue gate is the engine lock either way) and
        requeues the returned requests on survivors. The requests are
        untouched: never admitted, no slots, no pages, no counters to
        unwind."""
        with self._lock:
            out = list(self._queue)
            del self._queue[:]
            return out

    def reopen(self):
        """Readmit after a drain() (ISSUE 17 satellite: drain used to be
        terminal). The drained engine's slots are all free and its
        counters/pages consistent — reopening is just lifting the
        admission gate; queued requests (if any survived the drain
        untouched) admit on the next tick, and ``submit()`` works again.
        Idempotent; a no-op on an engine that was never drained."""
        with self._lock:
            self._draining = False
            if self.deploy_state == "draining":
                self.deploy_state = "serving"
        fflogger.info("serving: reopened — admitting again (version %s)",
                      self.weight_version)

    def swap_weights(self, params, version: str) -> Dict:
        """Hot-swap this engine's serving weights in place (ISSUE 17):
        install ``params`` (a device tree matching ``model.params`` in
        structure/shape/dtype — same geometry, so every warm fixed-shape
        program stays valid and nothing retraces) as the generator's
        per-engine override, re-quantize ONCE if this is a quantized
        tier, and flush the prefix cache (a drained engine holds every
        cached page at refcount 0, so the flush is total; stale-KV
        safety does not depend on it — the version salt already
        partitions the trie). ``params=None`` reverts to the shared
        ``model.params`` (rollback to the construction-time weights).

        The engine must be DRAINED: swapping under live slots would
        hand in-flight decodes a mid-stream weight change.

        FF_FAULT=swap_fail@deploy:<n> dies AFTER the install — the torn
        mid-swap drill; the deployer catches it, restores the prior
        version and rolls the whole deploy back."""
        with self._lock:
            if self.active.any():
                raise RuntimeError(
                    "swap_weights: engine has live slots — drain() first "
                    "(a mid-stream weight change corrupts in-flight "
                    "decodes)")
            prev = (self.gen._params_override, self.weight_version)
            self.deploy_state = "swapping"
            try:
                self.gen.set_params(params)
                if self.gen.quantize:
                    # re-quantize once, now, under the swap — admission
                    # and decode never pay the quantization pass
                    self.gen._quantized_params()
                faultinject.maybe_fail("swap_fail", "deploy")
            except BaseException:
                # restore the prior weights before re-raising: a failed
                # swap must leave the engine serving what it served
                self.gen.set_params(prev[0])
                if self.gen.quantize:
                    self.gen._quantized_params()
                self.deploy_state = "serving"
                raise
            self.weight_version = str(version)
            self._weight_swaps += 1
            flushed = self.flush_prefix_cache()
            self.deploy_state = "serving"
        fflogger.info(
            "serving: weight swap -> %s (%d cached pages flushed, "
            "swap #%d)", self.weight_version, flushed, self._weight_swaps)
        return {"version": self.weight_version, "flushed_pages": flushed,
                "swaps": self._weight_swaps}

    def health(self) -> Dict:
        """Cheap liveness/readiness probe for a router: admission status
        plus the load counters a balancer steers by, sliced from the one
        ``stats()`` snapshot so the two probes share every formula and
        key name. Never compiles or touches the device. Serializes
        behind a running tick — for a contention-free mid-tick load
        estimate use ``load()``."""
        with self._lock:
            active = int(self.active.sum())
            if self._draining:
                # the frozen queue does not hold "draining": those
                # requests can never be admitted here (they belong to the
                # replacement engine), so the drain is over when the live
                # slots are
                status = "draining" if active else "drained"
            else:
                status = "busy" if (active or self._queue) else "idle"
            snap = self.stats()
            return {
                "status": status,
                "admitting": not self._draining,
                "active_slots": active,
                "queued": len(self._queue),
                "weight_version": self.weight_version,
                "deploy_state": self.deploy_state,
                **{k: snap[k] for k in ("serve_slots", "free_pages",
                                        "completed", "failed", "timeouts",
                                        "occupancy", "recompiles",
                                        "pages_in_use", "kv_pages_shared",
                                        "prefix_hit_rate",
                                        "spec_accept_rate",
                                        "kv_cache_dtype", "weight_dtype",
                                        "kv_bytes_per_token",
                                        "tokens_per_pool_gb")},
            }

    def load(self) -> Dict:
        """Lock-free load snapshot for a router's dispatch loop: active
        slots + queue depth, read WITHOUT the engine lock so a dispatcher
        never blocks behind a replica mid-tick. The reads race the owning
        thread by design — a balancer steering on slightly stale load is
        correct; a balancer stalled behind every decode dispatch is not."""
        return {"active_slots": int(self.active.sum()),
                "queued": len(self._queue)}

    # ---- metrics ------------------------------------------------------------

    def flush_prefix_cache(self) -> int:
        """Evict EVERY refcount-0 cached page back to the free list;
        returns the number reclaimed. For weight hot-swap (cached KV is
        stale under new weights) and for page-leak accounting: after
        drain() + flush, free_pages must equal kv_pages - 1. Pages still
        mounted by live requests survive (and stay cached)."""
        if self.prefix_cache is None:
            return 0
        with self._lock:
            freed = self.prefix_cache.evict(self.num_pages, pressure=False)
            self._free_pages.extend(freed)
            return len(freed)

    def stats(self) -> Dict:
        with self._lock:
            return self._stats_locked()

    def _stats_locked(self) -> Dict:
        pc = self.prefix_cache
        ttfts = sorted(self._ttfts)  # bounded window of completions

        def pct(p):
            if not ttfts:
                return 0.0
            return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))]

        return {
            "requests": self._submitted,
            "completed": self._completed,
            "failed": self._failed,
            "timeouts": self._timeouts,
            # rolling-deploy identity (ISSUE 17): the weight version this
            # engine serves, where it stands in a roll, and how many
            # in-place swaps it has taken (keys pinned)
            "weight_version": self.weight_version,
            "deploy_state": self.deploy_state,
            "weight_swaps": self._weight_swaps,
            "tokens_generated": self._tokens_emitted,
            "decode_steps": self.decode_steps,
            "recompiles": self.recompile_count,
            # post-warmup jit cache misses the ffsan sentinel saw
            # (0 unless FF_SANITIZE is on and a warm program
            # retraced — the smokes assert this stays 0)
            "sanitizer_retraces": self._retrace.hits,
            # mean fraction of computed positions doing USEFUL work per
            # decode step (mid-chunk retirements stop counting) — the
            # engine's steady-state utilization headline. Under
            # speculation the denominator counts all K+1 verify
            # positions, so occupancy folds the accept rate in
            # ((1 + aK)/(K+1) on a saturated engine): it measures wasted
            # COMPUTE, not idle slots — a router balancing on busyness
            # should use active_slots/queued (health()) and read
            # spec_accept_rate separately. occupied_slot_steps is the
            # raw numerator so callers can compute occupancy over a
            # WINDOW from two stats() snapshots
            "occupancy": (self._occupancy_sum
                          / max(1, self.decode_steps) / self.slots),
            "occupied_slot_steps": self._occupancy_sum,
            "ttft_p50_ms": round(pct(0.50) * 1e3, 3),
            "ttft_p99_ms": round(pct(0.99) * 1e3, 3),
            "free_pages": len(self._free_pages),
            "kv_pages": self.num_pages,
            "kv_page_size": self.page_size,
            "serve_slots": self.slots,
            # quantized-tier observability (ISSUE 11): what the pool and
            # weights are stored as, what a token of KV costs in HBM
            # (scales included), how many tokens a GB of pool holds, and
            # the capacity multiplier vs a bf16 pool of the same
            # geometry — effective page capacity = kv_page_size x that
            # multiplier in bf16-equivalent tokens per page's bytes.
            # These are the router/bench placement signals: a quantized
            # replica advertises more tokens per byte, not more bytes.
            "kv_cache_dtype": self.kv_cache_dtype,
            "weight_dtype": self.weight_dtype,
            "kv_pool_bytes": self._pool_bytes,
            "kv_bytes_per_token": round(self._kv_bytes_per_token, 3),
            "tokens_per_pool_gb": int((1 << 30)
                                      / self._kv_bytes_per_token),
            "kv_capacity_vs_bf16": round(
                self._bf16_bytes_per_token / self._kv_bytes_per_token, 3),
            "kv_effective_page_capacity": round(
                self.page_size * self._bf16_bytes_per_token
                / self._kv_bytes_per_token, 1),
            # KV-pool observability (ROADMAP item 1: the router balances
            # on these): in-use counts every non-free page (live-private
            # + cached), cached the pages the radix trie holds (warm,
            # reclaimable at refcount 0), shared those mounted by >1
            # live request right now
            "pages_in_use": self.num_pages - 1 - len(self._free_pages),
            "kv_pages_cached": pc.pages if pc else 0,
            "kv_pages_shared": pc.shared_pages() if pc else 0,
            # tiered-cache observability (ISSUE 12): pages by tier (hbm
            # = trie-cached pool pages, host = pinned host copies incl.
            # publishes still in flight), the migration counters the
            # bench/router steer by, and the handoff ledger (prefill-
            # only admissions run for the role split, slabs moved)
            "host_kv_pages": pc.host_pages if pc else 0,
            "kv_pages_hbm": pc.pages if pc else 0,
            "kv_pages_host": pc.host_used if pc else 0,
            "tier_demotions": pc.demotions if pc else 0,
            "tier_promotions": pc.promotions if pc else 0,
            "tier_demote_failures": pc.demote_failures if pc else 0,
            "tier_promote_failures": pc.promote_failures if pc else 0,
            "tier_host_evictions": pc.host_evictions if pc else 0,
            "tier_pending_migrations": (pc.pending_migrations()
                                        if pc else 0),
            "prefill_only_requests": self._prefill_only,
            "prefix_slab_exports": self._slab_exports,
            "prefix_slab_imports": self._slab_imports,
            "prefix_pages_imported": self._import_pages,
            # long-context serving (ISSUE 18): interleaved-admission
            # progress (chunks run between decode ticks, ticks where a
            # long prefill was preempted by the budget, slots currently
            # mid-prefill) and partial-prefix merges (start_page > 0
            # slab imports from sequence-parallel prefill shards)
            "prefill_interleave_chunks": self.prefill_interleave_chunks,
            "prefill_chunks_interleaved":
                self._prefill_chunks_interleaved,
            "prefill_preempted_ticks": self._prefill_preempted_ticks,
            "prefill_partial_slots": len(self._partial),
            "partial_slab_imports": self._partial_slab_imports,
            "prefix_cache": pc is not None,
            "prefix_lookups": pc.lookups if pc else 0,
            "prefix_hits": pc.hits if pc else 0,
            "prefix_hit_rate": (round(pc.hits / max(1, pc.lookups), 4)
                                if pc else 0.0),
            "prefill_tokens_saved": pc.tokens_saved if pc else 0,
            "prefix_evictions": pc.evictions if pc else 0,
            # live references into the trie: must be 0 after drain() —
            # nonzero at idle means a refcount leak
            "prefix_refs_live": pc.live_refs() if pc else 0,
            "speculate_k": self.speculate_k,
            "spec_proposed": self._spec_proposed,
            "spec_accepted": self._spec_accepted,
            "spec_accept_rate": round(
                self._spec_accepted / max(1, self._spec_proposed), 4),
            # per-request sampling + multi-tenant adapter pool
            # (ISSUE 14): requests that sampled (temperature > 0), the
            # engine-level submit() defaults, and the adapter pool's
            # occupancy/fault/eviction ledger (zeros without a pool —
            # the keys are pinned either way). spec_accept_by_adapter
            # mirrors the labeled telemetry series for host callers.
            "sampled_requests": self._sampled_requests,
            "serve_temperature": self.default_temperature,
            "serve_top_p": self.default_top_p,
            "serve_top_k": self.default_top_k,
            "lora_rank": self.lora_rank,
            **(self.lora.stats() if self.lora is not None else {
                "adapter_pool_pages": 0, "adapters_registered": 0,
                "adapters_resident": 0, "adapter_pages_in_use": 0,
                "adapter_pool_occupancy": 0.0, "adapter_lookups": 0,
                "adapter_hits": 0, "adapter_faults": 0,
                "adapter_evictions": 0, "adapter_refs_live": 0}),
            "spec_accept_by_adapter": {
                name: round(v[1] / max(1, v[0]), 4)
                for name, v in self._adapter_spec.items()},
            "requests_by_adapter": dict(self._adapter_requests),
            # decode-attention hot-path observability (ISSUE 7): which
            # impl this engine's programs trace, how many pool pages the
            # last dispatch's attention read (vs the table-width gather
            # the einsum path always re-materializes), and the kernel
            # autotune table's process-wide hit/miss deltas since engine
            # construction (see the baseline note in __init__)
            "paged_attention_impl": self.paged_attention_impl,
            "pages_touched": self._pages_touched,
            "last_pages_touched": self._last_pages_touched,
            **{f"kernel_tune_{k}": v - self._ktune_base.get(k, 0)
               for k, v in _ktune_stats().items()
               if k in ("hits", "misses")},
        }
