"""Continuous-batching serving runtime: slot decode over a paged KV cache.

The reference's only inference story is the training graph run forward-only
(CompMode::COMP_MODE_INFERENCE); runtime/generation.py added the modern
one-program KV-cache decode, but as a FIXED batch: finished rows burn full
decode steps emitting pads, a new request cannot start until the whole
batch retires, and every (prompt shape, max_new_tokens) pair compiles its
own program. This module is the serving-side performance subsystem on top
of it:

  * ONE jitted slot-decode step of fixed shape ``(serve_slots, 1)`` runs
    for the life of the engine — the compiled program never changes shape,
    the HOST scheduler moves work in and out of slots (the partition-
    don't-pad philosophy applied to serving: keep XLA static, move the
    raggedness to the host).
  * The KV cache is a POOL of ``(kv_pages, kv_page_size, KVH, Dh)`` blocks
    with a per-slot page table (ops/attention.py paged_decode_forward):
    long and short requests share HBM instead of every slot preallocating
    ``max_seq_len``. Pages are allocated at admission and freed at
    retirement; page 0 is a scratch page inactive slots harmlessly write.
  * Admission prefills the prompt into the slot's pages through the
    EXISTING prefill path (Generator._prefill, chunked via chunk_forward
    when ``prefill_chunk`` is set) on a contiguous per-request cache, then
    scatters that k/v into the pool — prefill numerics are therefore
    identical to batch generate's, and greedy continuous batching is
    token-identical to per-request Generator.generate
    (tests/test_serving.py).
  * Prompt lengths are rounded up to SHAPE BUCKETS (powers of two by
    default, ``decode_buckets`` to pin explicit boundaries) so warm
    prefill programs are reused across mixed lengths; ``recompile_count``
    exposes every program build, and after bucket warmup it stays flat.
  * Every compiled program returns a per-slot finiteness flag computed
    in-graph; a request whose logits go non-finite (e.g. FF_FAULT
    ``nan_loss@serve:<n>`` poisons the n-th admitted request) is retired
    as ``failed`` without stalling the other slots — serving inherits the
    fault-injection story of runtime/faultinject.py.
  * ``drain()``/``health()``: graceful shutdown for deploys and elastic
    topology changes (docs/resilience.md) — stop admitting, finish the
    in-flight slots, final stats snapshot; queued-but-unadmitted requests
    stay queued for re-submission to the replacement engine.

Per-slot cache layout (identical to the ragged rule of
MultiHeadAttention.decode_forward, with a per-slot prompt pad width):
logical positions ``[0, row_len)`` hold the true prompt, ``[row_len,
prompt_pad)`` hold masked bucket-pad garbage, decode tokens append from
``prompt_pad``; RoPE positions stay LOGICAL (``row_len + emitted``).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu._env import compilation_cache_entries
from flexflow_tpu.logger import fflogger
from flexflow_tpu.runtime import faultinject
from flexflow_tpu.runtime.generation import Generator


@dataclass
class Request:
    """One serving request and its full lifecycle record."""

    rid: int
    prompt: np.ndarray              # (S,) int32, true (unpadded) prompt
    max_new_tokens: int
    state: str = "queued"           # queued | running | done | failed
    tokens: List[int] = field(default_factory=list)  # emitted tokens
    slot: int = -1
    bucket: int = 0
    pages: List[int] = field(default_factory=list)
    t_submit: float = 0.0
    ttft: float = 0.0               # submit -> first emitted token (s)
    t_done: float = 0.0
    error: str = ""

    @property
    def output(self) -> np.ndarray:
        """prompt + emitted tokens, the shape generate() would return
        for this request alone (minus trailing pads it never emitted)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])


def _pow2_bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class ServingEngine:
    """Continuous-batching engine over a compiled FFModel decoder LM.

    Build once (after model.compile()); ``submit()`` requests and drive
    ``step()`` yourself, or hand ``run()`` a list of prompts. Construction
    knobs default to the model's FFConfig (serve_slots, kv_page_size,
    kv_pages, decode_buckets)."""

    def __init__(self, model, serve_slots: Optional[int] = None,
                 kv_page_size: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 decode_buckets: Optional[List[int]] = None,
                 max_seq_len: int = 1024, temperature: float = 0.0,
                 top_k: int = 0, eos_id: Optional[int] = None,
                 pad_id: int = 0, prefill_chunk: int = 0,
                 decode_chunk: int = 8,
                 quantize: Optional[str] = None, seed: int = 0):
        cfg = model.config
        self.model = model
        self.slots = int(serve_slots or getattr(cfg, "serve_slots", 4))
        # decode steps per device dispatch (an in-graph lax.scan): host
        # round-trips amortize over the chunk — the per-token dispatch of
        # chunk=1 dominates small-model decode. Retirement granularity
        # coarsens to the chunk; tokens a slot computes past its own
        # eos/length are truncated by the host, so outputs are identical
        # at any chunk (tests/test_serving.py). Waste is bounded by
        # chunk-1 steps per retirement, idle-slot time by chunk-1 per
        # admission — keep it well under typical max_new_tokens.
        self.decode_chunk = max(1, int(decode_chunk))
        self.page_size = int(kv_page_size
                             or getattr(cfg, "kv_page_size", 128))
        buckets = (decode_buckets
                   if decode_buckets is not None
                   else getattr(cfg, "decode_buckets", None))
        self.buckets = sorted(int(b) for b in buckets) if buckets else None
        self.max_seq_len = int(max_seq_len)
        self.prefill_chunk = int(prefill_chunk)
        if self.slots < 1 or self.page_size < 1 or self.max_seq_len < 2:
            raise ValueError(
                f"serve_slots={self.slots}, kv_page_size={self.page_size},"
                f" max_seq_len={self.max_seq_len}: all must be positive "
                f"(max_seq_len >= 2)")
        self.pages_per_slot = math.ceil(self.max_seq_len / self.page_size)
        want_pages = 1 + self.slots * self.pages_per_slot  # +1: scratch
        self.num_pages = int(kv_pages or getattr(cfg, "kv_pages", 0)
                             or want_pages)
        if self.num_pages < 1 + self.pages_per_slot:
            raise ValueError(
                f"kv_pages={self.num_pages} cannot hold even one "
                f"max_seq_len={self.max_seq_len} request "
                f"(needs {1 + self.pages_per_slot} incl. scratch page 0)")

        # Generator supplies graph validation, the graph walk, prefill and
        # sampling — serving adds scheduling + the paged pool around them
        self.gen = Generator(model, temperature=temperature, top_k=top_k,
                             eos_id=eos_id, pad_id=pad_id, quantize=quantize)
        self.eos_id = eos_id
        self.pad_id = pad_id
        cdtype = self.gen._compute_dtype()
        # the pool is COMMITTED (replicated on the model's mesh) up front:
        # an uncommitted fresh pool has a different pjit signature
        # (UnspecifiedValue) than the committed arrays every program
        # RETURNS, so the second call to each warm program would silently
        # retrace and recompile it — a ~0.5 s stall in the serving loop
        # that the recompile counter could not see
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(model.mesh, PartitionSpec(None, None, None,
                                                       None))
        self.pool = {
            op.name: jax.tree.map(
                lambda a: jax.device_put(a, repl),
                op.init_paged_cache(self.num_pages, self.page_size,
                                    cdtype))
            for op in self.gen.attn_ops}
        self._free_pages = list(range(self.num_pages - 1, 0, -1))

        # per-slot scheduler state (host side, shipped to device each step)
        n = self.slots
        self.page_tables = np.zeros((n, self.pages_per_slot), np.int32)
        self.row_len = np.zeros((n,), np.int32)
        self.prompt_pad = np.zeros((n,), np.int32)
        self.emitted = np.zeros((n,), np.int32)
        self.last_tok = np.zeros((n,), np.int32)
        self.active = np.zeros((n,), bool)
        self.poison = np.zeros((n,), np.float32)
        self.slot_req: List[Optional[Request]] = [None] * n

        self._queue: List[Request] = []
        self._draining = False
        self._programs: Dict = {}
        self._key = jax.random.PRNGKey(seed)
        self._next_rid = 0
        self.recompile_count = 0
        self.decode_steps = 0
        self._occupancy_sum = 0
        # aggregate counters instead of retaining every Request: a
        # long-lived engine must not grow memory with total traffic.
        # Retired Request objects are dropped (callers keep their own
        # handles — submit()/run() return them); TTFT percentiles come
        # from a bounded window of recent completions
        self._submitted = 0
        self._completed = 0
        self._failed = 0
        self._tokens_emitted = 0
        import collections

        self._ttfts = collections.deque(maxlen=4096)

    # ---- request lifecycle --------------------------------------------------

    def _bucket(self, prompt_len: int) -> int:
        if self.buckets:
            for b in self.buckets:
                if b >= prompt_len:
                    return b
            raise ValueError(
                f"prompt length {prompt_len} exceeds the largest decode "
                f"bucket {self.buckets[-1]}")
        return _pow2_bucket(prompt_len)

    def submit(self, prompt, max_new_tokens: int) -> Request:
        if self._draining:
            # the serving-side preemption notice: a draining engine is on
            # its way down (elastic restart / deploy) — callers must
            # route new traffic elsewhere, not queue behind a shutdown
            raise RuntimeError(
                "ServingEngine is draining: new requests are not admitted "
                "(health()['status'] exposes this to the router)")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens}: must be >= 1")
        bucket = self._bucket(prompt.size)
        if bucket + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"bucketed prompt ({bucket}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len {self.max_seq_len}")
        req = Request(rid=self._next_rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens), bucket=bucket,
                      t_submit=time.perf_counter())
        self._next_rid += 1
        self._submitted += 1
        self._queue.append(req)
        return req

    def pending(self) -> bool:
        return bool(self._queue) or bool(self.active.any())

    def _retire(self, slot: int, state: str, error: str = ""):
        req = self.slot_req[slot]
        req.state = state
        req.error = error
        req.t_done = time.perf_counter()
        if state == "done":
            self._completed += 1
        else:
            self._failed += 1
        if req.ttft:
            self._ttfts.append(req.ttft)
        self._free_pages.extend(req.pages)
        req.slot = -1
        self.slot_req[slot] = None
        self.active[slot] = False
        self.poison[slot] = 0.0
        self.page_tables[slot, :] = 0   # scratch page: dead writes land there
        self.row_len[slot] = 0
        self.prompt_pad[slot] = 0
        self.emitted[slot] = 0

    def _record_token(self, slot: int, tok: int, ok: bool):
        """Append a sampled token to the slot's request and retire on
        non-finite logits, eos, or length — shared by prefill/decode."""
        req = self.slot_req[slot]
        if not ok:
            self._retire(slot, "failed", "non-finite logits")
            return
        req.tokens.append(int(tok))
        self._tokens_emitted += 1
        if not req.ttft:
            req.ttft = time.perf_counter() - req.t_submit
        self.emitted[slot] += 1
        self.last_tok[slot] = tok
        if (self.eos_id is not None and tok == self.eos_id) \
                or len(req.tokens) >= req.max_new_tokens:
            self._retire(slot, "done")

    # ---- compiled programs --------------------------------------------------

    def _compiled_call(self, key, build, *args):
        """Program-cache lookup; a miss builds + runs the program and
        bumps recompile_count, logging whether jax's persistent
        compilation cache (FFConfig.compilation_cache_dir) absorbed the
        compile. Every shape-affecting datum is part of `key`, so this
        counter is exactly the number of XLA compiles the engine caused."""
        fn = self._programs.get(key)
        if fn is not None:
            return fn(*args)
        fn = self._programs[key] = build()
        self.recompile_count += 1
        cache_dir = getattr(self.model.config, "compilation_cache_dir", "")
        before = compilation_cache_entries(cache_dir) if cache_dir else 0
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        if cache_dir:
            grew = compilation_cache_entries(cache_dir) - before
            fflogger.info(
                "serving: compiled %r in %.2fs — persistent cache %s",
                key, dt, f"MISS (+{grew} entries)" if grew > 0 else "HIT")
        else:
            fflogger.info("serving: compiled %r in %.2fs", key, dt)
        return out

    def _build_prefill(self, bucket: int, n_pages: int):
        gen = self.gen
        cdtype = gen._compute_dtype()

        def prefill(params, state, tokens, length, pool, pages, poison,
                    key):
            caches = {op.name: op.init_cache(1, bucket, cdtype)
                      for op in gen.attn_ops}
            logits, caches = gen._prefill(params, state, tokens, caches,
                                          length, self.prefill_chunk)
            logits = logits[:, -1] + poison            # (1, V)
            ok = jnp.isfinite(logits).all(axis=-1)
            tok, _ = gen._sample(logits, key)
            new_pool = {
                op.name: op.paged_prefill_write(
                    pool[op.name], caches[op.name]["k"],
                    caches[op.name]["v"], pages)
                for op in gen.attn_ops}
            return tok, ok, new_pool

        return jax.jit(prefill, donate_argnums=(4,))

    def _build_decode(self, n_steps: int):
        gen = self.gen

        def decode(params, state, pool, page_table, last_tok, write_pos0,
                   rope_pos0, row_len, prompt_pad, budget, poison, key):
            """`n_steps` slot-decode steps as ONE in-graph scan. Past a
            slot's own budget (prompt_pad + its max_new_tokens) the write
            position and RoPE clamp to the final allocated slot — those
            steps only produce tokens the host truncates, and the
            repeated overwrite stays inside the slot's own pages."""
            rope_cap = budget - prompt_pad + row_len - 1

            def body(carry, i):
                pool, tok, key = carry
                paged = {
                    "page_table": page_table,
                    "write_pos": jnp.minimum(write_pos0 + i, budget - 1),
                    "rope_pos": jnp.minimum(rope_pos0 + i, rope_cap),
                    "row_len": row_len, "prompt_pad": prompt_pad}
                logits, pool = gen._walk(params, state, tok[:, None],
                                         pool, None, paged=paged)
                logits = logits[:, 0] + poison[:, None]  # (B_slots, V)
                ok = jnp.isfinite(logits).all(axis=-1)
                key, sub = jax.random.split(key)
                nxt, _ = gen._sample(logits, sub)
                return (pool, nxt, key), (nxt, ok)

            (pool, _, _), (toks, oks) = jax.lax.scan(
                body, (pool, last_tok, key),
                jnp.arange(n_steps, dtype=jnp.int32))
            return toks, oks, pool                     # (n_steps, B_slots)

        return jax.jit(decode, donate_argnums=(2,))

    def _split_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    # ---- the scheduler loop -------------------------------------------------

    def _admit(self):
        """Move queued requests into free slots: allocate pages, prefill
        the prompt (bucket-shaped program) into them, seed the slot."""
        while self._queue:
            try:
                slot = next(i for i in range(self.slots)
                            if not self.active[i])
            except StopIteration:
                return
            req = self._queue[0]
            total = req.bucket + req.max_new_tokens
            n_total = math.ceil(total / self.page_size)
            if len(self._free_pages) < n_total:
                # HBM pressure: wait for a retirement to free pages. Head-
                # of-line blocking is deliberate — FIFO admission keeps
                # TTFT fairness; submit() already guarantees the request
                # fits an EMPTY pool, so progress is always possible.
                return
            self._queue.pop(0)
            req.pages = [self._free_pages.pop() for _ in range(n_total)]
            req.slot = slot
            req.state = "running"
            self.slot_req[slot] = req

            n_prefill = math.ceil(req.bucket / self.page_size)
            padded = np.full((1, req.bucket), self.pad_id, np.int32)
            padded[0, :req.prompt.size] = req.prompt
            # fault injection: FF_FAULT=nan_loss@serve:<n> poisons the
            # n-th ADMITTED request in-graph (NaN added to its logits), so
            # the detect-and-retire path runs end to end, not a host stub
            if faultinject.active_plan().fire("nan_loss", "serve"):
                self.poison[slot] = np.float32(np.nan)
            table = np.zeros((self.pages_per_slot,), np.int32)
            table[:n_total] = req.pages
            self.page_tables[slot] = table
            self.row_len[slot] = req.prompt.size
            self.prompt_pad[slot] = req.bucket
            self.emitted[slot] = 0

            tok, ok, self.pool = self._compiled_call(
                ("prefill", req.bucket, n_prefill, self.prefill_chunk),
                lambda: self._build_prefill(req.bucket, n_prefill),
                self.gen._params(), self.model.bn_state, padded,
                np.asarray([req.prompt.size], np.int32), self.pool,
                np.asarray(req.pages[:n_prefill], np.int32),
                np.float32(self.poison[slot]), self._split_key())
            self.active[slot] = True
            self._record_token(slot, int(np.asarray(tok)[0]),
                               bool(np.asarray(ok)[0]))

    def _decode_step(self):
        k = self.decode_chunk
        write_pos = self.prompt_pad + self.emitted - 1
        rope_pos = self.row_len + self.emitted - 1
        # inactive slots: state arrays are zeroed, so write_pos = -1 would
        # index page -1; clamp to 0 — the write lands in scratch page 0
        write_pos = np.maximum(write_pos, 0).astype(np.int32)
        rope_pos = np.maximum(rope_pos, 0).astype(np.int32)
        # per-slot decode budget: last legal write position + 1. Inactive
        # slots get 1, clamping their scratch writes to position 0
        budget = np.ones((self.slots,), np.int32)
        for slot in range(self.slots):
            req = self.slot_req[slot]
            if req is not None:
                budget[slot] = req.bucket + req.max_new_tokens
        toks, oks, self.pool = self._compiled_call(
            ("decode", k), lambda: self._build_decode(k),
            self.gen._params(), self.model.bn_state, self.pool,
            self.page_tables, self.last_tok, write_pos, rope_pos,
            self.row_len, self.prompt_pad, budget, self.poison,
            self._split_key())
        toks = np.asarray(toks)                        # (k, B_slots)
        oks = np.asarray(oks)
        self.decode_steps += k
        for slot in range(self.slots):
            for t in range(k):
                if not self.active[slot]:
                    break  # retired mid-chunk: later tokens are truncated
                # occupancy counts USEFUL slot-steps only — a slot that
                # retires mid-chunk stops counting, so the metric is not
                # inflated by the truncated past-retirement steps
                self._occupancy_sum += 1
                self._record_token(slot, int(toks[t, slot]),
                                   bool(oks[t, slot]))

    def step(self) -> bool:
        """One scheduler tick: admit what fits (unless draining), then one
        slot-decode step if any slot is live. Returns whether
        PROGRESSABLE work remains — on a draining engine only live slots
        count (the frozen queue can never be admitted here), so a
        while-step loop always terminates."""
        if not self._draining:
            self._admit()
        if self.active.any():
            self._decode_step()
        if self._draining:
            return bool(self.active.any())
        return self.pending()

    def run(self, prompts=None, max_new_tokens: int = 32) -> List[Request]:
        """Submit `prompts` (list of 1-D int32 arrays) and drive the
        scheduler until the engine is idle; returns THIS call's requests
        in submission order (with prompts=None: whatever was pending at
        entry). The engine holds no reference to retired requests."""
        if prompts is not None:
            batch = [self.submit(p, max_new_tokens) for p in prompts]
        else:
            batch = [r for r in self.slot_req if r is not None] \
                + list(self._queue)
        while self.step():
            pass
        return batch

    # ---- graceful shutdown --------------------------------------------------

    def drain(self) -> Dict:
        """Graceful shutdown (the serving half of elastic recovery: a
        preemption notice or planned restart must not drop tokens already
        being decoded): stop admitting new requests, run the decode loop
        until every in-flight slot retires on eos/length/failure, and
        return a final stats snapshot. Requests still QUEUED (never
        admitted) stay queued untouched — the caller re-submits them to
        the replacement engine; their count rides the snapshot. Idempotent
        — a second drain() finds no live slots and returns the snapshot
        again."""
        self._draining = True
        while self.active.any():
            self._decode_step()
        snap = self.stats()
        snap["drained"] = True
        snap["queued"] = len(self._queue)
        fflogger.info(
            "serving: drained — %d completed, %d failed, %d still queued "
            "(re-submit to the replacement engine), occupancy %.2f, "
            "%d recompiles", snap["completed"], snap["failed"],
            snap["queued"], snap["occupancy"], snap["recompiles"])
        return snap

    def health(self) -> Dict:
        """Cheap liveness/readiness probe for a router: admission status
        plus the load counters a balancer steers by, sliced from the one
        ``stats()`` snapshot so the two probes share every formula and
        key name. Never compiles or touches the device."""
        active = int(self.active.sum())
        if self._draining:
            # the frozen queue does not hold "draining": those requests
            # can never be admitted here (they belong to the replacement
            # engine), so the drain is over when the live slots are
            status = "draining" if active else "drained"
        else:
            status = "busy" if (active or self._queue) else "idle"
        snap = self.stats()
        return {
            "status": status,
            "admitting": not self._draining,
            "active_slots": active,
            "queued": len(self._queue),
            **{k: snap[k] for k in ("serve_slots", "free_pages",
                                    "completed", "failed", "occupancy",
                                    "recompiles")},
        }

    # ---- metrics ------------------------------------------------------------

    def stats(self) -> Dict:
        ttfts = sorted(self._ttfts)  # bounded window of completions

        def pct(p):
            if not ttfts:
                return 0.0
            return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))]

        return {
            "requests": self._submitted,
            "completed": self._completed,
            "failed": self._failed,
            "tokens_generated": self._tokens_emitted,
            "decode_steps": self.decode_steps,
            "recompiles": self.recompile_count,
            # mean fraction of slots doing USEFUL work per decode step
            # (mid-chunk retirements stop counting) — the engine's
            # steady-state utilization headline. occupied_slot_steps is
            # the raw numerator so callers can compute occupancy over a
            # WINDOW from two stats() snapshots
            "occupancy": (self._occupancy_sum
                          / max(1, self.decode_steps) / self.slots),
            "occupied_slot_steps": self._occupancy_sum,
            "ttft_p50_ms": round(pct(0.50) * 1e3, 3),
            "ttft_p99_ms": round(pct(0.99) * 1e3, 3),
            "free_pages": len(self._free_pages),
            "kv_pages": self.num_pages,
            "kv_page_size": self.page_size,
            "serve_slots": self.slots,
        }
