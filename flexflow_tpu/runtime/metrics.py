"""PerfMetrics: per-batch training metrics, accumulated across iterations.

Reference: include/metrics_functions.h:28-44 PerfMetrics{train_all,
train_correct, cce_loss, sparse_cce_loss, mse_loss, rmse_loss, mae_loss,
start_time}; computed on-GPU per shard (metrics_functions.cu:57-230) and
reduced through chained Legion futures into a CPU UPDATE_METRICS_TASK
(model.cc:1827-1850). On TPU the per-shard compute + cross-shard reduction is
just sharded jnp reductions inside the jitted step; accumulation across steps
happens on host from the step's returned scalars.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import LossType, MetricsType


@dataclasses.dataclass
class PerfMetrics:
    train_all: int = 0
    train_correct: int = 0
    # denominator for accuracy: number of PREDICTIONS scored (== train_all
    # for per-sample classification; batch x seq for token-level tasks)
    train_pred_total: int = 0
    cce_loss: float = 0.0
    sparse_cce_loss: float = 0.0
    mse_loss: float = 0.0
    rmse_loss: float = 0.0
    mae_loss: float = 0.0
    start_time: float = dataclasses.field(default_factory=time.time)

    def update(self, batch_metrics: Dict[str, float], batch_size: int):
        self.train_all += batch_size
        if "accuracy_count" in batch_metrics:
            self.train_correct += int(batch_metrics["accuracy_count"])
            self.train_pred_total += int(
                batch_metrics.get("accuracy_total", batch_size))
        for k in ("cce_loss", "sparse_cce_loss", "mse_loss", "rmse_loss", "mae_loss"):
            if k in batch_metrics:
                setattr(self, k, getattr(self, k) + float(batch_metrics[k]) * batch_size)

    def report(self, loss_type: LossType, metrics: Sequence[MetricsType]) -> str:
        """Epoch summary in the reference's print style (model.cc:1827-1850)."""
        parts = [f"train_all={self.train_all}"]
        denom = self.train_pred_total or self.train_all
        if MetricsType.METRICS_ACCURACY in metrics and denom:
            acc = 100.0 * self.train_correct / denom
            parts.append(f"accuracy={acc:.2f}% ({self.train_correct}/{denom})")
        n = max(self.train_all, 1)
        if self.sparse_cce_loss:
            parts.append(f"sparse_cce_loss={self.sparse_cce_loss / n:.4f}")
        if self.cce_loss:
            parts.append(f"cce_loss={self.cce_loss / n:.4f}")
        for m in metrics:
            if m == MetricsType.METRICS_MEAN_SQUARED_ERROR and self.mse_loss:
                parts.append(f"mse={self.mse_loss / n:.4f}")
        return "[Metrics] " + " ".join(parts)

    @property
    def accuracy(self) -> float:
        return self.train_correct / max(self.train_pred_total
                                        or self.train_all, 1)


def batch_metrics(loss_type: LossType, metric_types: Sequence[MetricsType],
                  logits, labels,
                  ignore_index: int = None) -> Dict[str, jnp.ndarray]:
    """Per-batch metric values, computed inside the jitted step (sharded).

    ignore_index (FFConfig.metrics_ignore_index): label value excluded
    from token-level accuracy — both the correct count AND the
    denominator — so padded causal-LM batches aren't diluted by pad
    positions. None = count every position."""
    out: Dict[str, jnp.ndarray] = {}
    lab = labels
    for m in metric_types:
        if m == MetricsType.METRICS_ACCURACY:
            # accuracy_total carries the PREDICTION count: for token-level
            # tasks (labels per position, e.g. causal-LM training) it is
            # batch x seq, not batch — without it the epoch report divides
            # token-correct counts by sample counts and prints >100%
            if loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
                li = lab.astype(jnp.int32)
                if li.ndim == logits.ndim:
                    li = li[..., 0]
                pred = jnp.argmax(logits, axis=-1)
                if ignore_index is not None:
                    live = li != ignore_index
                    out["accuracy_count"] = jnp.sum((pred == li) & live)
                    out["accuracy_total"] = jnp.sum(live).astype(jnp.int32)
                else:
                    out["accuracy_count"] = jnp.sum(pred == li)
                    out["accuracy_total"] = jnp.asarray(pred.size, jnp.int32)
            elif loss_type == LossType.LOSS_CATEGORICAL_CROSSENTROPY:
                pred = jnp.argmax(logits, axis=-1)
                out["accuracy_count"] = jnp.sum(pred == jnp.argmax(lab, axis=-1))
                out["accuracy_total"] = jnp.asarray(pred.size, jnp.int32)
            else:
                # regression "accuracy": |err| < 0.5 (metrics_functions.cu MSE path)
                out["accuracy_count"] = jnp.sum(
                    jnp.all(jnp.abs(logits - lab) < 0.5,
                            axis=tuple(range(1, logits.ndim))))
                out["accuracy_total"] = jnp.asarray(logits.shape[0], jnp.int32)
        elif m == MetricsType.METRICS_CATEGORICAL_CROSSENTROPY:
            logp = jax.nn.log_softmax(logits, axis=-1)
            out["cce_loss"] = -jnp.mean(jnp.sum(lab * logp, axis=-1))
        elif m == MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY:
            logp = jax.nn.log_softmax(logits, axis=-1)
            li = lab.astype(jnp.int32)
            if li.ndim == logits.ndim:
                li = li[..., 0]
            out["sparse_cce_loss"] = jnp.mean(
                -jnp.take_along_axis(logp, li[..., None], axis=-1))
        elif m == MetricsType.METRICS_MEAN_SQUARED_ERROR:
            out["mse_loss"] = jnp.mean(jnp.square(logits - lab))
        elif m == MetricsType.METRICS_ROOT_MEAN_SQUARED_ERROR:
            out["rmse_loss"] = jnp.sqrt(jnp.mean(jnp.square(logits - lab)))
        elif m == MetricsType.METRICS_MEAN_ABSOLUTE_ERROR:
            out["mae_loss"] = jnp.mean(jnp.abs(logits - lab))
    return out


_KERAS_METRIC_NAMES = {
    "accuracy": MetricsType.METRICS_ACCURACY,
    "categorical_crossentropy": MetricsType.METRICS_CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy": MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.METRICS_MEAN_SQUARED_ERROR,
    "mse": MetricsType.METRICS_MEAN_SQUARED_ERROR,
    "root_mean_squared_error": MetricsType.METRICS_ROOT_MEAN_SQUARED_ERROR,
    "mean_absolute_error": MetricsType.METRICS_MEAN_ABSOLUTE_ERROR,
}


def metrics_from_names(names) -> List[MetricsType]:
    out = []
    for n in names:
        out.append(n if isinstance(n, MetricsType) else _KERAS_METRIC_NAMES[n])
    return out
