"""SLO-driven fleet autoscaling + ICI/DCN replacement placement.

The fleet's replica count was fixed at router construction; real load
breathes and preemptible TPUs vanish on a deadline. This module closes
the loop the ROADMAP's last open item names: the PR-15 SLO monitor is
the scale TRIGGER, the router's live-membership primitives (ISSUE 20:
``add_replica``/``remove_replica``/``request_preempt``) are the
ACTUATORS, and the PR-9 machine model (search/machine.py, "Beyond Data
and Model Parallelism") PRICES where a replacement lands.

``AutoscalePolicy`` is deliberately dumb-and-auditable — a windowed
hysteresis controller, not a forecaster:

  * SCALE OUT when a ``queue_wait_p99``/``ttft_p99`` SLO breach persists
    across ``autoscale_breach_windows`` consecutive policy windows (one
    window = one SLO evaluation, FFConfig.slo_window_s) — a single bad
    window never grows the fleet;
  * SCALE IN when the fleet sits fully idle (nothing queued, nothing
    outstanding, no breach) for ``autoscale_idle_windows`` consecutive
    windows — capacity steps down only after sustained calm;
  * HYSTERESIS everywhere: breach and idle streaks reset each other,
    every action zeroes both and starts ``autoscale_cooldown_s`` during
    which no further action fires, and ``autoscale_min_replicas`` /
    ``autoscale_max_replicas`` bound the fleet — a breach storm thrashes
    counters, never replicas.

Drive it with ``start()`` (a daemon thread ticking every policy window)
or call ``tick()`` directly for deterministic stepping (what the tests
and the elastic_serve smoke do). The policy registers itself on the
``/healthz`` rollup (controller state is operational state) and exports
``ff_autoscale_*`` series at scrape time.

``PlacementAdvisor`` prices a replacement replica's state inheritance —
the evacuation bytes a retiree hands over, or the warm prefix state a
newcomer wants nearby — through ``MachineModel.p2p_time`` on both
interconnect tiers. The advice (prefer ICI while its modeled transfer
fits the warmup budget; fall back to DCN otherwise) rides every scale
event and the health row, so placement is a recorded, priced decision
rather than an implicit default.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

from flexflow_tpu.logger import fflogger
from flexflow_tpu.runtime import flightrec, locks, telemetry
from flexflow_tpu.search.machine import MachineModel

# the SLO series that mean "not enough serving capacity" — the only two
# an autoscaler may act on (hit-rate or accept-rate SLOs are quality
# regressions more capacity cannot fix)
_SCALE_SLOS = ("queue_wait_p99", "ttft_p99")

# fallback per-page byte estimate for placement pricing before the fleet
# has observed a real evacuation (one KV page of a small bf16 model;
# refined from the router's evacuation ledger as soon as one exists)
_DEFAULT_PAGE_BYTES = 64 * 1024


class PlacementAdvisor:
    """Price where a replacement/scale-out replica should land.

    ``place(nbytes)`` models moving ``nbytes`` of inherited state (page
    slabs, adapter weights) to a replica on the same ICI domain vs
    across hosts on DCN, via the measured-constant interconnect model
    the search already trusts (search/machine.py). ICI wins while its
    modeled transfer time fits ``budget_s`` (a warmup-scale bound);
    past that the advisor still ranks the tiers so the caller can see
    exactly what the cheap tier would have cost."""

    def __init__(self, machine: Optional[MachineModel] = None,
                 budget_s: float = 1.0):
        self.machine = machine or MachineModel()
        self.budget_s = float(budget_s)

    def place(self, nbytes: int) -> Dict:
        ici_s = self.machine.p2p_time(float(nbytes), cross_host=False)
        dcn_s = self.machine.p2p_time(float(nbytes), cross_host=True)
        tier = "ici" if ici_s <= self.budget_s else "dcn"
        return {"tier": tier, "state_bytes": int(nbytes),
                "ici_s": round(ici_s, 6), "dcn_s": round(dcn_s, 6),
                "dcn_penalty_x": round(dcn_s / max(ici_s, 1e-12), 2)}


class AutoscalePolicy:
    """The windowed-hysteresis autoscaler over one ``ServingRouter``.

    Lock order: the policy's own lock ranks ``autoscale`` (7) — above
    ``deploy``, below ``router`` — and is NEVER held across an actuator
    call: ``tick()`` decides under its lock, then acts (add/remove
    replica, each taking router + engine locks) outside it, serialized
    by the single-admission ``_acting`` latch instead."""

    def __init__(self, router, config=None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 breach_windows: Optional[int] = None,
                 idle_windows: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 advisor: Optional[PlacementAdvisor] = None):
        cfg = config if config is not None else router.model.config

        def knob(val, name, default):
            return val if val is not None else getattr(cfg, name, default)

        self.router = router
        self.min_replicas = int(knob(min_replicas,
                                     "autoscale_min_replicas", 1))
        self.max_replicas = int(knob(max_replicas,
                                     "autoscale_max_replicas", 8))
        self.breach_windows = int(knob(breach_windows,
                                       "autoscale_breach_windows", 2))
        self.idle_windows = int(knob(idle_windows,
                                     "autoscale_idle_windows", 6))
        self.cooldown_s = float(knob(cooldown_s,
                                     "autoscale_cooldown_s", 30.0))
        self.interval_s = float(interval_s if interval_s is not None
                                else getattr(cfg, "slo_window_s", 10.0))
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas={self.min_replicas}: must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas={self.max_replicas}: must be >= "
                f"min_replicas ({self.min_replicas})")
        self.advisor = advisor or PlacementAdvisor(
            MachineModel(dcn_axes=dict(
                getattr(cfg, "dcn_mesh_shape", None) or {})))
        self._lock = locks.make_lock("autoscale")
        self._breach_streak = 0
        self._idle_streak = 0
        self._last_action = ""
        self._last_action_t = 0.0       # monotonic; 0 = never acted
        self._breach_windows_total = 0
        self._idle_windows_total = 0
        self._cooldown_blocks = 0
        self._bound_blocks = 0
        self._scale_outs = 0
        self._scale_ins = 0
        self._events: collections.deque = collections.deque(maxlen=64)
        self._acting = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tm_on = getattr(cfg, "telemetry", "on") != "off"
        if self._tm_on:
            telemetry.registry().add_collector(self._tm_collect)
            flightrec.register_health_source(self._health_probe)

    # ---- the policy ------------------------------------------------------

    def tick(self) -> Optional[str]:
        """One policy evaluation: fold the current SLO verdict and fleet
        load into the streaks, then act if a threshold crossed. Returns
        the action taken (``"scale_out"``/``"scale_in"``) or None.
        Deterministic given the monitor's window state — the smoke and
        tests call this directly instead of racing the loop thread."""
        slo = flightrec.slo_monitor()
        slo.maybe_evaluate()
        breaches = [b for b in slo.breaches()
                    if b["slo"] in _SCALE_SLOS]
        h = self.router.health()
        busy = bool(h["queued"] or h["outstanding"])
        alive = h["alive"]
        now = time.monotonic()
        with self._lock:
            if breaches:
                self._breach_streak += 1
                self._breach_windows_total += 1
                self._idle_streak = 0
            elif not busy:
                self._idle_streak += 1
                self._idle_windows_total += 1
                self._breach_streak = 0
            else:
                # healthy under load: neither pressure nor calm
                self._breach_streak = 0
                self._idle_streak = 0
            cooling = (self._last_action_t
                       and now - self._last_action_t < self.cooldown_s)
            action = None
            if self._breach_streak >= self.breach_windows:
                if alive >= self.max_replicas:
                    self._bound_blocks += 1
                elif cooling:
                    self._cooldown_blocks += 1
                else:
                    action = "scale_out"
            elif self._idle_streak >= self.idle_windows:
                if alive <= self.min_replicas:
                    self._bound_blocks += 1
                elif cooling:
                    self._cooldown_blocks += 1
                else:
                    action = "scale_in"
        if action is None:
            return None
        if self._acting.is_set():
            return None     # an actuator call is already in flight
        self._acting.set()
        try:
            return self._act(action, breaches)
        finally:
            self._acting.clear()

    def _act(self, action: str, breaches) -> Optional[str]:
        advice = self.advisor.place(self._est_state_bytes())
        try:
            if action == "scale_out":
                r = self.router.add_replica()
            else:
                r = self._pick_retiree()
                if r is None:
                    return None
                self.router.remove_replica(r)
        except Exception as e:  # noqa: BLE001 — a failed actuation must
            #   not kill the policy loop; the streaks re-trigger it
            fflogger.warning("autoscale: %s failed (%s)", action, e)
            return None
        event = {"action": action, "replica": r,
                 "t": time.time(), "placement": advice,
                 "breached": sorted({b["slo"] for b in breaches})}
        with self._lock:
            if action == "scale_out":
                self._scale_outs += 1
            else:
                self._scale_ins += 1
            self._breach_streak = 0
            self._idle_streak = 0
            self._last_action = action
            self._last_action_t = time.monotonic()
            self._events.append(event)
        if self._tm_on:
            telemetry.tracer().instant(
                "autoscale", track="router", action=action, replica=r,
                tier=advice["tier"])
        fflogger.info(
            "autoscale: %s -> replica %d (placement %s: ici %.3gs vs "
            "dcn %.3gs for %d inherited bytes)", action, r,
            advice["tier"], advice["ici_s"], advice["dcn_s"],
            advice["state_bytes"])
        return action

    def _pick_retiree(self) -> Optional[int]:
        """Retire the least-loaded, least-prefix-hot live replica —
        evacuation then moves the least state. Suspended/canary replicas
        are the deployer's business, never the autoscaler's."""
        st = self.router.stats()
        rows = [r for r in st["per_replica"]
                if not r["fenced"] and not r["retired"]
                and not r["suspended"]]
        if len(rows) <= self.min_replicas:
            return None
        rows.sort(key=lambda r: (r["outstanding"], r["queued"],
                                 -r["replica"]))
        return rows[0]["replica"]

    def _est_state_bytes(self) -> int:
        """Bytes a replacement inherits, for placement pricing: the
        fleet's observed per-page evacuation cost (its own ledger) times
        the pages one replica holds — falling back to a nominal page
        size before any evacuation has been measured."""
        st = self.router.stats()
        pages = sum(st["fleet"]["pages_by_tier"].values())
        per_replica_pages = pages / max(1, st["alive"])
        if st["evacuated_pages"]:
            per_page = st["evacuation_bytes"] / st["evacuated_pages"]
        else:
            per_page = _DEFAULT_PAGE_BYTES
        return int(per_replica_pages * per_page)

    # ---- lifecycle -------------------------------------------------------

    def start(self):
        """Spawn the policy loop (one tick per ``interval_s``);
        idempotent."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ff-autoscale")
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001
                fflogger.warning("autoscale: tick failed (%s)", e)

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # ---- observability ---------------------------------------------------

    def state(self) -> Dict:
        """Controller state (keys pinned — the /healthz row and the
        smoke's assertion surface)."""
        with self._lock:
            cooldown_left = 0.0
            if self._last_action_t:
                cooldown_left = max(
                    0.0, self.cooldown_s
                    - (time.monotonic() - self._last_action_t))
            return {
                "breach_streak": self._breach_streak,
                "idle_streak": self._idle_streak,
                "breach_windows": self.breach_windows,
                "idle_windows": self.idle_windows,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "cooldown_s": self.cooldown_s,
                "cooldown_remaining_s": round(cooldown_left, 3),
                "last_action": self._last_action,
                "scale_outs": self._scale_outs,
                "scale_ins": self._scale_ins,
                "cooldown_blocks": self._cooldown_blocks,
                "bound_blocks": self._bound_blocks,
                "events": list(self._events),
            }

    def _health_probe(self) -> Dict:
        # deliberately no "alive"/"replicas"/"fenced"/"status" keys:
        # those would alias the rollup's fleet-degradation heuristics —
        # the router's own row covers the fleet
        st = self.state()
        st.pop("events", None)
        return {"kind": "autoscaler", **st}

    def _tm_collect(self, reg):
        st = self.state()
        reg.gauge("ff_autoscale_scale_outs",
                  "autoscaler-initiated replica additions"
                  ).set(st["scale_outs"])
        reg.gauge("ff_autoscale_scale_ins",
                  "autoscaler-initiated replica retirements"
                  ).set(st["scale_ins"])
        reg.gauge("ff_autoscale_breach_streak",
                  "consecutive policy windows with a capacity-SLO "
                  "breach").set(st["breach_streak"])
        reg.gauge("ff_autoscale_idle_streak",
                  "consecutive fully-idle policy windows"
                  ).set(st["idle_streak"])
        reg.gauge("ff_autoscale_cooldown_blocks",
                  "actions suppressed by the cooldown (hysteresis "
                  "working)").set(st["cooldown_blocks"])
        reg.gauge("ff_autoscale_bound_blocks",
                  "actions suppressed by the min/max replica bounds"
                  ).set(st["bound_blocks"])
        reg.gauge("ff_autoscale_cooldown_remaining_seconds",
                  "seconds until the next action is allowed"
                  ).set(st["cooldown_remaining_s"])
