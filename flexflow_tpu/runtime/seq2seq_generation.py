"""Encoder-decoder (seq2seq) generation with KV-cached decoding.

Net-new capability for the NMT/seq2seq family (SURVEY S1): the reference
trains its LSTM NMT and twin-stream Transformer but has NO decode story
at all — inference is the training graph run forward. Here the
encoder runs ONCE, cross-attention k/v are projected ONCE from the
encoder states (MultiHeadAttention.encode_kv), and the decoder runs the
same one-program prefill + `lax.scan` token loop as the decoder-only
path (runtime/generation.py), with a KV cache on decoder
SELF-attention and the static k/v on cross-attention — per-token cost
is O(tgt_prefix + src) attention reads, never a re-encode.

Scope (v1): greedy and temperature/top-k sampling with eos/pad
handling; uniform-length source batches (pad-free); no beam, no int8 —
the decoder-only Generator documents both patterns for a later lift.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu.ffconst import DataType, OperatorType
from flexflow_tpu.ops.attention import MultiHeadAttention
from flexflow_tpu.ops.base import InputOp
from flexflow_tpu.runtime.executor import resolve_tied_params
from flexflow_tpu.runtime.generation import _DECODE_SAFE, Generator


class Seq2SeqGenerator:
    """Compiles generate programs for an encoder-decoder graph.

    Graph contract: exactly two inputs — a source and an int32/int64
    TARGET token input; the target stream's self-attention must be
    causal; cross-attention ops take q from the decoder stream and
    k = v = an encoder-side tensor, non-causal and rope-free (the
    seq2seq_lm builder's layout). Encoder ops may be anything the
    forward path supports; decoder non-attention ops must be
    per-position (_DECODE_SAFE), same rule as decoder-only decode.
    """

    def __init__(self, model, temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None, pad_id: int = 0):
        self.model = model
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_id = eos_id
        self.pad_id = pad_id
        import collections

        self._jitted: Dict = collections.OrderedDict()

        if getattr(model.executor, "jits_per_group", False):
            raise NotImplementedError(
                "generate_seq2seq() is unsupported under an "
                "operator-placement strategy")
        inputs = [op for op in model.ops if isinstance(op, InputOp)]
        if len(inputs) != 2:
            raise ValueError(
                f"generate_seq2seq() needs exactly two graph inputs "
                f"(source, target tokens); this graph has {len(inputs)}")

        # the decoder stream is whatever transitively depends on the
        # target input; try each int input as the target and keep the
        # partition whose decoder self-attentions are all causal
        int_inputs = [op for op in inputs
                      if op.outputs[0].dtype in (DataType.DT_INT32,
                                                 DataType.DT_INT64)]
        if not int_inputs:
            raise ValueError(
                "generate_seq2seq() needs an integer target-token input")
        chosen = None
        for tgt in int_inputs:
            part = self._partition(model, tgt)
            if part is not None:
                chosen = (tgt, part)
                break
        if chosen is None:
            raise ValueError(
                "no input yields a decodable decoder stream (causal "
                "self-attention downstream of an int token input)")
        self.tgt_input, (self.enc_ops, self.dec_ops, self.self_ops,
                         self.cross_ops) = chosen
        self.src_input = next(op for op in inputs
                              if op is not self.tgt_input)
        # encoder tensors the decoder reads (cross k/v sources + any
        # other boundary values)
        dec_set = set(self.dec_ops)
        self.boundary = []
        for op in self.dec_ops:
            for t in op.inputs:
                if (t.owner_op is not None and t.owner_op not in dec_set
                        and not isinstance(t.owner_op, InputOp)
                        and t not in self.boundary):
                    self.boundary.append(t)

    @staticmethod
    def _partition(model, tgt_input):
        """Split ops into (encoder, decoder, self_attns, cross_attns)
        treating `tgt_input` as the decoder token stream; None when the
        split violates the decode contract (picks the wrong input)."""
        dec_tensors = {tgt_input.outputs[0]}
        enc_ops, dec_ops, self_ops, cross_ops = [], [], [], []
        for op in model.ops:
            if isinstance(op, InputOp):
                continue
            in_dec = any(t in dec_tensors for t in op.inputs)
            if not in_dec:
                enc_ops.append(op)
                continue
            dec_ops.append(op)
            dec_tensors.update(op.outputs)
            if isinstance(op, MultiHeadAttention):
                if op.inputs[0] is op.inputs[1] is op.inputs[2]:
                    if not op.causal:
                        return None  # bidirectional self-attn in decoder
                    self_ops.append(op)
                else:
                    # cross: q from decoder, k=v an encoder tensor
                    if op.inputs[1] is not op.inputs[2]:
                        return None
                    if op.inputs[1] in dec_tensors or op.causal or op.rope:
                        return None
                    cross_ops.append(op)
            elif op.op_type not in _DECODE_SAFE:
                return None
        if not self_ops:
            return None
        return enc_ops, dec_ops, self_ops, cross_ops

    # ---- walks --------------------------------------------------------------

    def _params_for(self, params, op):
        return self._cast_params(resolve_tied_params(
            self.model, params, op.name, params.get(op.name, {})))

    def _run_op(self, op, p, xs, state):
        with jax.named_scope(op.name):
            if op.stateful:
                outs, _ = op.forward_stateful(p, state.get(op.name, {}),
                                              xs, training=False, rng=None)
            else:
                kwargs = {}
                if getattr(op, "wants_shard_ctx", False):
                    kwargs["shard_ctx"] = None
                if op.op_type == OperatorType.OP_MOE:
                    kwargs["capacity"] = int(np.prod(xs[0].shape[:-1]))
                outs = op.forward(p, xs, training=False, rng=None, **kwargs)
        return outs

    def _encode(self, params, state, src):
        """One forward over the encoder ops; returns {tensor: value} for
        the decoder-consumed boundary tensors."""
        vals = {self.src_input.outputs[0]: src}
        for op in self.enc_ops:
            xs = [vals[t] for t in op.inputs]
            outs = self._run_op(op, self._params_for(params, op), xs, state)
            for i, t in enumerate(op.outputs):
                vals[t] = outs[i]
        return {t: vals[t] for t in self.boundary}

    def _dec_walk(self, params, state, toks, enc_vals, self_caches,
                  cross_kvs, pos):
        """Walk the decoder ops on a (B, C) token slab. pos=None →
        prefill (fills self-attn caches causally); else C == 1 and pos
        is the cache slot. Cross-attention always reads the static
        kv."""
        vals = dict(enc_vals)
        vals[self.tgt_input.outputs[0]] = toks
        new_caches = {}
        for op in self.dec_ops:
            p = self._params_for(params, op)
            xs = [vals[t] for t in op.inputs]
            if op in self.self_ops:
                cache = self_caches[op.name]
                if pos is None:
                    out, nc = op.prefill_forward(p, xs, cache)
                else:
                    out, nc = op.decode_forward(p, xs, cache, pos)
                new_caches[op.name] = nc
                outs = [out]
            elif op in self.cross_ops:
                outs = [op.cross_forward_cached(p, xs, cross_kvs[op.name])]
            else:
                outs = self._run_op(op, p, xs, state)
            for i, t in enumerate(op.outputs):
                vals[t] = outs[i]
        return vals[self.model._final_tensor], new_caches

    # ---- sampling + dtype + program LRU: REUSED from the decoder-only
    # Generator, so the two paths cannot drift (top-k tie handling,
    # top_k>=vocab no-op warning, bf16 compute selection, cache bounds)
    _sample = Generator._sample
    _compute_dtype = Generator._compute_dtype
    _cached_program = Generator._cached_program

    def _cast_params(self, p):
        if self._compute_dtype() != jnp.bfloat16:
            return p
        return {k: v.astype(jnp.bfloat16) if v.dtype == jnp.float32 else v
                for k, v in p.items()}

    # ---- the compiled program -----------------------------------------------

    def _build(self, max_new_tokens: int):
        cdtype = self._compute_dtype()

        def gen(params, state, src, tgt, key):
            b, t0 = tgt.shape
            max_len = t0 + max_new_tokens
            enc_vals = self._encode(params, state, src)
            cross_kvs = {op.name: op.encode_kv(
                self._params_for(params, op), enc_vals[op.inputs[1]])
                for op in self.cross_ops}
            self_caches = {op.name: op.init_cache(b, max_len, cdtype)
                           for op in self.self_ops}
            logits, self_caches = self._dec_walk(
                params, state, tgt, enc_vals, self_caches, cross_kvs, None)
            key, sub = jax.random.split(key)
            tok, _ = self._sample(logits[:, -1], sub)
            done = (tok == self.eos_id) if self.eos_id is not None \
                else jnp.zeros((b,), bool)

            def body(carry, i):
                self_caches, tok, done, key = carry
                logits, self_caches = self._dec_walk(
                    params, state, tok[:, None], enc_vals, self_caches,
                    cross_kvs, t0 + i)
                key, sub = jax.random.split(key)
                nxt, _ = self._sample(logits[:, 0], sub)
                if self.eos_id is not None:
                    nxt = jnp.where(done, self.pad_id, nxt)
                    done = done | (nxt == self.eos_id)
                return (self_caches, nxt, done, key), nxt

            if max_new_tokens > 1:
                _, rest = jax.lax.scan(
                    body, (self_caches, tok, done, key),
                    jnp.arange(max_new_tokens - 1, dtype=jnp.int32))
                new = jnp.concatenate([tok[:, None], rest.T], axis=1)
            else:
                new = tok[:, None]
            return jnp.concatenate([tgt, new], axis=1)

        return jax.jit(gen)

    def __call__(self, src_tokens, tgt_prompt, max_new_tokens: int,
                 seed: int = 0):
        src = jnp.asarray(src_tokens)
        tgt = jnp.asarray(tgt_prompt, jnp.int32)
        key = ("s2s", max_new_tokens, tuple(src.shape), tuple(tgt.shape))
        fn = self._cached_program(key, lambda: self._build(max_new_tokens))
        return np.asarray(fn(self.model.params, self.model.bn_state, src,
                             tgt, jax.random.PRNGKey(seed)))
