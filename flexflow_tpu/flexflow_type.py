"""Frontend interchange enums.

Reference: python/flexflow/core/flexflow_type.py:49-95 — the OpType vocabulary
of the .ff text IR emitted by the PyTorch-FX exporter and consumed by
PyTorchModel/ONNXModelKeras. Values kept identical so .ff files produced by
the reference exporter parse here and vice versa.
"""

from enum import Enum

from flexflow_tpu.ffconst import ActiMode, DataType, PoolType  # noqa: F401


class OpType(Enum):
    CONV2D = 2011
    EMBEDDING = 2012
    POOL2D = 2013
    LINEAR = 2014
    SOFTMAX = 2015
    CONCAT = 2016
    FLAT = 2017
    MSELOSS = 2020
    BATCH_NORM = 2021
    RELU = 2022
    SIGMOID = 2023
    TANH = 2024
    ELU = 2025
    DROPOUT = 2026
    BATCH_MATMUL = 2027
    SPLIT = 2028
    RESHAPE = 2029
    TRANSPOSE = 2030
    REVERSE = 2031
    EXP = 2040
    ADD = 2041
    SUBTRACT = 2042
    MULTIPLY = 2043
    DIVIDE = 2044
    INPUT = 2050
    OUTPUT = 2051
    MULTIHEAD_ATTENTION = 2060
    GETITEM = 2070
    GELU = 2080
    LAYER_NORM = 2081
    MEAN = 2082
    IDENTITY = 2083


def enum_to_int(enum_cls, item) -> int:
    return item.value


def int_to_enum(enum_cls, value: int):
    for item in enum_cls:
        if item.value == value:
            return item
    raise ValueError(f"unknown {enum_cls.__name__} value {value}")


def enum_to_str(enum_cls, item) -> str:
    return item.name


def str_to_enum(enum_cls, name: str):
    return enum_cls[name]
