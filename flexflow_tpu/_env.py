"""Virtual-device provisioning shared by every entry point.

This image preloads the TPU plugin at interpreter startup (sitecustomize),
so JAX_PLATFORMS/XLA_FLAGS in the launching shell can arrive too late; the
supported post-import path is jax.config. One implementation here serves the
package import hook (FLEXFLOW_FORCE_CPU_DEVICES), the driver entry
(__graft_entry__), and the C API (FFT_JAX_PLATFORMS/FFT_NUM_CPU_DEVICES).
"""

from __future__ import annotations


def _backend_initialized() -> bool:
    """Whether jax has already created a backend (after which platform /
    device-count config is a no-op). Best-effort across jax versions."""
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False


def force_cpu_devices(n: int) -> bool:
    """Point jax at an n-device virtual CPU platform. Must run before the
    first backend query (jax.devices() locks platform selection). Returns
    True if the config was applied, False if the backend was already
    initialized (in which case the caller should check device count)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        if n > 0:
            try:
                jax.config.update("jax_num_cpu_devices", int(n))
            except AttributeError:
                # older jax (e.g. 0.4.37) has no jax_num_cpu_devices; the
                # XLA flag is the pre-backend-init equivalent. XLA consumed
                # the flag at backend creation, so if a backend already
                # exists the count can no longer change — report False per
                # the docstring contract (caller checks device count)
                if _backend_initialized():
                    return False
                import os
                import re

                flags = os.environ.get("XLA_FLAGS", "")
                want = f"--xla_force_host_platform_device_count={int(n)}"
                flags = re.sub(
                    r"--xla_force_host_platform_device_count=\d+", "",
                    flags)
                # an existing count flag is REPLACED — keeping a stale
                # different value while returning True would lie
                os.environ["XLA_FLAGS"] = " ".join(
                    (flags + " " + want).split())
        return True
    except RuntimeError:
        return False


def enable_sharding_invariant_rng() -> None:
    """Force partitionable threefry, making every `jax.random` draw a pure
    function of (key, shape) independent of the out_sharding it is jitted
    under. On jax <= 0.4.x the default (False) generates DIFFERENT bits
    when GSPMD partitions dim 0 of the draw — so a CONTRACT/FSDP-sharded
    weight initialized via `jit(init, out_shardings=...)` silently started
    from different values than its replicated twin (the root cause of the
    long-standing test_contract_tp / test_fsdp "numerics drift": the drift
    was in the INIT, not the psum). Newer jax flipped the default to True;
    setting it is then a no-op. Tracing-time flag: safe after backend init."""
    import jax

    try:
        jax.config.update("jax_threefry_partitionable", True)
    except Exception:  # future jax: flag removed once True is the only impl
        pass


def enable_compilation_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at `cache_dir` (created if
    missing) so repeated runs skip recompiles; returns False (with the
    reason logged) when this jax build lacks the option. Must run before
    the first trace to cover it — FFModel.compile() and the launcher both
    call this from FFConfig.compilation_cache_dir."""
    import os

    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # low threshold: serving programs on CPU compile in 0.1-1 s and
        # they are exactly the recompiles the cache exists to absorb
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        try:
            # jax latches a cache-unused decision at the FIRST compile of
            # the process; any jit before this call (graph-build helpers,
            # warmup probes) would silently disable persistence for good.
            # reset_cache clears the latch so the next compile re-checks.
            from jax._src import compilation_cache

            compilation_cache.reset_cache()
        except Exception:
            pass
        return True
    except Exception as e:  # unsupported build or unwritable dir
        from flexflow_tpu.logger import fflogger

        fflogger.warning("compilation cache at %s unavailable: %s",
                         cache_dir, e)
        return False


def compilation_cache_entries(cache_dir: str) -> int:
    """Number of entries in the persistent compilation cache directory —
    sampled before/after a compile to log hit (count unchanged) vs miss
    (new entry written). Zero for a missing dir."""
    import os

    try:
        return sum(1 for n in os.listdir(cache_dir)
                   if not n.startswith("."))
    except OSError:
        return 0


def lax_axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` with a fallback for jax builds that predate
    it (e.g. 0.4.37): inside shard_map/pmap the static mapped-axis size is
    available from ``jax.core.axis_frame`` (which, depending on version,
    returns the size directly or a frame carrying ``.size``). Every
    shard_map kernel in the tree (ring attention, pipeline loops) resolves
    axis sizes through here."""
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    import jax.core as jax_core

    frame = jax_core.axis_frame(axis_name)
    return getattr(frame, "size", frame)


def force_cpu_devices_from_env(value: str) -> bool:
    """Env-var flavored wrapper: accepts '8', '1', or truthy junk ('true',
    'yes' -> platform forced, device count left at default)."""
    try:
        n = int(value)
    except ValueError:
        n = 0
    return force_cpu_devices(n)
