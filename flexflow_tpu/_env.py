"""Virtual-device provisioning shared by every entry point.

This image preloads the TPU plugin at interpreter startup (sitecustomize),
so JAX_PLATFORMS/XLA_FLAGS in the launching shell can arrive too late; the
supported post-import path is jax.config. One implementation here serves the
package import hook (FLEXFLOW_FORCE_CPU_DEVICES), the driver entry
(__graft_entry__), and the C API (FFT_JAX_PLATFORMS/FFT_NUM_CPU_DEVICES).
"""

from __future__ import annotations


def _backend_initialized() -> bool:
    """Whether jax has already created a backend (after which platform /
    device-count config is a no-op). Best-effort across jax versions."""
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False


def force_cpu_devices(n: int) -> bool:
    """Point jax at an n-device virtual CPU platform. Must run before the
    first backend query (jax.devices() locks platform selection). Returns
    True if the config was applied, False if the backend was already
    initialized (in which case the caller should check device count)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        if n > 0:
            try:
                jax.config.update("jax_num_cpu_devices", int(n))
            except AttributeError:
                # older jax (e.g. 0.4.37) has no jax_num_cpu_devices; the
                # XLA flag is the pre-backend-init equivalent. XLA consumed
                # the flag at backend creation, so if a backend already
                # exists the count can no longer change — report False per
                # the docstring contract (caller checks device count)
                if _backend_initialized():
                    return False
                import os
                import re

                flags = os.environ.get("XLA_FLAGS", "")
                want = f"--xla_force_host_platform_device_count={int(n)}"
                flags = re.sub(
                    r"--xla_force_host_platform_device_count=\d+", "",
                    flags)
                # an existing count flag is REPLACED — keeping a stale
                # different value while returning True would lie
                os.environ["XLA_FLAGS"] = " ".join(
                    (flags + " " + want).split())
        return True
    except RuntimeError:
        return False


def lax_axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` with a fallback for jax builds that predate
    it (e.g. 0.4.37): inside shard_map/pmap the static mapped-axis size is
    available from ``jax.core.axis_frame`` (which, depending on version,
    returns the size directly or a frame carrying ``.size``). Every
    shard_map kernel in the tree (ring attention, pipeline loops) resolves
    axis sizes through here."""
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    import jax.core as jax_core

    frame = jax_core.axis_frame(axis_name)
    return getattr(frame, "size", frame)


def force_cpu_devices_from_env(value: str) -> bool:
    """Env-var flavored wrapper: accepts '8', '1', or truthy junk ('true',
    'yes' -> platform forced, device count left at default)."""
    try:
        n = int(value)
    except ValueError:
        n = 0
    return force_cpu_devices(n)
