"""Runtime configuration.

The analog of the reference's FFConfig (reference: include/config.h:88-140,
defaults src/runtime/model.cc:1917-1968, parse_args model.cc:1970-2071).
Legion's `-ll:*` processor/memory knobs become mesh-shape knobs; the strategy
table is a map op-name -> ParallelConfig, persisted in the reference's text
schema (src/runtime/strategy.cc).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Dict, List, Optional, Tuple

MAX_NUM_WORKERS = 1024  # reference: include/config.h:30-42
MAX_TENSOR_DIM = 5
MAX_NUM_INPUTS = 8
MAX_NUM_WEIGHTS = 4
MAX_NUM_OUTPUTS = 8


@dataclasses.dataclass
class FFConfig:
    # training flags (reference defaults model.cc:1917-1938)
    batch_size: int = 64
    epochs: int = 1
    learning_rate: float = 0.01
    weight_decay: float = 1e-4
    iterations: int = 1  # per-epoch iteration override for synthetic runs

    # parallelism / machine shape (replaces -ll:gpu/-ll:cpu/numNodes)
    num_devices: Optional[int] = None  # default: all visible jax devices
    mesh_shape: Optional[Dict[str, int]] = None  # e.g. {"data": 8} or {"data": 4, "model": 2}
    ici_mesh_shape: Optional[Dict[str, int]] = None
    # axis -> number of hosts it spans; feeds the search's two-tier machine
    # model (collectives over these axes are priced at DCN bandwidth)
    dcn_mesh_shape: Optional[Dict[str, int]] = None

    # search flags (reference model.cc:1930-1932)
    search_budget: int = 0
    search_alpha: float = 0.05
    import_strategy_file: str = ""
    export_strategy_file: str = ""
    enable_parameter_parallel: bool = False
    enable_attribute_parallel: bool = False
    # search cost-table fidelity: False/"" = analytic roofline; "analyze" =
    # compile-only XLA cost_analysis (flops/bytes through the machine model);
    # True/"measure" = real on-device fwd+bwd timing (reference:
    # measure_operator_cost, simulator.cc:296-316)
    measure_search_costs: object = False
    # persistent op-cost DB (search/cost_db.py): measured/analyzed entries
    # keyed by op signature + environment survive the process, so a
    # warm-started search re-measures zero already-keyed ops. "" = off
    # (hermetic in-process caches only); the FF_COST_DB env var also
    # activates it when this field is unset
    cost_db_path: str = ""

    # dataloader (native threaded gather/prefetch; reference's dataloader is
    # native too — flexflow_dataloader.cc)
    native_dataloader: bool = True   # fall back to Python slicing if no g++
    dataloader_shuffle: bool = False  # reference slices sequentially
    dataloader_threads: int = 2
    dataloader_prefetch_slots: int = 3

    # execution flags
    sp_mode: str = "ring"  # sequence-parallel lowering: "ring" | "ulysses"
    profiling: bool = False
    # write the simulated schedule as DOT after compile (reference
    # --taskgraph, model.cc:2066-2069)
    taskgraph_file: str = ""
    # graph-level FusedOp pass (ops/fused.py); XLA fuses kernels regardless
    perform_fusion: bool = False
    simulator_workspace_size: int = 2 * 1024 * 1024 * 1024
    compute_dtype: str = "float32"  # "bfloat16" for MXU-native training
    # storage dtype of master weights/optimizer state. "bfloat16" halves the
    # optimizer's HBM traffic and removes the per-step f32->bf16 cast pass
    # (the measured ~7ms/step non-layer overhead, round-2 notes); update
    # MATH stays f32 inside the optimizer regardless
    master_dtype: str = "float32"
    # fuse residual-add + layernorm into one Pallas kernel in models that
    # opt in (models/transformer.py encoder blocks)
    use_fused_ln: bool = False
    # single-fusion optimizer update over flattened param buckets
    # (runtime/optimizer.py FusedUpdate): one elementwise kernel per dtype
    # instead of one per weight. Applies only when every param is
    # replicated (single chip / pure DP); sharded strategies fall back
    fused_optimizer: bool = False
    use_flash_attention: bool = True  # Pallas flash kernel on the dense path
    # multi-step scanned training (executor.make_train_scan): fit() runs up
    # to this many steps per device dispatch via lax.scan — the TPU-native
    # analog of the reference's Legion tracing replay around each iteration
    # (base_model.py:408-418). 0 = one dispatch per step (per-step verbs
    # keep working either way). Requires device-resident data.
    scan_steps: int = 0
    # gradient accumulation: split each global batch into this many equal
    # microbatches scanned through fwd+bwd with ONE optimizer update —
    # numerically the full-batch step (losses are batch means), at a
    # microbatch's activation memory. 1 = off.
    grad_accum_steps: int = 1
    # FSDP / ZeRO-3 analog: shard every weight (and with it the optimizer
    # state) over this mesh axis in addition to any strategy sharding —
    # each weight's largest divisible un-sharded dim is split, GSPMD
    # all-gathers at use and reduce-scatters the gradient. Param + opt
    # HBM divides by the axis size. "" = off.
    fsdp_axis: str = ""
    # in-graph compute/communication overlap (runtime/executor.py +
    # runtime/optimizer.py Zero1Update): reduce each microbatch's
    # gradients into data-axis-scattered per-op buckets INSIDE the
    # accumulation scan (the collective for microbatch k overlaps the
    # backward of microbatch k+1) and run the optimizer update sharded
    # ZeRO-1 style — each data shard updates its slice of params and
    # optimizer state from the already-scattered grads, then params
    # all-gather ONCE. Optimizer-state HBM divides by the data degree;
    # composes with fsdp_axis (a weight the FSDP axis already shards
    # keeps its ZeRO-3 layout). No-op on meshes without a data axis > 1.
    overlap_grad_sync: bool = False
    # async checkpointing (runtime/checkpoint.py): save_checkpoint
    # snapshots params to host in-step and runs the atomic tmp-dir +
    # manifest + publish-rename path on ONE background publisher thread,
    # so checkpoint_every stops costing step time. The TrainSupervisor
    # quiesces pending saves at SIGTERM/rewind/final; single-controller
    # only (multihost saves are collective and stay synchronous).
    async_checkpointing: bool = False
    # fflint (flexflow_tpu/analysis): static strategy validation inside
    # compile(), after the table is final but before params/programs are
    # built. "warn" logs violations through fflogger; "strict" raises
    # StrategyLintError on any error-severity finding (a bad strategy file
    # is then rejected in milliseconds with the op + rule named, instead
    # of failing deep inside mesh construction or XLA compile); "off"
    # skips the analyzer entirely.
    strategy_lint: str = "warn"
    # label value excluded from token-level accuracy (count AND
    # denominator) — set to the pad id for causal-LM training so padded
    # positions don't dilute the metric; None counts every position
    metrics_ignore_index: int = None
    # keep datasets device-resident (next_batch = on-device slice, the
    # reference's ZC-resident design) when they fit the budget
    device_resident_data: bool = True
    device_data_budget_bytes: int = 2 << 30
    seed: int = 0

    # ---- host-overlap step engine (runtime/pipeline_loader.py) ----
    # bounded background prefetch for host-resident data in fit(): a
    # worker thread pulls batches and device_puts them (committed) up to
    # this many ahead, so the hot loop's batch is already on device.
    # 0 = synchronous staging (the old loop). Device-resident datasets
    # bypass this (their next_batch is already an on-device slice).
    prefetch_depth: int = 2
    # max training steps in flight before fit() blocks on the OLDEST
    # step's loss scalar (a device-progress wait, not a host sync on the
    # current step). Bounds queued work + host memory; losses/metrics
    # still drain asynchronously at epoch boundaries. 0 = wait for each
    # step's own loss (fully synchronous device progress, for debugging).
    dispatch_ahead: int = 2

    # ---- fault tolerance (runtime/resilience.py) ----
    # checkpoint directory for the TrainSupervisor / fit() auto-resume.
    # "" = no supervision (fit behaves exactly as before)
    checkpoint_dir: str = ""
    # periodic checkpoint cadence in steps (0 = only preemption/final
    # saves); atomic tmp-dir + rename writes, see runtime/checkpoint.py
    checkpoint_every: int = 0
    keep_checkpoints: int = 3  # retention: newest K step dirs survive
    # divergence guard compiled INTO the train step (one jnp.isfinite
    # reduction over loss + global grad-norm; skip/keep selected in-graph):
    #   "none"    — guard off, the step program is byte-identical to before
    #   "skip"    — non-finite steps leave params/opt state untouched
    #   "backoff" — skip + halve the loss scale on non-finite, regrow
    #               after loss_scale_growth_interval clean steps
    on_nonfinite: str = "none"
    # rewind-to-last-checkpoint after this many CONSECUTIVE non-finite
    # steps (0 = never rewind; requires a checkpoint_dir supervisor)
    nonfinite_rewind_after: int = 0
    # wall-clock watchdog per train step: dump all thread stacks and abort
    # when a step's host fetch blocks longer than this (0 = off). Hung
    # cross-host collectives otherwise block forever with no diagnostics.
    step_timeout_s: float = 0.0
    loss_scale: float = 1.0  # initial loss scale ("backoff" mode)
    loss_scale_growth_interval: int = 200

    # ---- elastic recovery (runtime/elastic.py) ----
    # what a resuming process does when its actual topology (visible
    # devices / mesh) differs from the checkpoint's:
    #   "resume_resharded" — refit the mesh to the surviving devices
    #       (csim-ranked candidates over the saved axes), re-shard the
    #       saved params/opt-state onto it, and preserve the GLOBAL batch
    #       by scaling grad_accum_steps with the data-degree change
    #   "research"        — same mesh refit, then re-run the MCMC strategy
    #       search at the new device count (budget: search_budget, else a
    #       small default) instead of re-deriving the saved strategy
    #   "abort"           — raise TopologyChangedError (the pre-elastic
    #       behavior, for jobs whose semantics pin the topology)
    on_topology_change: str = "resume_resharded"
    # verify the content-hash manifest (ff_manifest.json) of a checkpoint
    # before restoring, and fall back to the newest INTACT step when the
    # latest fails (torn write, bitrot, injected corruption)
    verify_checkpoints: bool = True
    # refuse to resume-reshard below this many devices (a 256-chip job
    # "recovering" onto 2 chips is an outage, not elasticity)
    elastic_min_devices: int = 1

    # ---- serving (runtime/serving.py: continuous batching) ----
    # decode slots in the ONE compiled slot-decode program; the host
    # scheduler admits/retires requests per slot
    serve_slots: int = 4
    # paged KV cache: pool of (kv_pages, kv_page_size, KVH, Dh) blocks
    # shared by all slots through per-slot page tables. kv_pages = 0
    # derives 1 (scratch) + serve_slots * ceil(max_seq_len /
    # kv_page_size) + prefix-cache slack (half the slot pages, at least
    # one slot's worth) when serve_prefix_cache is on — without the
    # slack the derived pool has zero free pages for refcount-0 cached
    # prefixes and the radix cache silently goes cold (ISSUE 18). The
    # engine logs the derived split at init.
    kv_page_size: int = 128
    kv_pages: int = 0
    # prompt-length admission buckets (ascending ints); None = powers of
    # two from 8 — warm prefill programs are reused within a bucket, and
    # ServingEngine.recompile_count proves it
    decode_buckets: Optional[List[int]] = None
    # radix prefix cache (runtime/serving.py RadixPrefixCache): share KV
    # pages across requests whose prompts start with the same page-aligned
    # token prefix — admission mounts the cached pages read-only and
    # prefills only the tail (copy-on-write: shared pages are never
    # written). False = the PR-3 allocate-everything path.
    serve_prefix_cache: bool = True
    # speculative decoding: the draft model proposes this many greedy
    # tokens per slot per iteration; one fixed-shape verify program
    # scores all K+1 positions in a single dispatch. 0 = off. Greedy
    # streams stay token-identical to non-speculative decode.
    serve_speculate_k: int = 0
    # the compiled draft FFModel (same vocab as the target — validated at
    # engine construction). A runtime object, not a flag: pass it
    # programmatically or via make_serving_engine(draft_model=...)
    draft_model: Optional[object] = None
    # fleet router (runtime/router.py ServingRouter): bound on the router
    # queue — submissions past it are REJECTED immediately (state
    # "rejected") instead of queueing, so accepted-request p99 TTFT stays
    # bounded under overload while excess load fails fast at the front
    # door. 0 = unbounded (the pre-router behavior: the queue grows with
    # the backlog and every request's tail latency grows with it).
    serve_max_queue: int = 0
    # ---- quantized serving tier (ISSUE 11) ----
    # storage dtype of the paged KV pool (runtime/serving.py):
    #   "native" — the compute dtype (float32/bfloat16), the pre-quant
    #              behavior
    #   "bf16"   — store pages in bfloat16 regardless of compute dtype
    #              (plain cast, no scales): halves an f32 pool
    #   "int8"   — symmetric int8 pages with per-page-per-kv-head f32
    #              scales stored alongside the pool; ~2x the tokens per
    #              pool byte vs bf16. Dequantization happens in VMEM —
    #              inside the Pallas paged-attention kernel, or fused
    #              into the einsum gather — so wide KV is never
    #              materialized in HBM.
    #   "fp8"    — float8_e4m3fn pages, same scale layout (needs a jax
    #              build with jnp.float8_e4m3fn; validated at engine
    #              construction, not here, so config objects stay
    #              backend-free)
    # The page allocator, COW rule, radix trie, router affinity and
    # speculation are page-granular and unchanged — a page simply holds
    # more tokens per byte, multiplying prefix-cache capacity and
    # slots-per-chip at fixed HBM. Quantized KV is lossy: greedy streams
    # carry a per-dtype divergence budget vs the full-width path
    # (docs/serving.md "Quantized tier").
    kv_cache_dtype: str = "native"
    # serving-weight storage for the fixed-shape decode/prefill programs
    # (runtime/generation.py weight-only quantization, promoted to a
    # first-class serving mode): "native" | "int8" | "fp8". Quantization
    # happens ONCE at engine init (per-output-channel scales); dequant
    # fuses into each consuming matmul, so the HBM weight read per decode
    # step — the decode bottleneck — is the quantized bytes.
    serve_weight_dtype: str = "native"
    # decode/verify attention over the paged KV pool:
    #   "auto"   — Pallas paged-attention kernel on a TPU backend (page-
    #              table lookup inside the kernel, only a slot's live
    #              pages stream through VMEM), einsum page-gather
    #              elsewhere; a measured winner persisted by
    #              search/kernel_tune.py tune_paged_attention for this
    #              engine's exact shape+dtype overrides the backend
    #              default (measured costs beat heuristics)
    #   "pallas" — force the kernel everywhere (interpret mode off-TPU,
    #              so CPU CI executes the real kernel code path)
    #   "einsum" — force the page-gather oracle (bitwise the dense-cache
    #              attention) — the parity baseline
    # Greedy serving streams are token-identical under either impl
    # (tests/test_pallas_paged.py pins it).
    paged_attention_impl: str = "auto"
    # ---- disaggregated fleet + tiered prefix cache (ISSUE 12) ----
    # pinned host-memory second tier under the radix prefix cache
    # (runtime/serving.py): refcount-0 KV pages evicted under pool
    # pressure DEMOTE to host RAM (async ordered D2H) instead of dying,
    # and a trie match against a host-resident edge PROMOTES the page
    # back (H2D, bitwise). Sized in pages of kv_page_size positions —
    # the effective shared-prefix corpus becomes host-RAM-sized instead
    # of HBM-sized. 0 = off (the PR-6 evict-means-die behavior).
    host_kv_pages: int = 0
    # fleet replica roles (runtime/router.py ServingRouter): ""
    # (default) = every replica "mixed", bit-identical to the pre-role
    # fleet. A comma-separated list, one per replica (e.g.
    # "prefill,decode,decode"), turns on the disaggregated role split:
    # prefill replicas absorb long-prompt admission and hand the
    # finished KV pages off to decode replicas as a serialized page
    # slab, keeping decode slot occupancy high under bursty long-prompt
    # traffic. Roles are placement preferences, never constraints — a
    # dead tier degrades to the mixed-fleet path.
    serve_replica_roles: str = ""
    # ---- long-context serving (ISSUE 18) ----
    # chunk-interleaved admission (runtime/serving.py): > 0 turns an
    # admitted cold prompt's prefill chunks into schedulable quanta —
    # the scheduler runs at most this many prefill chunks per step()
    # between decode ticks, so a 100k-token prompt admits without
    # head-of-line-blocking the replica's decode streams. Partial
    # prefill state is slot-resident (the slot is held, inactive, until
    # the last chunk lands); greedy/sampled streams are token-identical
    # to run-to-completion admission. 0 = off (prefill completes at
    # admission, the pre-18 behavior).
    prefill_interleave_chunks: int = 0
    # sequence-parallel prefill (runtime/router.py): >= 2 splits a
    # long prompt's page-aligned prefix into that many contiguous
    # sequence shards fanned out across the prefill tier; each shard
    # exports its KV pages as a partial-prefix slab
    # (export_prefix_slab(start_page=...)) and the decode replica
    # merges them in order through import_prefix_slab. Bitwise the
    # single-replica prefill (tests/test_seq_parallel.py pins page and
    # pool equality). Requires a handoff-capable fleet
    # (serve_replica_roles); 0/1 = off.
    seq_parallel_shards: int = 0
    # ---- multi-tenant serving (ISSUE 14) ----
    # per-request sampling DEFAULTS (submit() overrides per request;
    # the values ride the one fixed-shape slot program as per-slot
    # scalars — ops/sampling.py): temperature 0 = greedy argmax
    # (bitwise the pre-sampling path), top_p in (0, 1] (1 = off),
    # top_k >= 0 (0 = off). Sample streams are counter-based on the
    # request seed, so they reproduce across slot reassignment and
    # failover resubmission.
    serve_temperature: float = 0.0
    serve_top_p: float = 1.0
    serve_top_k: int = 0
    # paged LoRA adapter pool (runtime/lora.py + ops/lora.py): device
    # pages for concurrently-resident adapters (0 = no pool). Each page
    # holds one adapter's (a, b) weights for every LoRA-targeted Linear
    # op at rank serve_lora_rank; a host allocator/LRU faults
    # registered adapters in through ONE fixed-shape writer, so N
    # tenants share a replica with zero recompiles.
    serve_adapter_pool_pages: int = 0
    serve_lora_rank: int = 8
    # jax persistent compilation cache directory ("" = off): set before
    # the first trace (FFModel.compile / launcher) so repeated runs skip
    # recompiles; serving logs hit/miss per program build
    compilation_cache_dir: str = ""
    # ---- unified telemetry plane (runtime/telemetry.py, ISSUE 13) ----
    # "on" (default): the metrics registry records counters/histograms
    # and the trace ring records per-request / per-step spans — the
    # substrate stats()/health() export through. "off": span creation
    # returns a shared no-op and every observe/inc short-circuits at one
    # predicate (the bench's telemetry_overhead_pct control arm).
    telemetry: str = "on"
    # serve a Prometheus text endpoint (/metrics), a JSON snapshot
    # (/metrics.json) and the Chrome trace ring (/trace.json) on
    # 127.0.0.1:<port> from a stdlib http.server daemon thread. 0 = no
    # server (the default; the registry still records — export is pull).
    # Engines/routers/fit start it lazily on first use; one per process.
    metrics_port: int = 0
    # ---- flight recorder + SLO health plane (runtime/flightrec.py,
    # ISSUE 15) ----
    # post-mortem bundle directory: every trigger (watchdog fire,
    # replica fence, nonfinite rewind, uncaught engine/driver
    # exception, SIGTERM preempt, any fired FF_FAULT, an SLO breach
    # with slo_trip_recorder, or a manual dump_flight_record()) writes
    # an atomic manifest-hashed bundle here (trace window + metrics
    # snapshot + recent logs + trigger cause/stack + config/env
    # fingerprint + per-engine stats + the HBM ledger). "" = auto
    # triggers disabled (the in-memory window still records;
    # FF_FLIGHT_DIR is the env fallback). telemetry="off" disables the
    # recorder at the same single predicate as every other emit.
    flight_recorder_dir: str = ""
    flight_keep: int = 4          # retention: newest K bundles survive
    # one bundle per cooldown window — a crash storm writes one bundle,
    # the rest count as suppressed in the next bundle's trigger.json
    flight_cooldown_s: float = 30.0
    # triggers arriving within this of the first merge into ONE pending
    # bundle (the storm's causes are all listed); flush() forces the
    # pending write immediately
    flight_debounce_s: float = 1.0
    flight_window_s: float = 120.0  # trace-ring window a bundle captures
    # declarative SLOs, evaluated over sliding windows of the telemetry
    # histograms / engine counters (runtime/flightrec.py SLOMonitor).
    # 0 = that SLO is off. A breach fires only after a full window,
    # emits ff_slo_breach_total{slo,replica} + a margin gauge + an
    # alert log + a trace annotation, flips /healthz to "breach", and
    # clears after slo_clear_windows consecutive healthy windows.
    slo_ttft_p99_s: float = 0.0          # ceiling: p99 TTFT per replica
    slo_queue_wait_p99_s: float = 0.0    # ceiling: engine queue wait p99
    slo_prefix_hit_rate_min: float = 0.0  # floor: prefix-cache hit rate
    slo_spec_accept_min: float = 0.0     # floor: speculative accept rate
    slo_step_time_p99_s: float = 0.0     # ceiling: train step p99
    slo_checkpoint_stall_s: float = 0.0  # ceiling: checkpoint stall p99
    slo_window_s: float = 10.0           # sliding evaluation window
    slo_clear_windows: int = 2           # hysteresis: healthy windows
    #                                      required to clear a breach
    # ---- ffsan runtime sanitizer (runtime/locks.py, ISSUE 16) ----
    # "" (default) leaves the env-derived FF_SANITIZE mode alone
    # (off unless the env sets it). "on": runtime locks created
    # from here on become order-asserting proxies checking every
    # acquisition against the declared hierarchy, and the engines'
    # retrace sentinel reports any post-warmup jit cache miss —
    # both routed to the flight recorder as incidents. "strict":
    # same checks, but violations raise. "off": force-disable.
    # Module-level locks (telemetry, native loader) are created at
    # import, before any FFConfig exists — set FF_SANITIZE for
    # process-wide coverage (what the CI sanitize tier does).
    sanitize: str = ""
    slo_trip_recorder: bool = False      # breach also trips the recorder
    # ---- rolling deployment (runtime/deploy.py, ISSUE 17) ----
    # watch path the weight-version registry scans: async checkpointing
    # publishes manifest-verified artifacts here (save_checkpoint
    # step_<N> layout; version "v<N>"), and RollingDeployer.deploy()
    # rolls the fleet onto the newest intact one. "" = no watch path
    # (pass one to WeightArtifactRegistry directly).
    deploy_watch_dir: str = ""
    # canary soak: the first swapped replica serves under its own
    # rebaselined SLO windows for this many full slo_window_s windows;
    # any breach attributed to it inside the soak rolls the whole
    # deploy back. 0 = no soak (swap and move on — the drill-less path).
    deploy_canary_windows: int = 2
    # hard ceiling on one replica's drain-quiesce wait during a roll
    # (seconds): a replica that cannot quiesce aborts the deploy
    # (state "failed") instead of wedging the roll forever
    deploy_drain_timeout_s: float = 120.0
    # ---- elastic fleet (runtime/autoscale.py, ISSUE 20) ----
    # AutoscalePolicy bounds + hysteresis: scale OUT only after
    # slo_queue_wait/slo_ttft breaches persist across this many
    # consecutive policy windows, scale IN only after this many idle
    # windows, and never act twice within the cooldown — a breach
    # storm cannot thrash the fleet. One policy window = one
    # slo_window_s evaluation.
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 8
    autoscale_breach_windows: int = 2    # breach windows before scale-out
    autoscale_idle_windows: int = 6      # idle windows before scale-in
    autoscale_cooldown_s: float = 30.0   # min seconds between actions
    # preemption evacuation: a SIGTERM'd (or FF_FAULT `preempt`) replica
    # races this deadline to hand queued/in-flight requests and hot
    # prefix slabs to survivors; on expiry it degrades to a plain fence
    # (remaining work resubmits cold, exactly-once either way)
    preempt_deadline_s: float = 5.0

    # populated at FFModel construction
    strategies: Dict[str, "ParallelConfig"] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.grad_accum_steps < 1:
            raise ValueError(
                f"grad_accum_steps={self.grad_accum_steps}: must be >= 1")
        if self.batch_size % max(1, self.grad_accum_steps):
            raise ValueError(
                f"batch_size {self.batch_size} not divisible by "
                f"grad_accum_steps {self.grad_accum_steps}")
        if self.strategy_lint not in ("off", "warn", "strict"):
            raise ValueError(
                f"strategy_lint={self.strategy_lint!r}: must be 'off', "
                f"'warn' or 'strict'")
        if self.on_nonfinite not in ("none", "skip", "backoff"):
            raise ValueError(
                f"on_nonfinite={self.on_nonfinite!r}: must be 'none', "
                f"'skip' or 'backoff'")
        if self.nonfinite_rewind_after < 0 or self.checkpoint_every < 0:
            raise ValueError(
                "nonfinite_rewind_after and checkpoint_every must be >= 0")
        if self.prefetch_depth < 0 or self.dispatch_ahead < 0:
            raise ValueError(
                f"prefetch_depth={self.prefetch_depth} and dispatch_ahead="
                f"{self.dispatch_ahead} must be >= 0")
        if self.loss_scale <= 0:
            # 0 would make the guard divide by zero and classify EVERY
            # step non-finite — the run would "complete" training nothing
            raise ValueError(
                f"loss_scale={self.loss_scale}: must be > 0")
        if self.loss_scale_growth_interval < 1:
            raise ValueError(
                f"loss_scale_growth_interval="
                f"{self.loss_scale_growth_interval}: must be >= 1")
        if self.on_topology_change not in ("resume_resharded", "research",
                                           "abort"):
            raise ValueError(
                f"on_topology_change={self.on_topology_change!r}: must be "
                f"'resume_resharded', 'research' or 'abort'")
        if self.elastic_min_devices < 1:
            raise ValueError(
                f"elastic_min_devices={self.elastic_min_devices}: "
                f"must be >= 1")
        if self.serve_slots < 1 or self.kv_page_size < 1 \
                or self.kv_pages < 0:
            raise ValueError(
                f"serve_slots={self.serve_slots} (>= 1), "
                f"kv_page_size={self.kv_page_size} (>= 1), "
                f"kv_pages={self.kv_pages} (>= 0, 0 = derive)")
        if self.kv_page_size & (self.kv_page_size - 1):
            # pow2 keeps position->page arithmetic exact under the pow2
            # prompt buckets AND keeps the radix chunk boundary aligned
            # with every bucket boundary (a non-pow2 page would let a
            # bucket end mid-page, splitting prefix chunks across
            # programs)
            raise ValueError(
                f"kv_page_size={self.kv_page_size}: must be a power of "
                f"two")
        if self.serve_speculate_k < 0:
            raise ValueError(
                f"serve_speculate_k={self.serve_speculate_k}: must be "
                f">= 0 (0 = speculative decoding off)")
        if self.prefill_interleave_chunks < 0:
            raise ValueError(
                f"prefill_interleave_chunks="
                f"{self.prefill_interleave_chunks}: must be >= 0 "
                f"(0 = run-to-completion prefill at admission)")
        if self.seq_parallel_shards < 0 or self.seq_parallel_shards == 1:
            raise ValueError(
                f"seq_parallel_shards={self.seq_parallel_shards}: must "
                f"be 0 (off) or >= 2 (shard count)")
        if self.serve_max_queue < 0:
            raise ValueError(
                f"serve_max_queue={self.serve_max_queue}: must be >= 0 "
                f"(0 = unbounded router queue)")
        if self.host_kv_pages < 0:
            raise ValueError(
                f"host_kv_pages={self.host_kv_pages}: must be >= 0 "
                f"(0 = no host tier)")
        if self.serve_replica_roles:
            roles = [t.strip()
                     for t in self.serve_replica_roles.split(",")]
            bad = [t for t in roles
                   if t not in ("prefill", "decode", "mixed")]
            if bad or not all(roles):
                raise ValueError(
                    f"serve_replica_roles={self.serve_replica_roles!r}: "
                    f"comma-separated 'prefill'|'decode'|'mixed', one "
                    f"per replica (bad: {bad or 'empty entry'})")
        # ONE validation rule for sampling params, shared with
        # engine/router submit paths (ops/sampling.py) — config-time and
        # submit-time acceptance can never diverge
        from flexflow_tpu.ops.sampling import validate_sampling

        validate_sampling(
            self.serve_temperature, self.serve_top_p, self.serve_top_k,
            "FFConfig (serve_temperature/serve_top_p/serve_top_k)")
        if self.serve_adapter_pool_pages < 0:
            raise ValueError(
                f"serve_adapter_pool_pages={self.serve_adapter_pool_pages}"
                f": must be >= 0 (0 = no adapter pool)")
        if self.serve_lora_rank < 1:
            raise ValueError(
                f"serve_lora_rank={self.serve_lora_rank}: must be >= 1")
        if self.sanitize not in ("", "off", "on", "strict"):
            raise ValueError(
                f"sanitize={self.sanitize!r}: must be '', 'off', "
                f"'on' or 'strict'")
        if self.telemetry not in ("on", "off"):
            raise ValueError(
                f"telemetry={self.telemetry!r}: must be 'on' or 'off'")
        if self.metrics_port < 0 or self.metrics_port > 65535:
            raise ValueError(
                f"metrics_port={self.metrics_port}: must be 0 (no "
                f"server) or a valid TCP port")
        if self.flight_keep < 1:
            raise ValueError(
                f"flight_keep={self.flight_keep}: must be >= 1 (the "
                f"bundle that just fired must survive its own retention)")
        if self.flight_cooldown_s < 0 or self.flight_debounce_s < 0:
            raise ValueError(
                f"flight_cooldown_s={self.flight_cooldown_s} and "
                f"flight_debounce_s={self.flight_debounce_s} must be "
                f">= 0")
        if self.flight_window_s <= 0:
            raise ValueError(
                f"flight_window_s={self.flight_window_s}: must be > 0")
        for knob in ("slo_ttft_p99_s", "slo_queue_wait_p99_s",
                     "slo_step_time_p99_s", "slo_checkpoint_stall_s"):
            if getattr(self, knob) < 0:
                raise ValueError(
                    f"{knob}={getattr(self, knob)}: must be >= 0 "
                    f"(0 = SLO off)")
        for knob in ("slo_prefix_hit_rate_min", "slo_spec_accept_min"):
            v = getattr(self, knob)
            if v < 0 or v > 1:
                raise ValueError(
                    f"{knob}={v}: must be in [0, 1] (0 = SLO off; it "
                    f"is a rate floor)")
        if self.slo_window_s <= 0:
            raise ValueError(
                f"slo_window_s={self.slo_window_s}: must be > 0")
        if self.slo_clear_windows < 1:
            raise ValueError(
                f"slo_clear_windows={self.slo_clear_windows}: must be "
                f">= 1 (a breach must be clearable)")
        if self.deploy_canary_windows < 0:
            raise ValueError(
                f"deploy_canary_windows={self.deploy_canary_windows}: "
                f"must be >= 0 (0 = no canary soak)")
        if self.deploy_drain_timeout_s <= 0:
            raise ValueError(
                f"deploy_drain_timeout_s={self.deploy_drain_timeout_s}: "
                f"must be > 0")
        if self.autoscale_min_replicas < 1:
            raise ValueError(
                f"autoscale_min_replicas={self.autoscale_min_replicas}: "
                f"must be >= 1 (the fleet must keep a survivor)")
        if self.autoscale_max_replicas < self.autoscale_min_replicas:
            raise ValueError(
                f"autoscale_max_replicas={self.autoscale_max_replicas}: "
                f"must be >= autoscale_min_replicas "
                f"({self.autoscale_min_replicas})")
        if self.autoscale_breach_windows < 1:
            raise ValueError(
                f"autoscale_breach_windows={self.autoscale_breach_windows}"
                f": must be >= 1")
        if self.autoscale_idle_windows < 1:
            raise ValueError(
                f"autoscale_idle_windows={self.autoscale_idle_windows}: "
                f"must be >= 1")
        if self.autoscale_cooldown_s < 0:
            raise ValueError(
                f"autoscale_cooldown_s={self.autoscale_cooldown_s}: "
                f"must be >= 0")
        if self.preempt_deadline_s <= 0:
            raise ValueError(
                f"preempt_deadline_s={self.preempt_deadline_s}: must be "
                f"> 0 (the evacuation race needs a budget)")
        if self.paged_attention_impl not in ("auto", "pallas", "einsum"):
            raise ValueError(
                f"paged_attention_impl={self.paged_attention_impl!r}: "
                f"must be 'auto', 'pallas' or 'einsum'")
        if self.kv_cache_dtype not in ("native", "bf16", "int8", "fp8"):
            raise ValueError(
                f"kv_cache_dtype={self.kv_cache_dtype!r}: must be "
                f"'native', 'bf16', 'int8' or 'fp8' (exact spelling — a "
                f"typo here would silently serve the wrong KV precision)")
        if self.serve_weight_dtype not in ("native", "int8", "fp8"):
            raise ValueError(
                f"serve_weight_dtype={self.serve_weight_dtype!r}: must "
                f"be 'native', 'int8' or 'fp8'")
        if self.decode_buckets is not None:
            bs = list(self.decode_buckets)
            if not bs or any(int(b) < 1 for b in bs) \
                    or sorted(set(int(b) for b in bs)) != [int(b) for b in bs]:
                raise ValueError(
                    f"decode_buckets={self.decode_buckets!r}: must be a "
                    f"strictly ascending list of positive ints")
        for field in ("compute_dtype", "master_dtype"):
            v = getattr(self, field)
            if v not in ("float32", "bfloat16"):
                raise ValueError(
                    f"{field}={v!r}: must be 'float32' or 'bfloat16' "
                    f"(exact spelling — a typo here would silently run the "
                    f"wrong precision)")
        if self.num_devices is None:
            if self.mesh_shape is not None:
                # derive from the mesh without touching the backend (keeps
                # graph-build/search-only flows from initializing devices)
                n = 1
                for s in self.mesh_shape.values():
                    n *= s
                self.num_devices = n
            else:
                import jax

                self.num_devices = len(jax.devices())
        if self.mesh_shape is None:
            self.mesh_shape = {"data": self.num_devices}

    @property
    def workers_per_node(self) -> int:
        return self.num_devices

    @property
    def num_nodes(self) -> int:
        return 1

    @staticmethod
    def parse_args(argv: Optional[List[str]] = None) -> "FFConfig":
        """CLI parity with reference flags (model.cc:1970-2071)."""
        p = argparse.ArgumentParser(add_help=False)
        p.add_argument("-e", "--epochs", type=int, default=1)
        p.add_argument("-b", "--batch-size", type=int, default=64)
        p.add_argument("--lr", "--learning-rate", dest="lr", type=float, default=0.01)
        p.add_argument("--wd", "--weight-decay", dest="wd", type=float, default=1e-4)
        p.add_argument("--budget", "--search-budget", dest="budget", type=int, default=0)
        p.add_argument("--alpha", "--search-alpha", dest="alpha", type=float, default=0.05)
        p.add_argument("--import", dest="import_file", type=str, default="")
        p.add_argument("--export", dest="export_file", type=str, default="")
        p.add_argument("--enable-parameter-parallel", action="store_true")
        p.add_argument("--enable-attribute-parallel", action="store_true")
        p.add_argument("--measure-costs", action="store_true")
        p.add_argument("--analyze-costs", action="store_true")
        p.add_argument("--cost-db", dest="cost_db", type=str, default="",
                       help="path to the persistent op-cost database "
                            "(JSON); measured/analyzed search costs are "
                            "read and written there so later searches "
                            "warm-start (also: FF_COST_DB env var)")
        p.add_argument("--taskgraph", dest="taskgraph", type=str, default="")
        p.add_argument("--profiling", action="store_true")
        p.add_argument("--fusion", action="store_true")
        p.add_argument("--num-devices", type=int, default=None)
        p.add_argument("--overlap-grad-sync", action="store_true",
                       help="bucketed grad reduce-scatter inside the "
                            "accumulation scan + ZeRO-1 sharded optimizer "
                            "update (opt-state HBM / data degree)")
        p.add_argument("--async-checkpointing", action="store_true",
                       help="publish checkpoints from a background thread "
                            "(snapshot in-step, fsync/manifest/rename off "
                            "the critical path)")
        p.add_argument("--fsdp", dest="fsdp_axis", nargs="?", const="data",
                       default="", metavar="AXIS",
                       help="shard params+optimizer state over AXIS "
                            "(default 'data') — ZeRO-3 analog")
        p.add_argument("--checkpoint-dir", type=str, default="",
                       help="enable the train supervisor: atomic periodic "
                            "checkpoints + auto-resume + SIGTERM handling")
        p.add_argument("--checkpoint-every", type=int, default=0)
        p.add_argument("--on-topology-change", type=str,
                       default="resume_resharded",
                       choices=("resume_resharded", "research", "abort"),
                       help="elastic resume policy when the visible "
                            "topology differs from the checkpoint's")
        p.add_argument("--no-verify-checkpoints", action="store_true",
                       help="skip content-hash manifest verification at "
                            "restore (on by default)")
        p.add_argument("--elastic-min-devices", type=int, default=1)
        p.add_argument("--serve-slots", type=int, default=4,
                       help="decode slots in the one compiled "
                            "slot-decode serving program")
        p.add_argument("--kv-page-size", type=int, default=128,
                       help="positions per paged-KV pool page "
                            "(power of two)")
        p.add_argument("--kv-pages", type=int, default=0,
                       help="KV pool pages (0 = derive the "
                            "no-pressure size)")
        p.add_argument("--no-prefix-cache", action="store_true",
                       help="disable the radix prefix cache "
                            "(on by default)")
        p.add_argument("--serve-speculate-k", type=int, default=0,
                       help="draft tokens proposed per speculative "
                            "decode iteration (0 = off; needs a "
                            "draft model)")
        p.add_argument("--serve-max-queue", type=int, default=0,
                       help="fleet-router queue bound: submissions past "
                            "it are rejected fast (0 = unbounded)")
        p.add_argument("--host-kv-pages", type=int, default=0,
                       help="pinned host-memory tier under the radix "
                            "prefix cache, in kv_page_size pages: "
                            "evicted ref-0 pages demote to host RAM "
                            "and promote back on a hit (0 = off)")
        p.add_argument("--serve-temperature", type=float, default=0.0,
                       help="default sampling temperature for serving "
                            "requests (0 = greedy argmax; per-request "
                            "submit() overrides)")
        p.add_argument("--serve-top-p", type=float, default=1.0,
                       help="default nucleus (top-p) filter in (0, 1] "
                            "(1 = off)")
        p.add_argument("--serve-top-k", type=int, default=0,
                       help="default top-k filter (0 = off)")
        p.add_argument("--serve-adapter-pool-pages", type=int, default=0,
                       help="paged LoRA adapter pool: device pages for "
                            "concurrently-resident adapters (0 = no "
                            "pool); tenants share one program, zero "
                            "recompiles")
        p.add_argument("--serve-lora-rank", type=int, default=8,
                       help="LoRA rank of the adapter pool's fixed page "
                            "geometry")
        p.add_argument("--serve-replica-roles", type=str, default="",
                       help="fleet replica roles, comma-separated "
                            "prefill|decode|mixed, one per replica "
                            "('' = all mixed); prefill replicas hand "
                            "finished KV pages off to decode replicas")
        p.add_argument("--prefill-interleave-chunks", type=int, default=0,
                       help="chunk-interleaved admission: max prefill "
                            "chunks the scheduler runs per step between "
                            "decode ticks (0 = run-to-completion "
                            "prefill at admission)")
        p.add_argument("--seq-parallel-shards", type=int, default=0,
                       help="sequence-parallel prefill: split a long "
                            "prompt's prefix into this many contiguous "
                            "shards across the prefill tier (0 = off, "
                            ">= 2 = shard count)")
        p.add_argument("--paged-attention-impl", type=str, default="auto",
                       choices=("auto", "pallas", "einsum"),
                       help="decode attention over the paged pool: "
                            "Pallas kernel vs einsum page-gather "
                            "(auto = pallas on TPU)")
        p.add_argument("--kv-cache-dtype", type=str, default="native",
                       choices=("native", "bf16", "int8", "fp8"),
                       help="paged KV pool storage dtype (int8/fp8: "
                            "per-page-per-head scales, in-kernel "
                            "dequant; 2-4x tokens per pool byte)")
        p.add_argument("--serve-weight-dtype", type=str, default="native",
                       choices=("native", "int8", "fp8"),
                       help="serving weight storage (weight-only "
                            "quantization with per-output-channel "
                            "scales, quantized once at engine init)")
        p.add_argument("--telemetry", type=str, default="on",
                       choices=("on", "off"),
                       help="unified telemetry plane: metrics registry "
                            "+ per-request trace ring (off = every "
                            "emit short-circuits)")
        p.add_argument("--sanitize", type=str, default="",
                       choices=("", "off", "on", "strict"),
                       help="ffsan runtime sanitizer: lock-order "
                            "asserting proxies + post-warmup "
                            "retrace sentinel ('' = follow "
                            "FF_SANITIZE; strict raises)")
        p.add_argument("--metrics-port", type=int, default=0,
                       help="serve Prometheus /metrics (+ /metrics.json"
                            ", /trace.json, /healthz, /slo.json) on "
                            "127.0.0.1:<port> (0 = no server)")
        p.add_argument("--flight-recorder-dir", type=str, default="",
                       help="post-mortem bundle directory: triggers "
                            "(watchdog/fence/rewind/fault/preempt/SLO "
                            "breach/manual) snapshot the recent trace "
                            "window + metrics + logs + HBM ledger into "
                            "atomic manifest-hashed bundles ('' = auto "
                            "triggers off)")
        p.add_argument("--flight-keep", type=int, default=4,
                       help="bundle retention: newest K survive")
        p.add_argument("--flight-cooldown-s", type=float, default=30.0,
                       help="one bundle per cooldown — a crash storm "
                            "writes one bundle, not N")
        p.add_argument("--flight-debounce-s", type=float, default=1.0,
                       help="triggers within this of the first merge "
                            "into ONE pending bundle (the storm's "
                            "causes all listed)")
        p.add_argument("--flight-window-s", type=float, default=120.0,
                       help="trace-ring window a bundle captures, in "
                            "seconds")
        p.add_argument("--slo-ttft-p99-s", type=float, default=0.0,
                       help="SLO ceiling: windowed p99 TTFT per replica "
                            "(0 = off)")
        p.add_argument("--slo-queue-wait-p99-s", type=float, default=0.0,
                       help="SLO ceiling: windowed p99 engine queue "
                            "wait (0 = off)")
        p.add_argument("--slo-prefix-hit-rate-min", type=float,
                       default=0.0,
                       help="SLO floor: windowed prefix-cache hit rate "
                            "(0 = off)")
        p.add_argument("--slo-spec-accept-min", type=float, default=0.0,
                       help="SLO floor: windowed speculative accept "
                            "rate (0 = off)")
        p.add_argument("--slo-step-time-p99-s", type=float, default=0.0,
                       help="SLO ceiling: windowed p99 train step time "
                            "(0 = off)")
        p.add_argument("--slo-checkpoint-stall-s", type=float,
                       default=0.0,
                       help="SLO ceiling: windowed p99 checkpoint "
                            "stall (0 = off)")
        p.add_argument("--slo-window-s", type=float, default=10.0,
                       help="SLO sliding-window length in seconds")
        p.add_argument("--slo-clear-windows", type=int, default=2,
                       help="hysteresis: consecutive healthy windows "
                            "required to clear a breach")
        p.add_argument("--slo-trip-recorder", action="store_true",
                       help="an SLO breach also trips the flight "
                            "recorder (needs --flight-recorder-dir)")
        p.add_argument("--autoscale-min-replicas", type=int, default=1,
                       help="elastic fleet: scale-in floor")
        p.add_argument("--autoscale-max-replicas", type=int, default=8,
                       help="elastic fleet: scale-out ceiling")
        p.add_argument("--autoscale-breach-windows", type=int, default=2,
                       help="consecutive SLO-breach windows before the "
                            "autoscaler adds a replica")
        p.add_argument("--autoscale-idle-windows", type=int, default=6,
                       help="consecutive idle windows before the "
                            "autoscaler retires a replica")
        p.add_argument("--autoscale-cooldown-s", type=float,
                       default=30.0,
                       help="refractory period between autoscaler "
                            "actions")
        p.add_argument("--preempt-deadline-s", type=float, default=5.0,
                       help="default evacuation budget when a replica "
                            "is preempted (SIGTERM/request_preempt)")
        # e.g. --mesh data=4,model=2 (replaces -ll:gpu device-count knobs)
        p.add_argument("--mesh", type=str, default="")
        args, _ = p.parse_known_args(argv)
        mesh_shape = None
        if args.mesh:
            mesh_shape = {}
            for part in args.mesh.split(","):
                ax, eq, size = part.partition("=")
                if not eq or not ax.strip() or not size.strip().isdigit() \
                        or int(size) < 1:
                    p.error(f"--mesh: bad entry {part!r}; expected "
                            f"'axis=size[,axis=size]', e.g. 'data=4,model=2'")
                mesh_shape[ax.strip()] = int(size)
        return FFConfig(
            batch_size=args.batch_size,
            epochs=args.epochs,
            learning_rate=args.lr,
            weight_decay=args.wd,
            search_budget=args.budget,
            search_alpha=args.alpha,
            import_strategy_file=args.import_file,
            export_strategy_file=args.export_file,
            enable_parameter_parallel=args.enable_parameter_parallel,
            enable_attribute_parallel=args.enable_attribute_parallel,
            measure_search_costs=("measure" if args.measure_costs else
                                  "analyze" if args.analyze_costs else False),
            cost_db_path=args.cost_db,
            taskgraph_file=args.taskgraph,
            profiling=args.profiling,
            perform_fusion=args.fusion,
            num_devices=args.num_devices,
            mesh_shape=mesh_shape,
            overlap_grad_sync=args.overlap_grad_sync,
            async_checkpointing=args.async_checkpointing,
            fsdp_axis=args.fsdp_axis,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            on_topology_change=args.on_topology_change,
            verify_checkpoints=not args.no_verify_checkpoints,
            elastic_min_devices=args.elastic_min_devices,
            serve_slots=args.serve_slots,
            kv_page_size=args.kv_page_size,
            kv_pages=args.kv_pages,
            serve_prefix_cache=not args.no_prefix_cache,
            serve_speculate_k=args.serve_speculate_k,
            serve_max_queue=args.serve_max_queue,
            host_kv_pages=args.host_kv_pages,
            serve_temperature=args.serve_temperature,
            serve_top_p=args.serve_top_p,
            serve_top_k=args.serve_top_k,
            serve_adapter_pool_pages=args.serve_adapter_pool_pages,
            serve_lora_rank=args.serve_lora_rank,
            serve_replica_roles=args.serve_replica_roles,
            prefill_interleave_chunks=args.prefill_interleave_chunks,
            seq_parallel_shards=args.seq_parallel_shards,
            paged_attention_impl=args.paged_attention_impl,
            kv_cache_dtype=args.kv_cache_dtype,
            serve_weight_dtype=args.serve_weight_dtype,
            telemetry=args.telemetry,
            sanitize=args.sanitize,
            metrics_port=args.metrics_port,
            flight_recorder_dir=args.flight_recorder_dir,
            flight_keep=args.flight_keep,
            flight_cooldown_s=args.flight_cooldown_s,
            flight_debounce_s=args.flight_debounce_s,
            flight_window_s=args.flight_window_s,
            slo_ttft_p99_s=args.slo_ttft_p99_s,
            slo_queue_wait_p99_s=args.slo_queue_wait_p99_s,
            slo_prefix_hit_rate_min=args.slo_prefix_hit_rate_min,
            slo_spec_accept_min=args.slo_spec_accept_min,
            slo_step_time_p99_s=args.slo_step_time_p99_s,
            slo_checkpoint_stall_s=args.slo_checkpoint_stall_s,
            slo_window_s=args.slo_window_s,
            slo_clear_windows=args.slo_clear_windows,
            slo_trip_recorder=args.slo_trip_recorder,
            autoscale_min_replicas=args.autoscale_min_replicas,
            autoscale_max_replicas=args.autoscale_max_replicas,
            autoscale_breach_windows=args.autoscale_breach_windows,
            autoscale_idle_windows=args.autoscale_idle_windows,
            autoscale_cooldown_s=args.autoscale_cooldown_s,
            preempt_deadline_s=args.preempt_deadline_s,
        )
