"""Strategy persistence — reference-compatible text schema.

Format (reference: src/runtime/strategy.cc:95-189):

    <num_ops>
    <op name>
    <device_type int>        # reference GPU=0? serialized as in enum; we write 1
    <nDims>
    <dim[0]> <dim[1]> ... (tab separated, REVERSED logical order: sample last)
    <num_device_ids>
    <id0> <id1> ...

The reference keys strategies by hash(op name) (strategy.cc:22-25) used as a
Legion MappingTagID; we key by the op name itself.

Extension (ours, backward compatible): an optional `@axismap` record after
an op's ids persists the EXACT mesh-axis assignment —

    @axismap <k> <axis0> <dim0> ... <axis_{k-1}> <dim_{k-1}>

with dim -1 = replicated over that axis, -2 = CONTRACT (row-parallel),
-3 = STAGE (pipeline). Degrees alone cannot express CONTRACT/STAGE (they
shard weights, not the output) or axis names, so without this record a
search-discovered PP or row-parallel strategy would not survive a
save/load round trip (the loader would fall back to the greedy
degree->axis heuristic, resolve_axis_map). Reference-written files never
contain `@` tokens, so they load unchanged; our files with the extension
are NOT parseable by the reference (it never reads our files — SURVEY
§7.6 cross-parse compat is reference->us only)."""

from __future__ import annotations

from typing import Dict

from flexflow_tpu.parallel.pconfig import STAGE, ParallelConfig

# Device-type serialization. The file int is a POOL, not a vendor: int 0
# means "the accelerator pool" — the reference writes its GPU enum there
# (strategy.cc device_type), and this rebuild executes the same record on
# TPU, so a reference-written GPU strategy deliberately loads as "TPU".
# Int 1 is the host CPU backend (the reference's hetero DLRM embeddings,
# dlrm_strategy_hetero.cc). Round-trip consequence: "GPU" is write-only —
# it normalizes to the accelerator int and reloads as "TPU"; everything
# about the record other than the vendor label survives exactly
# (tested in tests/test_strategy_schema.py).
_DEVICE_TYPE_TO_INT = {"GPU": 0, "CPU": 1, "TPU": 0}
_INT_TO_DEVICE_TYPE = {0: "TPU", 1: "CPU"}


def _ids_consistent(pc: ParallelConfig) -> bool:
    """True when pc.device_ids is representable as-is: one id per shard,
    or a stage-multiple list for STAGE strategies (the stage size itself
    is unknowable without the mesh — fflint's device-block-too-small is
    the mesh-aware check)."""
    n = pc.num_parts()
    if len(pc.device_ids) == n:
        return True
    has_stage = bool(pc.axis_map) and any(
        d == STAGE for d in pc.axis_map.values())
    return bool(has_stage and pc.device_ids
                and len(pc.device_ids) % max(n, 1) == 0)


def save_strategies_to_file(filename: str,
                            strategies: Dict[str, ParallelConfig],
                            strict: bool = False) -> None:
    if strict:
        # validate the WHOLE table before the first byte is written — a
        # mid-write raise would strand a truncated file whose op-count
        # header disagrees with its body
        for name in sorted(strategies):
            pc = strategies[name]
            if pc.device_ids and not _ids_consistent(pc):
                raise ValueError(
                    f"strategy {name!r}: {len(pc.device_ids)} device_ids "
                    f"for {pc.num_parts()} partitions (degrees "
                    f"{tuple(pc.dims)}) — the schema needs exactly one id "
                    f"per shard; writing range({pc.num_parts()}) instead "
                    f"(strict mode)")
    with open(filename, "w") as f:
        f.write(f"{len(strategies)}\n")
        for name in sorted(strategies):
            pc = strategies[name]
            f.write(f"{name}\n")
            f.write(f"{_DEVICE_TYPE_TO_INT.get(pc.device_type, 0)}\n")
            f.write(f"{pc.nDims}\n")
            f.write("\t".join(str(d) for d in reversed(pc.dims)) + "\n")
            n = pc.num_parts()
            ids = pc.device_ids
            # a stage-multiple id list is the canonical STAGE form
            # (_ids_consistent); an inconsistent list cannot be
            # represented (the schema pairs shard i with device_ids[i]) —
            # name the op and what happens instead of rewriting silently
            # (strict mode raised on the whole table before writing)
            if pc.device_ids and not _ids_consistent(pc):
                from flexflow_tpu.logger import fflogger

                fflogger.warning(
                    "strategy %r: %d device_ids for %d partitions "
                    "(degrees %s) — the schema needs exactly one id per "
                    "shard; writing range(%d) instead",
                    name, len(pc.device_ids), n, tuple(pc.dims), n)
                ids = tuple(range(n))
            elif not ids:
                ids = tuple(range(n))
            f.write(f"{len(ids)}\n")
            f.write("\t".join(str(i) for i in ids) + "\n")
            if pc.axis_map is not None:
                # an EMPTY axis_map ("explicitly replicated") still writes
                # a record — omitting it would reload as None and fall
                # back to the greedy degree->axis heuristic, breaking the
                # exact round trip the schema lint checks
                parts = []
                for ax, d in pc.axis_map.items():
                    parts.append(str(ax))
                    parts.append(str(-1 if d is None else d))
                f.write(f"@axismap {len(pc.axis_map)}"
                        + ("\t" + "\t".join(parts) if parts else "") + "\n")


def load_strategies_from_file(filename: str) -> Dict[str, ParallelConfig]:
    with open(filename) as f:
        tokens = f.read().split()
    pos = 0

    def take() -> str:
        nonlocal pos
        t = tokens[pos]
        pos += 1
        return t

    out: Dict[str, ParallelConfig] = {}
    num_ops = int(take())
    for _ in range(num_ops):
        name = take()
        device_type = _INT_TO_DEVICE_TYPE.get(int(take()), "TPU")
        ndims = int(take())
        rev_dims = [int(take()) for _ in range(ndims)]
        nids = int(take())
        ids = tuple(int(take()) for _ in range(nids))
        axis_map = None
        if pos < len(tokens) and tokens[pos] == "@axismap":
            take()
            k = int(take())
            axis_map = {}
            for _ in range(k):
                ax = take()
                d = int(take())
                axis_map[ax] = None if d == -1 else d
        out[name] = ParallelConfig(
            device_type=device_type,
            dims=tuple(reversed(rev_dims)),
            device_ids=ids,
            axis_map=axis_map,
        )
    return out
