"""Strategy persistence — reference-compatible text schema.

Format (reference: src/runtime/strategy.cc:95-189):

    <num_ops>
    <op name>
    <device_type int>        # reference GPU=0? serialized as in enum; we write 1
    <nDims>
    <dim[0]> <dim[1]> ... (tab separated, REVERSED logical order: sample last)
    <num_device_ids>
    <id0> <id1> ...

The reference keys strategies by hash(op name) (strategy.cc:22-25) used as a
Legion MappingTagID; we key by the op name itself.

Extension (ours, backward compatible): an optional `@axismap` record after
an op's ids persists the EXACT mesh-axis assignment —

    @axismap <k> <axis0> <dim0> ... <axis_{k-1}> <dim_{k-1}>

with dim -1 = replicated over that axis, -2 = CONTRACT (row-parallel),
-3 = STAGE (pipeline). Degrees alone cannot express CONTRACT/STAGE (they
shard weights, not the output) or axis names, so without this record a
search-discovered PP or row-parallel strategy would not survive a
save/load round trip (the loader would fall back to the greedy
degree->axis heuristic, resolve_axis_map). Reference-written files never
contain `@` tokens, so they load unchanged; our files with the extension
are NOT parseable by the reference (it never reads our files — SURVEY
§7.6 cross-parse compat is reference->us only)."""

from __future__ import annotations

from typing import Dict

from flexflow_tpu.parallel.pconfig import ParallelConfig

_DEVICE_TYPE_TO_INT = {"GPU": 0, "CPU": 1, "TPU": 0}
_INT_TO_DEVICE_TYPE = {0: "TPU", 1: "CPU"}


def save_strategies_to_file(filename: str, strategies: Dict[str, ParallelConfig]) -> None:
    with open(filename, "w") as f:
        f.write(f"{len(strategies)}\n")
        for name in sorted(strategies):
            pc = strategies[name]
            f.write(f"{name}\n")
            f.write(f"{_DEVICE_TYPE_TO_INT.get(pc.device_type, 0)}\n")
            f.write(f"{pc.nDims}\n")
            f.write("\t".join(str(d) for d in reversed(pc.dims)) + "\n")
            n = pc.num_parts()
            f.write(f"{n}\n")
            ids = pc.device_ids if len(pc.device_ids) == n else tuple(range(n))
            f.write("\t".join(str(i) for i in ids) + "\n")
            if pc.axis_map:
                parts = []
                for ax, d in pc.axis_map.items():
                    parts.append(str(ax))
                    parts.append(str(-1 if d is None else d))
                f.write(f"@axismap {len(pc.axis_map)} "
                        + "\t".join(parts) + "\n")


def load_strategies_from_file(filename: str) -> Dict[str, ParallelConfig]:
    with open(filename) as f:
        tokens = f.read().split()
    pos = 0

    def take() -> str:
        nonlocal pos
        t = tokens[pos]
        pos += 1
        return t

    out: Dict[str, ParallelConfig] = {}
    num_ops = int(take())
    for _ in range(num_ops):
        name = take()
        device_type = _INT_TO_DEVICE_TYPE.get(int(take()), "TPU")
        ndims = int(take())
        rev_dims = [int(take()) for _ in range(ndims)]
        nids = int(take())
        ids = tuple(int(take()) for _ in range(nids))
        axis_map = None
        if pos < len(tokens) and tokens[pos] == "@axismap":
            take()
            k = int(take())
            axis_map = {}
            for _ in range(k):
                ax = take()
                d = int(take())
                axis_map[ax] = None if d == -1 else d
        out[name] = ParallelConfig(
            device_type=device_type,
            dims=tuple(reversed(rev_dims)),
            device_ids=ids,
            axis_map=axis_map,
        )
    return out
