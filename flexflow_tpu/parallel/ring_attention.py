"""Ring attention: sequence/context parallelism over the 'seq' mesh axis.

Net-new capability vs the reference, whose attention asserts batch-only
partitioning (reference: src/ops/attention.cu:118-120; SURVEY §5.7). Design:
K/V shards rotate around the ICI ring via `jax.lax.ppermute` while each
device's Q shard accumulates attention with online-softmax rescaling
(blockwise/flash-style running max/sum), so sequence length scales with the
number of devices at O(S/P) activation memory per chip and compute overlaps
the rotation.

Also provides the Ulysses lowering (all-to-all head<->seq swap) as the
alternative SP strategy, and a blockwise local attention step shared by both.

All functions here must be called INSIDE shard_map (they use axis_name
collectives); flexflow_tpu/ops/attention.py wires them into MultiHeadAttention
when the strategy shards the sequence dim.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from flexflow_tpu._env import lax_axis_size

NEG_INF = -1e30


def pvary(x, axis_name):
    """Mark x as device-varying over axis_name (vma typing for scan carries).
    jax.lax.pvary was renamed to pcast(..., to='varying')."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis_name)
    return x


def _block_attend(q, k, v, m, l, o, scale, mask=None, dropout_rng=None,
                  dropout_rate=0.0):
    """One online-softmax accumulation step.

    q: (B, Sq, H, D); k/v: (B, Sk, H, D); m,l: (B, H, Sq); o: (B, Sq, H, D).
    Returns updated (m, l, o). f32 accumulation regardless of input dtype.

    Attention dropout: the Bernoulli mask is applied to the unnormalized
    block probs feeding the value product, while `l` keeps accumulating the
    undropped sum — the final o/l division then equals dropout(softmax(s)) @ v
    of the dense formulation exactly (dropout commutes with the global
    normalization elementwise).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)                      # (B, H, Sq)
    p = jnp.exp(s - m_new[..., None])               # (B, H, Sq, Sk)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv_in = p
    if dropout_rng is not None and dropout_rate > 0.0:
        keep = 1.0 - dropout_rate
        drop_mask = jax.random.bernoulli(dropout_rng, keep, p.shape)
        pv_in = jnp.where(drop_mask, p / keep, 0.0)
    pv = jnp.einsum("bhqk,bkhd->bqhd", pv_in.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _flash_block(q, k, v, causal: bool, scale: float):
    """One ring step through the Pallas flash kernel: the block's normalized
    output (B,S,H,D) f32 and logsumexp (B,H,S)."""
    from flexflow_tpu.ops.pallas_kernels import flash_attention_fwd_pallas

    b, sq, h, d = q.shape
    out, lse8 = flash_attention_fwd_pallas(q, k, v, causal, scale)
    o = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return o.astype(jnp.float32), lse8[..., 0].reshape(b, h, sq)


def _merge_blocks(o, lse, o_s, lse_s):
    """Combine two normalized attention partials by their logsumexps.
    (An all-masked partial carries lse = NEG_INF = -1e30; its weight
    exp(NEG_INF - new_lse) underflows to exactly 0.)"""
    new_lse = jnp.logaddexp(lse, lse_s)
    o_new = (o * jnp.exp(lse - new_lse).transpose(0, 2, 1)[..., None]
             + o_s * jnp.exp(lse_s - new_lse).transpose(0, 2, 1)[..., None])
    return o_new, new_lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention_flash(q, k, v, axis_name: str, causal: bool = False,
                         scale: Optional[float] = None):
    """Ring attention with the Pallas flash kernel as the per-step block
    compute (VERDICT r1 #4: the kernel on the SP hot path). Forward: each
    step attends the local Q shard against the visiting K/V shard entirely
    in-kernel; partials merge by logsumexp; future shards are skipped (the
    kernel never launches for fully-masked steps). Backward: the standard
    memory-efficient ring trick — only (q, k, v, o, lse) per device is
    saved (O(S/P)); K/V re-rotate around the ring while dk/dv buffers
    counter-rotate back to their owners, each step running the
    FlashAttention-2 block backward against the GLOBAL logsumexp."""
    o, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale)
    return o.astype(q.dtype)


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale):
    p_size = lax_axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    o0 = jnp.zeros((b, sq, h, d), jnp.float32)
    lse0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    o0, lse0 = (pvary(t, axis_name) for t in (o0, lse0))
    perm = [(i, (i - 1) % p_size) for i in range(p_size)]

    def step(carry, step_idx):
        o, lse, k_cur, v_cur = carry
        if causal:
            src = (my_idx + step_idx) % p_size

            def self_block(_):
                return _flash_block(q, k_cur, v_cur, True, scale)

            def full_block(_):
                return _flash_block(q, k_cur, v_cur, False, scale)

            def skip_block(_):  # future shard: no kernel launch at all
                return (jnp.zeros((b, sq, h, d), jnp.float32),
                        jnp.full((b, h, sq), NEG_INF, jnp.float32))

            which = jnp.where(step_idx == 0, 0, jnp.where(src > my_idx, 2, 1))
            o_s, lse_s = lax.switch(which, [self_block, full_block,
                                            skip_block], operand=None)
        else:
            o_s, lse_s = _flash_block(q, k_cur, v_cur, False, scale)
        o, lse = _merge_blocks(o, lse, o_s, lse_s)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, lse, k_nxt, v_nxt), None

    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(p_size))
    return o, lse


def _ring_flash_fwd(q, k, v, axis_name, causal, scale):
    o, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale)
    # O(S/P) residuals per device: local shards + local output + local lse
    return o.astype(q.dtype), (q, k, v, o.astype(q.dtype), lse)


def _ring_flash_bwd(axis_name, causal, scale, res, do):
    from flexflow_tpu.ops.pallas_kernels import flash_attention_bwd_pallas

    q, k, v, o, lse = res
    p_size = lax_axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale_v = scale if scale is not None else 1.0 / math.sqrt(d)
    # the block backward consumes the GLOBAL logsumexp (p = exp(s - LSE) is
    # the true global probability of each visiting block)
    lse8 = jnp.broadcast_to(lse.reshape(b * h, sq)[..., None],
                            (b * h, sq, 8))
    do = do.astype(q.dtype)
    # delta is loop-invariant (depends only on do and the final output);
    # compute once so the scan body doesn't re-emit it every ring step
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = delta.transpose(0, 2, 1)  # (B, H, Sq)
    perm = [(i, (i - 1) % p_size) for i in range(p_size)]

    def block_bwd(k_cur, v_cur, causal_flag):
        return flash_attention_bwd_pallas(q, k_cur, v_cur, o, lse8, do,
                                          causal_flag, scale_v,
                                          delta_precomputed=delta)

    def body(carry, step_idx):
        dq_acc, dk_buf, dv_buf, k_cur, v_cur = carry
        if causal:
            src = (my_idx + step_idx) % p_size

            def self_block(_):
                return block_bwd(k_cur, v_cur, True)

            def full_block(_):
                return block_bwd(k_cur, v_cur, False)

            def skip_block(_):
                return (jnp.zeros((b, sq, h, d), q.dtype),
                        jnp.zeros((b, sk, h, d), k.dtype),
                        jnp.zeros((b, sk, h, d), v.dtype))

            which = jnp.where(step_idx == 0, 0, jnp.where(src > my_idx, 2, 1))
            dq_s, dk_s, dv_s = lax.switch(which, [self_block, full_block,
                                                  skip_block], operand=None)
        else:
            dq_s, dk_s, dv_s = block_bwd(k_cur, v_cur, False)
        dq_acc = dq_acc + dq_s.astype(jnp.float32)
        dk_buf = dk_buf + dk_s.astype(jnp.float32)
        dv_buf = dv_buf + dv_s.astype(jnp.float32)
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_buf = lax.ppermute(dk_buf, axis_name, perm)
        dv_buf = lax.ppermute(dv_buf, axis_name, perm)
        return (dq_acc, dk_buf, dv_buf, k_cur, v_cur), None

    z = lambda shape: pvary(jnp.zeros(shape, jnp.float32), axis_name)
    init = (z((b, sq, h, d)), z((b, sk, h, d)), z((b, sk, h, d)), k, v)
    (dq_acc, dk_buf, dv_buf, _, _), _ = lax.scan(body, init,
                                                 jnp.arange(p_size))
    return (dq_acc.astype(q.dtype), dk_buf.astype(k.dtype),
            dv_buf.astype(v.dtype))


ring_attention_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None, dropout_rate: float = 0.0,
                   dropout_rng=None, use_flash: Optional[bool] = None):
    """Ring self-attention inside shard_map.

    q, k, v: (B, S_local, H, D) — the local sequence shard.
    Rotates K/V left around `axis_name`; after P steps every Q shard has
    attended to the full sequence. When the Pallas kernel applies (TPU or
    forced, no dropout), the per-step block compute runs in-kernel
    (ring_attention_flash); otherwise the pure-JAX online-softmax path.
    """
    if use_flash and dropout_rate > 0.0:
        raise ValueError(
            "use_flash=True is incompatible with attention dropout (the "
            "Pallas kernels have no dropout path); drop the flag to use the "
            "pure-JAX ring")
    if use_flash is None:
        import os

        from flexflow_tpu.ops.attention import flash_seq_cap

        cap = flash_seq_cap()
        use_flash = ((jax.default_backend() == "tpu"
                      or os.environ.get("FF_FORCE_FLASH_ATTENTION") == "1")
                     and dropout_rate == 0.0
                     # deployment escape hatch (FF_FLASH_MAX_SEQ): oversized
                     # local shards take the pure-JAX ring instead
                     and (not cap
                          or max(q.shape[1], k.shape[1]) <= cap))
    if use_flash:
        return ring_attention_flash(q, k, v, axis_name, causal, scale)
    p_size = lax_axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, sq, h, d), jnp.float32)
    # mark the fresh accumulators as device-varying over the ring axis so the
    # scan carry type matches after the first accumulation step
    m0, l0, o0 = (pvary(t, axis_name) for t in (m0, l0, o0))

    q_pos = my_idx * sq + jnp.arange(sq)  # global positions of local queries
    # per-device dropout stream: each (device, ring step) sees an
    # independent Bernoulli mask over its local (q block, k block) tile
    if dropout_rng is not None and dropout_rate > 0.0:
        dropout_rng = jax.random.fold_in(dropout_rng, my_idx)

    def step(carry, step_idx):
        m, l, o, k_cur, v_cur = carry
        # k_cur currently holds the shard originally owned by (my_idx + step)
        src = (my_idx + step_idx) % p_size
        if causal:
            k_pos = src * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]          # (Sq, Sk)
            mask = mask[None, None, :, :]                    # (1,1,Sq,Sk)
        else:
            mask = None
        step_rng = None
        if dropout_rng is not None and dropout_rate > 0.0:
            step_rng = jax.random.fold_in(dropout_rng, step_idx)
        m, l, o = _block_attend(q, k_cur, v_cur, m, l, o, scale, mask,
                                dropout_rng=step_rng,
                                dropout_rate=dropout_rate)
        # rotate: receive the next shard from the right neighbor
        perm = [(i, (i - 1) % p_size) for i in range(p_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m, l, o, k_nxt, v_nxt), None

    (m, l, o, _, _), _ = lax.scan(step, (m0, l0, o0, k, v),
                                  jnp.arange(p_size))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None,
                      dropout_rate: float = 0.0, dropout_rng=None):
    """Ulysses (DeepSpeed-style) SP inside shard_map: all-to-all swaps the
    sequence shard for a head shard, attention runs with full sequence on
    1/P of the heads, then swaps back. Requires num_heads % P == 0."""
    p_size = lax_axis_size(axis_name)
    b, sq, h, d = q.shape
    assert h % p_size == 0, f"heads {h} not divisible by seq-parallel {p_size}"

    def seq2head(x):
        # (B, S/P, H, D) -> (B, S, H/P, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head2seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    if dropout_rng is not None and dropout_rate > 0.0:
        # after the swap each device owns a disjoint head shard — fold the
        # device index in so head shards draw independent masks
        dropout_rng = jax.random.fold_in(dropout_rng, lax.axis_index(axis_name))
    qf, kf, vf = seq2head(q), seq2head(k), seq2head(v)
    out = blockwise_attention(qf, kf, vf, causal=causal, scale=scale,
                              dropout_rate=dropout_rate,
                              dropout_rng=dropout_rng)
    return head2seq(out)


def blockwise_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None,
                        block_size: int = 512,
                        dropout_rate: float = 0.0, dropout_rng=None):
    """Memory-efficient local attention: lax.scan over K/V blocks with online
    softmax (flash-attention recurrence in pure JAX — XLA keeps the working
    set at O(block) and fuses; the Pallas kernel in ops/pallas_kernels.py is
    the hand-tiled variant used on TPU when shapes allow)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if sk <= block_size:
        mask = None
        if causal:
            mask = (jnp.arange(sq)[:, None] + (sk - sq)
                    >= jnp.arange(sk)[None, :])[None, None]
        m, l, o = _block_attend(
            q, k, v,
            jnp.full((b, h, sq), NEG_INF, jnp.float32),
            jnp.zeros((b, h, sq), jnp.float32),
            jnp.zeros((b, sq, h, d), jnp.float32), scale, mask,
            dropout_rng=dropout_rng, dropout_rate=dropout_rate)
        return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)

    nblocks = (sk + block_size - 1) // block_size
    assert sk % block_size == 0, f"seq {sk} % block {block_size} != 0"
    kb = k.reshape(b, nblocks, block_size, h, d)
    vb = v.reshape(b, nblocks, block_size, h, d)
    q_pos = jnp.arange(sq) + (sk - sq)  # align causal diag when sq != sk

    def step(carry, blk):
        m, l, o = carry
        k_cur, v_cur, blk_idx = blk
        mask = None
        if causal:
            k_pos = blk_idx * block_size + jnp.arange(block_size)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
        blk_rng = None
        if dropout_rng is not None and dropout_rate > 0.0:
            blk_rng = jax.random.fold_in(dropout_rng, blk_idx)
        m, l, o = _block_attend(q, k_cur, v_cur, m, l, o, scale, mask,
                                dropout_rng=blk_rng,
                                dropout_rate=dropout_rate)
        return (m, l, o), None

    init = (jnp.full((b, h, sq), NEG_INF, jnp.float32),
            jnp.zeros((b, h, sq), jnp.float32),
            jnp.zeros((b, sq, h, d), jnp.float32))
    (m, l, o), _ = lax.scan(
        step, init,
        (kb.transpose(1, 0, 2, 3, 4), vb.transpose(1, 0, 2, 3, 4),
         jnp.arange(nblocks)))
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)
