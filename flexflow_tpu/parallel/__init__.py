from flexflow_tpu.parallel.pconfig import ParallelConfig  # noqa: F401
from flexflow_tpu.parallel.mesh import make_mesh, default_mesh  # noqa: F401


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map across JAX versions: new jax.shard_map takes check_vma,
    older jax.experimental.shard_map takes check_rep."""
    import jax as _jax

    if hasattr(_jax, "shard_map"):
        return _jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    try:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)
    except TypeError:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
