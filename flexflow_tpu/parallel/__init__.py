from flexflow_tpu.parallel.pconfig import ParallelConfig  # noqa: F401
from flexflow_tpu.parallel.mesh import make_mesh, default_mesh  # noqa: F401
