from flexflow_tpu.parallel.pconfig import ParallelConfig  # noqa: F401
from flexflow_tpu.parallel.mesh import make_mesh, default_mesh  # noqa: F401


def shard_entries(mesh, axis_map, shape, dims):
    """For each tensor dim in `dims`: the PartitionSpec entry (axis name,
    tuple of names, or None) the strategy shards it over — None when the
    dim is unsharded OR its size is not divisible by the mapped mesh degree
    (that group alone degrades to GSPMD padding while the rest keeps its
    parallelism). Shared by every per-shard Pallas lowering
    (ops/attention._flash_dense, ops/norm.AddLayerNorm)."""
    out = {}
    for d in dims:
        axes = [ax for ax, dd in (axis_map or {}).items()
                if dd == d and mesh.shape[ax] > 1]
        deg = 1
        for ax in axes:
            deg *= mesh.shape[ax]
        if shape[d] % deg != 0:
            axes = []
        if not axes:
            out[d] = None
        else:
            out[d] = axes[0] if len(axes) == 1 else tuple(axes)
    return out


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map across JAX versions: new jax.shard_map takes check_vma,
    older jax.experimental.shard_map takes check_rep."""
    import jax as _jax

    if hasattr(_jax, "shard_map"):
        return _jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    try:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)
    except TypeError:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
