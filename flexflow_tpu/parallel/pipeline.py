"""Pipeline parallelism over the 'pipe' mesh axis.

The reference has pipelining only in its hand-rolled NMT subsystem (sequence
chunked LSTM_PER_NODE_LENGTH=10 per device, per-(layer,timestep)
ParallelConfig tables — nmt/rnn.h:21-63). TPU re-design: a circulating
(collective-permute) GPipe loop inside shard_map — every device holds ONE
stage's params (stacked params sharded on dim 0 over 'pipe'); microbatches
ripple through the ring via `lax.ppermute`; the whole schedule is a
`lax.scan`, so it jits into one XLA program and autodiff gives pipelined
backward for free.

Constraint (classic for this scheme): all stages share one activation shape.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe_loop(stage_fn: Callable, stage_params, x_mb, axis_name: str):
    """Run inside shard_map. stage_params: this device's stage params (pytree,
    leading stage dim already stripped). x_mb: (num_micro, mb, ...) — the full
    microbatched input (replicated; only stage 0 reads it). Returns
    (num_micro, mb, ...) outputs (valid on the LAST stage; use
    `pipeline()` below for the replicated gather)."""
    n_stage = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    num_micro = x_mb.shape[0]
    steps = num_micro + n_stage - 1
    mb_shape = x_mb.shape[1:]

    from flexflow_tpu.parallel.ring_attention import pvary

    buf0 = jnp.zeros(mb_shape, x_mb.dtype)  # activation arriving at this stage
    out0 = jnp.zeros_like(x_mb)
    buf0, out0 = pvary(buf0, axis_name), pvary(out0, axis_name)
    perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

    def step(carry, t):
        buf, outs = carry
        # stage 0 ingests microbatch t (clamped; bubbles compute garbage that
        # is never written out)
        mb_idx = jnp.clip(t, 0, num_micro - 1)
        inp = jnp.where(idx == 0, x_mb[mb_idx], buf)
        y = stage_fn(stage_params, inp)
        # last stage completed microbatch t-(n_stage-1) this step
        done_idx = t - (n_stage - 1)
        write = jnp.logical_and(idx == n_stage - 1, done_idx >= 0)
        outs = lax.cond(
            write,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(done_idx, 0, num_micro - 1), 0),
            lambda o: o, outs)
        buf_next = lax.ppermute(y, axis_name, perm)
        return (buf_next, outs), None

    (_, outs), _ = lax.scan(step, (buf0, out0), jnp.arange(steps))
    return outs


def pipeline(stage_fn: Callable, stacked_params, x, mesh, axis_name: str = "pipe",
             num_microbatches: int = None, data_axis: str = None):
    """User-facing pipelined apply.

    stage_fn(params_i, x) -> y with y.shape == x.shape
    stacked_params: pytree with leading dim = num_stages
    x: (batch, ...) global input. Returns (batch, ...) output.
    data_axis: optional mesh axis the microbatch dim is ALSO sharded over
    (composes dp x pp: each pipe ring runs on its data slice).
    """
    from jax.sharding import PartitionSpec as P

    n_stage = mesh.shape[axis_name]
    num_micro = num_microbatches or n_stage
    b = x.shape[0]
    assert b % num_micro == 0, f"batch {b} % microbatches {num_micro}"
    x_mb = x.reshape(num_micro, b // num_micro, *x.shape[1:])

    def inner(params, xm):
        params = jax.tree_util.tree_map(lambda a: a[0], params)  # strip stage dim
        outs = gpipe_loop(stage_fn, params, xm, axis_name)
        # broadcast final outputs from the last stage to all stages so the
        # result is replicated over 'pipe' (psum of one-hot contribution)
        idx = lax.axis_index(axis_name)
        contrib = jnp.where(idx == n_stage - 1, outs, jnp.zeros_like(outs))
        return lax.psum(contrib, axis_name)

    from flexflow_tpu.parallel import shard_map_compat

    dp = (data_axis if data_axis and mesh.shape.get(data_axis, 1) > 1
          else None)
    pspec = jax.tree_util.tree_map(
        lambda a: P(axis_name, *([None] * (a.ndim - 1))), stacked_params)
    xspec = P(None, dp) if dp else P()
    out = shard_map_compat(inner, mesh, (pspec, xspec), xspec)(
        stacked_params, x_mb)
    return out.reshape(b, *out.shape[2:])
