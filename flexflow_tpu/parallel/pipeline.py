"""Pipeline parallelism over a 'pipe' (or search-assigned STAGE) mesh axis.

The reference has pipelining only in its hand-rolled NMT subsystem (sequence
chunked LSTM_PER_NODE_LENGTH=10 per device, per-(layer,timestep)
ParallelConfig tables — nmt/rnn.h:21-63). TPU re-design: circulating
(collective-permute) schedules inside shard_map — every device holds ONE
stage's params (stacked params sharded on dim 0 over the axis); microbatches
ripple through the ring via `lax.ppermute`; the whole schedule is a
`lax.scan`, so it jits into one XLA program.

Two schedules:
  * `pipeline` — GPipe forward; under outer autodiff the reverse scan gives
    a pipelined backward, stashing per-(tick) residuals: O(num_micro)
    boundary activations per device.
  * `pipeline_train_1f1b` — a hand-scheduled one-forward-one-backward
    training step: each scan tick runs (at most) one microbatch forward AND
    one backward, with the backward recomputing its stage from a stashed
    input (activation recompute). The stash is a ring of
    min(num_micro, 2*stages - 1) microbatch INPUTS — per-device activation
    memory is O(stages), independent of num_micro, which is the 1F1B memory
    property GPipe lacks.

Constraint (classic for both): all stages share one activation shape.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from flexflow_tpu._env import lax_axis_size


def gpipe_loop(stage_fn: Callable, stage_params, x_mb, axis_name: str):
    """Run inside shard_map. stage_params: this device's stage params (pytree,
    leading stage dim already stripped). x_mb: (num_micro, mb, ...) — the full
    microbatched input (replicated; only stage 0 reads it). Returns
    (num_micro, mb, ...) outputs (valid on the LAST stage; use
    `pipeline()` below for the replicated gather)."""
    n_stage = lax_axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    num_micro = x_mb.shape[0]
    steps = num_micro + n_stage - 1
    mb_shape = x_mb.shape[1:]

    from flexflow_tpu.parallel.ring_attention import pvary

    buf0 = jnp.zeros(mb_shape, x_mb.dtype)  # activation arriving at this stage
    out0 = jnp.zeros_like(x_mb)
    buf0, out0 = pvary(buf0, axis_name), pvary(out0, axis_name)
    perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

    def step(carry, t):
        buf, outs = carry
        # stage 0 ingests microbatch t (clamped; bubbles compute garbage that
        # is never written out)
        mb_idx = jnp.clip(t, 0, num_micro - 1)
        inp = jnp.where(idx == 0, x_mb[mb_idx], buf)
        y = stage_fn(stage_params, inp)
        # last stage completed microbatch t-(n_stage-1) this step
        done_idx = t - (n_stage - 1)
        write = jnp.logical_and(idx == n_stage - 1, done_idx >= 0)
        outs = lax.cond(
            write,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(done_idx, 0, num_micro - 1), 0),
            lambda o: o, outs)
        buf_next = lax.ppermute(y, axis_name, perm)
        return (buf_next, outs), None

    (_, outs), _ = lax.scan(step, (buf0, out0), jnp.arange(steps))
    return outs


def pipeline(stage_fn: Callable, stacked_params, x, mesh, axis_name: str = "pipe",
             num_microbatches: int = None, data_axis: str = None):
    """User-facing pipelined apply.

    stage_fn(params_i, x) -> y with y.shape == x.shape
    stacked_params: pytree with leading dim = num_stages
    x: (batch, ...) global input. Returns (batch, ...) output.
    data_axis: optional mesh axis the microbatch dim is ALSO sharded over
    (composes dp x pp: each pipe ring runs on its data slice).
    """
    from jax.sharding import PartitionSpec as P

    n_stage = mesh.shape[axis_name]
    num_micro = num_microbatches or n_stage
    b = x.shape[0]
    assert b % num_micro == 0, f"batch {b} % microbatches {num_micro}"
    x_mb = x.reshape(num_micro, b // num_micro, *x.shape[1:])

    def inner(params, xm):
        params = jax.tree_util.tree_map(lambda a: a[0], params)  # strip stage dim
        outs = gpipe_loop(stage_fn, params, xm, axis_name)
        # broadcast final outputs from the last stage to all stages so the
        # result is replicated over 'pipe' (psum of one-hot contribution)
        idx = lax.axis_index(axis_name)
        contrib = jnp.where(idx == n_stage - 1, outs, jnp.zeros_like(outs))
        return lax.psum(contrib, axis_name)

    from flexflow_tpu.parallel import shard_map_compat

    dp = (data_axis if data_axis and mesh.shape.get(data_axis, 1) > 1
          else None)
    pspec = jax.tree_util.tree_map(
        lambda a: P(axis_name, *([None] * (a.ndim - 1))), stacked_params)
    xspec = P(None, dp) if dp else P()
    out = shard_map_compat(inner, mesh, (pspec, xspec), xspec)(
        stacked_params, x_mb)
    return out.reshape(b, *out.shape[2:])


def _1f1b_loop(stage_fn, loss_fn, params, x_mb, lab_mb, head_params,
               axis_name: str):
    """Per-device 1F1B body (inside shard_map). Schedule, for n stages and
    m microbatches over ticks t = 0 .. 2(n-1)+m-1:
        forward  of microbatch j at stage i: tick t = i + j
        backward of microbatch j at stage i: tick t = 2(n-1) - i + j
    Both are injective in j for fixed (i, t), so each device does at most
    one F and one B per tick; the last stage runs B(j) in the same tick as
    F(j) (the loss cotangent seeds immediately — no wait). The backward
    recomputes its stage via jax.vjp from a stashed INPUT; live in-flight
    microbatches per device never exceed 2(n-1-i), so a ring stash of
    S = min(m, 2n-1) slots is aliasing-safe: a live F(j) and live B(j')
    share a slot only if j - j' is a positive multiple of S, impossible
    with both live (j - j' < m <= S or masked)."""
    n = lax_axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = x_mb.shape[0]
    S = min(m, 2 * n - 1)
    ticks = 2 * (n - 1) + m

    from flexflow_tpu.parallel.ring_attention import pvary

    mb_shape = x_mb.shape[1:]
    buf_f0 = pvary(jnp.zeros(mb_shape, x_mb.dtype), axis_name)
    buf_b0 = pvary(jnp.zeros(mb_shape, x_mb.dtype), axis_name)
    stash0 = pvary(jnp.zeros((S,) + mb_shape, x_mb.dtype), axis_name)
    g0 = jax.tree_util.tree_map(
        lambda a: pvary(jnp.zeros_like(a), axis_name), params)
    gh0 = jax.tree_util.tree_map(
        lambda a: pvary(jnp.zeros_like(a), axis_name), head_params)
    dx0 = pvary(jnp.zeros_like(x_mb), axis_name)
    loss0 = pvary(jnp.zeros((), jnp.float32), axis_name)

    perm_f = [(i, (i + 1) % n) for i in range(n)]
    perm_b = [(i, (i - 1) % n) for i in range(n)]
    is_last = idx == n - 1

    def tick(carry, t):
        buf_f, buf_b, stash, g, gh, dx, loss = carry

        # ---- forward slot: F(idx, jf) ----
        jf = t - idx
        do_f = jnp.logical_and(jf >= 0, jf < m)
        mb_f = jnp.clip(jf, 0, m - 1)
        inp = jnp.where(idx == 0, x_mb[mb_f], buf_f)
        slot_f = mb_f % S
        stash = lax.cond(
            do_f,
            lambda s: lax.dynamic_update_index_in_dim(s, inp, slot_f, 0),
            lambda s: s, stash)
        y = stage_fn(params, inp)

        # last stage: this microbatch's loss + cotangent seed, same tick
        lab = lab_mb[mb_f]
        loss_j, (dy_j, dh_j) = jax.value_and_grad(
            lambda yy, hp: loss_fn(yy, lab, hp), argnums=(0, 1))(
                y, head_params)
        fin = jnp.logical_and(is_last, do_f)
        loss = loss + jnp.where(fin, loss_j.astype(jnp.float32), 0.0)
        # select, not multiply-by-mask: dead warm-up ticks run stage_fn on
        # zero-initialized garbage, and a loss with log/div yields NaN there;
        # 0*NaN = NaN would poison the accumulator even though the tick is
        # masked. where() drops the dead value entirely.
        gh = jax.tree_util.tree_map(
            lambda a, b: a + jnp.where(fin, b, jnp.zeros_like(b)), gh, dh_j)

        # ---- backward slot: B(idx, jb) ----
        jb = t - (2 * (n - 1) - idx)
        do_b = jnp.logical_and(jb >= 0, jb < m)
        mb_b = jnp.clip(jb, 0, m - 1)
        inp_b = stash[mb_b % S]
        cot = jnp.where(is_last, dy_j, buf_b).astype(inp_b.dtype)
        _, pull = jax.vjp(stage_fn, params, inp_b)
        dparams, dinp = pull(cot)
        g = jax.tree_util.tree_map(
            lambda a, b: a + jnp.where(do_b, b, jnp.zeros_like(b)), g, dparams)
        dx = lax.cond(
            jnp.logical_and(idx == 0, do_b),
            lambda d: lax.dynamic_update_index_in_dim(d, dinp, mb_b, 0),
            lambda d: d, dx)

        buf_f = lax.ppermute(y, axis_name, perm_f)
        buf_b = lax.ppermute(dinp, axis_name, perm_b)
        return (buf_f, buf_b, stash, g, gh, dx, loss), None

    carry0 = (buf_f0, buf_b0, stash0, g0, gh0, dx0, loss0)
    (buf_f, buf_b, stash, g, gh, dx, loss), _ = lax.scan(
        tick, carry0, jnp.arange(ticks))
    return g, gh, dx, loss


def pipeline_train_1f1b(stage_fn: Callable, loss_fn: Callable,
                        stacked_params, x, labels, mesh,
                        axis_name: str = "pipe",
                        num_microbatches: int = None,
                        head_params=None, data_axis: str = None):
    """One 1F1B-scheduled pipelined training step (fwd + bwd + grads).

    stage_fn(params_i, h) -> h' with h'.shape == h.shape
    loss_fn(y_mb, labels_mb, head_params) -> scalar mean loss for one
        microbatch (the trainable head — e.g. the LM output projection —
        lives in `head_params`, replicated over the pipe axis)
    stacked_params: pytree with leading dim = num_stages
    x: (batch, ...); labels: (batch, ...)

    Returns (loss, grads, head_grads, dx): microbatch-mean loss
    (replicated), grads with the same stage-stacked structure as
    stacked_params (sharded over `axis_name` on dim 0 — exactly the layout
    an optimizer update wants), head grads (replicated, already summed over
    microbatches — divide by num_microbatches upstream if loss_fn returns a
    per-microbatch mean), and d(loss_sum)/dx.

    Memory: O(min(m, 2n-1)) stashed microbatch inputs per device (true
    1F1B in-flight bound) — vs O(m) boundary residuals for autodiff through
    `pipeline` — at the cost of one forward recompute per backward, the
    standard TPU rematerialization trade.
    """
    from jax.sharding import PartitionSpec as P

    n_stage = mesh.shape[axis_name]
    num_micro = num_microbatches or n_stage
    b = x.shape[0]
    assert b % num_micro == 0, f"batch {b} % microbatches {num_micro}"
    x_mb = x.reshape(num_micro, b // num_micro, *x.shape[1:])
    lab_mb = labels.reshape(num_micro, b // num_micro, *labels.shape[1:])
    if head_params is None:
        head_params = {}

    dp = (data_axis if data_axis and mesh.shape.get(data_axis, 1) > 1
          else None)

    def inner(params, xm, lm, hp):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        g, gh, dx, loss = _1f1b_loop(stage_fn, loss_fn, params, xm, lm, hp,
                                     axis_name)
        # stage grads stay sharded (leading stage dim restored); loss /
        # head grads / dx live on one stage only — psum replicates them
        g = jax.tree_util.tree_map(lambda a: a[None], g)
        gh = jax.tree_util.tree_map(
            lambda a: lax.psum(a, axis_name), gh)
        dx = lax.psum(dx, axis_name)
        loss = lax.psum(loss, axis_name) / num_micro
        if dp is not None:
            # dp x pp: each slice's loss_fn already means over ITS sub-
            # microbatch, so the full-batch per-microbatch mean (and its
            # grad) is the MEAN over slices; dx stays sharded (out_spec
            # xspec) — it is d(slice loss)/d(slice inputs), scaled below
            # by the same 1/dp so the full-batch semantics match
            nd = mesh.shape[dp]
            g = jax.tree_util.tree_map(lambda a: lax.psum(a, dp) / nd, g)
            gh = jax.tree_util.tree_map(lambda a: lax.psum(a, dp) / nd, gh)
            loss = lax.psum(loss, dp) / nd
            dx = dx / nd
        return g, gh, dx, loss

    from flexflow_tpu.parallel import shard_map_compat
    pspec = jax.tree_util.tree_map(
        lambda a: P(axis_name, *([None] * (a.ndim - 1))), stacked_params)
    hspec = jax.tree_util.tree_map(lambda a: P(*([None] * a.ndim)),
                                   head_params)
    xspec = P(None, dp) if dp else P()
    g, gh, dx, loss = shard_map_compat(
        inner, mesh, (pspec, xspec, xspec, hspec),
        (pspec, hspec, xspec, P()))(stacked_params, x_mb, lab_mb,
                                    head_params)
    return (loss, g, gh,
            dx.reshape(b, *dx.shape[2:]))
