"""ParallelConfig: the per-op parallelization strategy record.

Reference: include/config.h:47-69 `ParallelConfig{device_type, nDims, dim[],
device_ids[]}`; data-parallel seeding src/runtime/model.cc:483-494.

TPU re-design: the strategy must be expressible as a GSPMD sharding over one
`jax.sharding.Mesh`, so alongside the reference's per-dim partition degrees we
carry an explicit `axis_map`: mesh-axis-name -> logical tensor dim (or None for
"replicated over that axis"). Degrees are derivable from the axis_map + mesh;
they are kept so the reference text schema round-trips
(src/runtime/strategy.cc:95-189) and so the C++ simulator can reason about
degrees without a mesh object.

Dim order: we store degrees in LOGICAL order (dim 0 = sample/batch). The
reference stores them reversed (Legion domain order, sample last —
model.cc:489-491); file IO reverses accordingly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

# axis_map value meaning "shard the op's CONTRACTION dim over this mesh axis"
# (row-parallel / Megatron-style tensor parallelism): the weight is sharded on
# its input-feature dim, the input arrives sharded on its last dim (matching a
# column-parallel producer), and the output is replicated over the axis after
# an activation psum that GSPMD inserts automatically. The reference expresses
# the same concept as Linear's NDIM+1 replica dimension
# (linear.cu:171-192,774-835: replicated input + backward2 reduction).
CONTRACT = -2

# axis_map value meaning "run this op PIPELINED over this mesh axis": the op's
# layer/stage dim (a weight dim, not an output dim — only ops exposing
# pipeline_stages() accept it) shards over the axis and microbatches ripple
# through a ppermute ring (parallel/pipeline.py). Like CONTRACT, the output is
# delivered replicated over the axis, so it never appears in output
# PartitionSpecs. The reference's only pipelining was the hand-scheduled NMT
# per-(layer,timestep) device tables (nmt/rnn.h:21-63); here PP is a
# first-class strategy-search axis.
STAGE = -3

# axis_map value meaning "shard this op's EXPERTS over this mesh axis"
# (MoE expert parallelism): the expert-indexed weights (w_in/w_out) shard
# on their expert dim, tokens all-to-all to their experts and back, and
# the output is delivered replicated over the axis — like CONTRACT/STAGE
# it never appears in output PartitionSpecs. Only ops exposing
# expert_parallel_size() accept it; before ISSUE 19 expert parallelism
# existed solely as the literal 'expert' mesh-axis convention, invisible
# to legal_axis_maps and hence to the search.
EXPERT = -4


@dataclasses.dataclass
class ParallelConfig:
    device_type: str = "TPU"  # serialized as the reference's GPU enum value
    dims: Tuple[int, ...] = ()  # partition degree per logical output dim
    device_ids: Tuple[int, ...] = ()
    # mesh-axis name -> logical tensor dim it partitions (None = unused/replicated)
    axis_map: Optional[Dict[str, Optional[int]]] = None
    # per-op memory-relief mode the multi-objective search chose
    # (cost_model.MEM_MODES: none | remat | zero1 | zero3 | offload);
    # "none" for strategies from files/earlier searches — field default
    # keeps old pickles/records loading unchanged
    mem_mode: str = "none"

    # ---- constructors -----------------------------------------------------

    @staticmethod
    def data_parallel(ndims: int, num_parts: int) -> "ParallelConfig":
        """Reference: Op::get_data_parallel_config model.cc:483-494."""
        dims = tuple(num_parts if i == 0 else 1 for i in range(ndims))
        return ParallelConfig(
            dims=dims,
            device_ids=tuple(range(num_parts)),
            axis_map={"data": 0} if num_parts > 1 else {"data": None},
        )

    @staticmethod
    def replicated(ndims: int) -> "ParallelConfig":
        return ParallelConfig(dims=(1,) * ndims, device_ids=(0,), axis_map={})

    @staticmethod
    def host(ndims: int) -> "ParallelConfig":
        """Host (CPU) placement: the op runs replicated on the host CPU
        backend via a PlacementExecutor group — the reference's
        heterogeneous strategy (CPU embeddings with AVX2 kernels,
        src/ops/embedding_avx2.cc:5-30 + DLRM
        examples/cpp/DLRM/dlrm_strategy_hetero.cc). Degree 1: like the
        reference's per-node CPU embedding, host ops do not shard."""
        return ParallelConfig(dims=(1,) * ndims, device_ids=(0,),
                              axis_map={}, device_type="CPU")

    @staticmethod
    def from_axis_map(ndims: int, mesh_shape: Dict[str, int],
                      axis_map: Dict[str, Optional[int]]) -> "ParallelConfig":
        dims = [1] * ndims
        contract_deg = 1
        stage_deg = 1
        expert_deg = 1
        for ax, d in axis_map.items():
            if d == CONTRACT:
                contract_deg *= mesh_shape[ax]
            elif d == STAGE:
                # stage degree shards a WEIGHT dim, not an output dim — it
                # lives only in the axis_map (degree lists follow the
                # reference file schema, which has no PP concept), but the
                # op still OCCUPIES the stage devices
                stage_deg *= mesh_shape[ax]
            elif d == EXPERT:
                # like STAGE: shards the expert (weight) dim, not an output
                # dim — lives only in the axis_map, but occupies the devices
                expert_deg *= mesh_shape[ax]
            elif d is not None:
                dims[d] *= mesh_shape[ax]
        if contract_deg > 1:
            # serialized as an extra trailing degree — the reference's own
            # convention for Linear's replica dim (an NDIM+1 tensor,
            # linear.cu:171-192)
            dims.append(contract_deg)
        n = 1
        for v in dims:
            n *= v
        # device_ids covers every device the op runs on, INCLUDING pipeline
        # stages (matching csim.native_optimize's ndev and what
        # placement.op_block requires the block to hold); num_parts() stays
        # the schema's degree product, so for STAGE strategies
        # len(device_ids) is a stage-size multiple of num_parts()
        return ParallelConfig(dims=tuple(dims),
                              device_ids=tuple(range(n * stage_deg
                                                     * expert_deg)),
                              axis_map=dict(axis_map))

    # ---- queries ----------------------------------------------------------

    @property
    def nDims(self) -> int:
        return len(self.dims)

    def num_parts(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def degree(self, dim: int) -> int:
        return self.dims[dim]

    def is_data_parallel_only(self) -> bool:
        return all(d == 1 for d in self.dims[1:])

    def to_partition_spec(self, ndims: Optional[int] = None,
                          mesh_axis_order: Optional[List[str]] = None):
        """Lower to a jax PartitionSpec. Requires axis_map (set by the
        strategy layer when it validates degrees against the mesh)."""
        from jax.sharding import PartitionSpec as P

        ndims = ndims if ndims is not None else self.nDims
        if not self.axis_map:
            return P(*([None] * ndims))
        dim_axes: List[List[str]] = [[] for _ in range(ndims)]
        order = mesh_axis_order or list(self.axis_map.keys())
        for ax in order:
            d = self.axis_map.get(ax)
            # CONTRACT axes do not shard the output (it is replicated over
            # them after the psum) — only true output dims land in the spec
            if d is not None and 0 <= d < ndims:
                dim_axes[d].append(ax)
        entries = []
        for axes in dim_axes:
            if not axes:
                entries.append(None)
            elif len(axes) == 1:
                entries.append(axes[0])
            else:
                entries.append(tuple(axes))
        return P(*entries)

    def __hash__(self):
        am = tuple(sorted((k, v if v is not None else -1)
                          for k, v in (self.axis_map or {}).items()))
        return hash((self.dims, am))
