"""Device mesh construction.

Replaces the reference's machine discovery in the mapper
(src/mapper/mapper.cc:55-144: GPUs/CPUs/memories per node) with
`jax.sharding.Mesh` construction. Axis vocabulary used across the framework:

  data   — batch/sample parallelism (reference SOAP 'S')
  model  — parameter/tensor parallelism (reference SOAP 'P'; linear.cu out-channel)
  seq    — sequence/context parallelism (net-new vs reference, SURVEY §5.7)
  pipe   — pipeline stages (reference: nmt/ hand-rolled pipeline)
  expert — MoE expert parallelism (net-new)

Axes of size 1 are always legal, so a single mesh covers every strategy the
search proposes (GSPMD constraint; SURVEY §7 hard part 2).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

AXIS_ORDER = ("pipe", "data", "expert", "seq", "model")


def make_mesh(shape: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from {axis: size}. Axes ordered canonically so ICI-neighbor
    axes ('model', 'seq') are innermost (fastest-varying => nearest devices)."""
    axes = [a for a in AXIS_ORDER if a in shape and shape[a] > 0]
    extra = [a for a in shape if a not in AXIS_ORDER]
    axes += sorted(extra)
    sizes = [shape[a] for a in axes]
    n = int(np.prod(sizes)) if sizes else 1
    if devices is None:
        devices = jax.devices()[:n]
    if len(devices) < n:
        raise ValueError(f"mesh {shape} needs {n} devices, have {len(devices)}")
    dev_array = np.asarray(devices[:n]).reshape(sizes if sizes else (1,))
    return Mesh(dev_array, axis_names=tuple(axes) if axes else ("data",))


def default_mesh(num_devices: Optional[int] = None) -> Mesh:
    n = num_devices if num_devices is not None else len(jax.devices())
    return make_mesh({"data": n})


def mesh_shape_dict(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
