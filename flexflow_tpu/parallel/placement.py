"""Operator placement: run different ops on disjoint device sub-meshes.

The SOAP "O" axis (reference: per-op device_ids in ParallelConfig,
include/config.h:47-69; FFMapper::slice_task placing each index point on the
op's own device list, src/mapper/mapper.cc:346-424; MCMC proposing random
contiguous device ranges, src/runtime/model.cc:496-525).

TPU re-design: GSPMD wants one device set per compiled program, so a strategy
that places op groups on disjoint contiguous device blocks is lowered as a
sequence of per-group jitted programs, each compiled over its own
`jax.sharding.Mesh` slice. JAX dispatches computations asynchronously, so
groups on disjoint blocks genuinely overlap in wall-clock (the property the
per-device simulator ranks, search/csrc/sim.cc). Boundary tensors move
between blocks with `jax.device_put` (ICI transfers).

Training runs as: forward group-by-group -> loss on the final group's block
-> backward group-by-group in reverse via per-group jitted VJPs (the group
forward is rematerialized inside the backward jit — jax.checkpoint spirit) ->
per-group optimizer updates. Gradient parity with the single-mesh executor is
tested in tests/test_placement.py.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flexflow_tpu.ffconst import LossType, MetricsType
from flexflow_tpu.ops.base import InputOp, Op
from flexflow_tpu.parallel.pconfig import ParallelConfig
from flexflow_tpu.runtime.loss import compute_loss
from flexflow_tpu.runtime.metrics import batch_metrics


class PlacementGroup:
    """Ops sharing one device block, packed dependency-safely (an op may
    join any group at or after all its producers' groups — branchy graphs
    interleave ops from parallel branches in insertion order, and strictly
    consecutive runs would fragment them into many tiny programs)."""

    def __init__(self, index: int, place: int, ndev: int, mesh: Mesh,
                 devtype: str = "TPU"):
        self.index = index
        self.place = place
        self.ndev = ndev
        self.mesh = mesh
        self.devtype = devtype  # "TPU" (accelerator pool) | "CPU" (host)
        self.ops: List[Op] = []

    def __repr__(self):
        return (f"PlacementGroup({self.index}: {self.devtype} devices "
                f"[{self.place},{self.place + self.ndev}), "
                f"ops={[o.name for o in self.ops]})")


def op_block(pc: Optional[ParallelConfig], axis_map, mesh_shape,
             num_devices: int) -> Tuple[int, int]:
    """(place, ndev) for an op: the contiguous aligned device block its
    strategy assigns. Alignment delegates to the simulator's rule
    (cost_model.align_place — the C++ sim.cc mirror) so the executed block
    always matches the block the search ranked."""
    from flexflow_tpu.search.cost_model import align_place

    parts = 1
    for ax, d in (axis_map or {}).items():
        if d is not None:
            parts *= mesh_shape[ax]
    parts = max(1, min(parts, num_devices))
    ndev = parts
    place = 0
    if pc is not None and pc.device_ids:
        place = min(pc.device_ids)
        n = len(pc.device_ids)
        if n < parts:
            raise ValueError(
                f"strategy places a {parts}-way sharded op on only {n} "
                f"devices ({tuple(pc.device_ids)[:4]}...) — the device block "
                f"must hold the sharding; fix the strategy entry")
        if 1 <= n <= num_devices and num_devices % n == 0:
            ndev = n
    if ndev >= num_devices or num_devices % ndev != 0:
        return 0, num_devices
    return align_place(place, ndev, num_devices), ndev


def has_placement(strategies: Dict[str, ParallelConfig],
                  num_devices: int) -> bool:
    """True when some op is EXPLICITLY placed off block 0. device_ids
    defaulting to range(num_parts) (what from_axis_map emits) is not a
    placement — plain GSPMD strategies with mixed degrees must keep running
    as one full-mesh program. Any genuine multi-block placement necessarily
    has an op whose block starts at a non-zero device."""
    for pc in strategies.values():
        if getattr(pc, "device_type", "TPU") == "CPU":
            # host-placed op (reference hetero DLRM: embeddings on CPU,
            # embedding_avx2.cc) — always needs the per-group executor
            return True
        ids = getattr(pc, "device_ids", ())
        if (ids and min(ids) > 0 and 0 < len(ids) < num_devices
                and num_devices % len(ids) == 0):
            return True
    return False


class PlacementExecutor:
    """Executes the graph as a sequence of per-group sub-mesh programs.

    Reuses GraphExecutor's strategy resolution (axis maps) but compiles one
    program per placement group instead of one whole-step program.
    """

    jits_per_group = True  # callers must not wrap our fns in an outer jit

    def __init__(self, model):
        from flexflow_tpu.parallel.mesh import mesh_shape_dict
        from flexflow_tpu.runtime.executor import GraphExecutor

        # tie_weights composes with placement: same-block ties resolve
        # in-program, cross-block ties broadcast the source weight to the
        # dest block and route the gradient home (see _group_tie_srcs)
        if getattr(model.config, "fsdp_axis", ""):
            raise NotImplementedError(
                "fsdp_axis + operator placement is unsupported: FSDP "
                "shards weights over the full mesh axis, but placement "
                "groups own disjoint device blocks; drop one of the two")
        self.model = model
        self.base = GraphExecutor(model)  # strategy resolution + helpers
        self.full_mesh: Mesh = model.mesh
        self.mesh_shape = mesh_shape_dict(self.full_mesh)
        self.devices = list(np.asarray(self.full_mesh.devices).reshape(-1))
        self.num_devices = len(self.devices)
        self.groups: List[PlacementGroup] = []
        self._op_group: Dict[str, PlacementGroup] = {}
        self._build_groups()
        # ties compose across groups: the dst group's program takes the
        # source weight as an extra input and its gradient contribution is
        # summed with the source group's own. Same device BLOCK: the weight
        # already lives on those devices. DIFFERENT blocks (r5, VERDICT r4
        # #5): the source weight is device_put into the dst block for the
        # dst program (one ICI broadcast per step) and the dst's gradient
        # contribution is device_put back to the source block before the
        # sum — storage and the optimizer state stay with the source.
        self._group_tie_srcs: Dict[int, Dict[str, set]] = {}
        for (dst_op, dst_w), (src_op, src_w, _) in \
                (getattr(model, "_tied", None) or {}).items():
            gd = self._op_group.get(dst_op)
            gs = self._op_group.get(src_op)
            if gd is None or gs is None:
                continue
            if gd is not gs:
                self._group_tie_srcs.setdefault(
                    gd.index, {}).setdefault(src_op, set()).add(src_w)
        # strategy table shared with the single-mesh executor (profiler &
        # tests read executor._op_axis_maps)
        self._op_axis_maps = self.base._op_axis_maps

    # ---- grouping -----------------------------------------------------------

    def _submesh(self, place: int, ndev: int, axis_map) -> Mesh:
        """Mesh over devices [place, place+ndev) carrying the axes the
        group's ops actually shard over (sized from the full mesh), with a
        trailing fill axis when the used axes don't cover the block."""
        used = {}
        for ax, d in (axis_map or {}).items():
            if d is not None:
                used[ax] = self.mesh_shape[ax]
        covered = 1
        for v in used.values():
            covered *= v
        names = list(used.keys())
        shape = list(used.values())
        if covered < ndev or not names:
            names.append("_fill")
            shape.append(max(ndev // covered, 1))
        devs = np.asarray(self.devices[place:place + ndev]).reshape(shape)
        return Mesh(devs, tuple(names))

    def _build_groups(self):
        """Greedy dependency-safe packing: each op joins the earliest
        existing group that (a) sits on the same device block, (b) has index
        >= every producer's group (groups dispatch in index order), and
        (c) can still host the op's mesh axes (e.g. a 'data'-sharded op and
        a 'model'-sharded op both 2-way on a 2-device block need separate
        programs). Parallel branches on the same block therefore share one
        program; branches on disjoint blocks become separate groups that
        overlap via async dispatch."""
        strategies = self.model.config.strategies

        def coverage(axes: Dict[str, Optional[int]]) -> int:
            n = 1
            for ax, d in axes.items():
                if d is not None:
                    n *= self.mesh_shape[ax]
            return n

        group_axes: List[Dict[str, int]] = []  # merged used-axes per group
        for op in self.model.ops:
            if isinstance(op, InputOp):
                continue
            am = self.base._op_axis_maps.get(op.name, {})
            pc = strategies.get(op.name)
            devtype = getattr(pc, "device_type", "TPU") if pc else "TPU"
            if devtype == "CPU":
                # host placement (reference embedding_avx2.cc /
                # dlrm_strategy_hetero.cc): the op runs replicated on the
                # host CPU backend — one device per process, like the
                # reference's per-node CPU embedding
                op_axes = {ax: d for ax, d in am.items() if d is not None}
                if op_axes:
                    raise NotImplementedError(
                        f"op {op.name!r}: device_type CPU with a sharded "
                        f"axis_map {op_axes} — host-placed ops run "
                        f"replicated on the host backend; drop the "
                        f"sharding or place the op back on the "
                        f"accelerator pool")
                place, ndev = 0, 1
            else:
                place, ndev = op_block(pc, am, self.mesh_shape,
                                       self.num_devices)
                op_axes = {ax: d for ax, d in am.items() if d is not None}
            g_min = 0
            for t in op.inputs:
                if t.owner_op is not None \
                        and not isinstance(t.owner_op, InputOp):
                    pg = self._op_group.get(t.owner_op.name)
                    if pg is not None:
                        g_min = max(g_min, pg.index)
            target = None
            for gi in range(g_min, len(self.groups)):
                g = self.groups[gi]
                if g.place != place or g.ndev != ndev \
                        or g.devtype != devtype:
                    continue
                cand = dict(group_axes[gi])
                cand.update(op_axes)
                if coverage(cand) <= ndev:
                    target = g
                    group_axes[gi] = cand
                    break
            if target is None:
                target = PlacementGroup(len(self.groups), place, ndev,
                                        None, devtype)
                self.groups.append(target)
                group_axes.append(dict(op_axes))
            target.ops.append(op)
            self._op_group[op.name] = target
        # build each group's mesh to cover all axes its member ops use
        for g, axes in zip(self.groups, group_axes):
            if g.devtype == "CPU":
                host = jax.local_devices(backend="cpu")[:1]
                g.mesh = Mesh(np.asarray(host).reshape(1), ("_host",))
            else:
                g.mesh = self._submesh(g.place, g.ndev, axes)

    # ---- per-group forward --------------------------------------------------

    def _group_sharding(self, g: PlacementGroup, op: Op) -> NamedSharding:
        am = {ax: d for ax, d in self.base._op_axis_maps.get(op.name, {})
              .items() if ax in g.mesh.shape}
        pspec = ParallelConfig(axis_map=am).to_partition_spec(
            op.outputs[0].num_dims, list(g.mesh.axis_names))
        return NamedSharding(g.mesh, pspec)

    def _group_forward_fn(self, g: PlacementGroup, training: bool,
                          exports: frozenset):
        """Pure fn: (params_g, state_g, inputs_dict, rng) ->
        (outputs_dict, new_state_g). inputs_dict keys are tensor names;
        `exports` (captured by value) names the tensors to return."""
        bf16 = self.model.config.compute_dtype == "bfloat16"

        def to_compute(a):
            return a.astype(jnp.bfloat16) \
                if (bf16 and a.dtype == jnp.float32) else a

        op_indices = {op.name: i for i, op in enumerate(self.model.ops)}
        from flexflow_tpu.runtime.executor import resolve_tied_params

        def fn(params_g, state_g, inputs, rng):
            vals: Dict[str, jnp.ndarray] = {k: to_compute(v)
                                            for k, v in inputs.items()}
            new_state: Dict[str, Dict] = {}
            for op in g.ops:
                xs = [vals[t.name] for t in op.inputs]
                op_rng = None
                if op.needs_rng and rng is not None:
                    op_rng = jax.random.fold_in(rng, op_indices[op.name])
                    seed = getattr(op, "seed", 0)
                    if seed:
                        op_rng = jax.random.fold_in(op_rng, seed)
                p = resolve_tied_params(self.model, params_g, op.name,
                                        params_g.get(op.name, {}))
                if bf16:
                    p = {k: to_compute(v) for k, v in p.items()}
                kwargs = {}
                if getattr(op, "wants_shard_ctx", False):
                    kwargs["shard_ctx"] = {
                        "mesh": g.mesh,
                        "axis_map": {ax: d for ax, d in
                                     self.base._op_axis_maps
                                     .get(op.name, {}).items()
                                     if ax in g.mesh.shape},
                        "sp_mode": getattr(self.model.config, "sp_mode",
                                           "ring"),
                    }
                # op-name HLO metadata for trace attribution (see
                # GraphExecutor.apply_graph)
                with jax.named_scope(op.name):
                    if op.stateful:
                        outs, ns = op.forward_stateful(
                            p, state_g.get(op.name, {}), xs,
                            training=training, rng=op_rng)
                        new_state[op.name] = ns
                    else:
                        outs = op.forward(p, xs, training=training,
                                          rng=op_rng, **kwargs)
                sharding = self._group_sharding(g, op)
                for i, t in enumerate(op.outputs):
                    v = outs[i]
                    if v.ndim == t.num_dims and len(sharding.spec) <= v.ndim:
                        v = jax.lax.with_sharding_constraint(v, sharding)
                    vals[t.name] = v
            # exported values: tensors consumed outside the group or final
            outputs = {}
            for op in g.ops:
                for t in op.outputs:
                    if t.name in exports:
                        outputs[t.name] = vals[t.name]
            for k, v in state_g.items():
                if k not in new_state:
                    new_state[k] = v
            return outputs, new_state

        return fn

    def _compute_exports(self, final_tensors) -> List[frozenset]:
        """Which tensor names each group must hand to later groups."""
        exports: List[set] = [set() for _ in self.groups]
        keep = {t.name for t in final_tensors}
        for op in self.model.ops:
            if isinstance(op, InputOp):
                continue
            g = self._op_group[op.name]
            for t in op.inputs:
                if t.owner_op is None or isinstance(t.owner_op, InputOp):
                    continue
                pg = self._op_group[t.owner_op.name]
                if pg.index != g.index:
                    exports[pg.index].add(t.name)
        for g in self.groups:
            for op in g.ops:
                for t in op.outputs:
                    if t.name in keep:
                        exports[g.index].add(t.name)
        return [frozenset(s) for s in exports]

    # ---- parameter init -----------------------------------------------------

    def param_shardings(self):
        out = {}
        for op in self.model.ops:
            specs = op.weight_specs()
            if not specs:
                continue
            g = self._op_group[op.name]
            am = {ax: d for ax, d in self.base._op_axis_maps
                  .get(op.name, {}).items() if ax in g.mesh.shape}
            wp = op.weight_partition(am)
            out[op.name] = {name: NamedSharding(g.mesh, ps)
                            for name, ps in wp.items()}
        return out

    def reshard_params(self, host_tree):
        """Checkpoint-restore placement: each op's weights land on its own
        group's sub-mesh (see executor.reshard_tree)."""
        from flexflow_tpu.runtime.executor import reshard_tree

        return reshard_tree(host_tree, self.param_shardings())

    def init_params(self, rng_key):
        from flexflow_tpu.runtime.executor import _stable_hash
        from flexflow_tpu.runtime.initializer import init_weight
        from flexflow_tpu.ffconst import dtype_to_np

        shardings = self.param_shardings()
        params = {}
        for op in self.model.ops:
            specs = op.weight_specs()
            if not specs:
                continue
            op_params = {}
            tied = getattr(self.model, "_tied", {})
            for i, spec in enumerate(specs):
                if (op.name, spec.name) in tied:
                    continue  # storage lives with the tie source
                key = jax.random.fold_in(
                    jax.random.fold_in(rng_key, _stable_hash(op.name)), i)
                sharding = shardings[op.name].get(spec.name)
                init_fn = functools.partial(init_weight, spec)
                dtype = dtype_to_np(spec.dtype)
                op_params[spec.name] = jax.jit(
                    lambda k, f=init_fn, d=dtype: f(k, dtype=d),
                    out_shardings=sharding)(key)
            params[op.name] = op_params
        return params

    def init_state(self):
        state = {}
        for op in self.model.ops:
            if op.stateful:
                g = self._op_group[op.name]
                s = op.init_state()
                sh = NamedSharding(g.mesh, P())
                state[op.name] = {k: jax.device_put(jnp.asarray(v), sh)
                                  for k, v in s.items()}
        return state

    # ---- data movement ------------------------------------------------------

    def _put(self, value, g: PlacementGroup, spec=None):
        sh = NamedSharding(g.mesh, spec if spec is not None
                           else P(*([None] * jnp.ndim(value))))
        return jax.device_put(value, sh)

    def _group_inputs(self, g: PlacementGroup, vals: Dict[str, Any],
                      batch: Dict[str, Any]) -> Dict[str, Any]:
        """Collect + transfer the tensors group g consumes from outside."""
        ins = {}
        for op in g.ops:
            for t in op.inputs:
                if t.name in ins:
                    continue
                if t.owner_op is None or isinstance(t.owner_op, InputOp):
                    src = batch[t.owner_op.name] if t.owner_op is not None \
                        else batch[t.name]
                    entries = [None] * jnp.ndim(src)
                    if "data" in g.mesh.shape and g.mesh.shape["data"] > 1:
                        entries[0] = "data"
                    ins[t.name] = self._put(src, g, P(*entries))
                elif self._op_group[t.owner_op.name].index != g.index:
                    ins[t.name] = self._put(vals[t.name], g)
        return ins

    def _same_block(self, a: PlacementGroup, b: PlacementGroup) -> bool:
        return (a.place, a.ndev, a.devtype) == (b.place, b.ndev, b.devtype)

    def _group_params(self, g: PlacementGroup, params):
        """The param slice group g's program sees: its member ops' params
        plus, for ties whose dest lives here but source elsewhere, the
        source weights the tie resolves from — device_put onto THIS block
        (replicated) when the source lives on a different one."""
        p_g = {op.name: params[op.name] for op in g.ops
               if op.name in params}
        for src_op, names in self._group_tie_srcs.get(g.index, {}).items():
            if src_op not in params:
                continue
            gs = self._op_group[src_op]
            cross = not self._same_block(gs, g)
            p_g[src_op] = {
                w: (self._put(params[src_op][w], g) if cross
                    else params[src_op][w])
                for w in names if w in params[src_op]}
        return p_g

    # ---- compiled steps -----------------------------------------------------

    def shard_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        # group inputs are device_put to their consumer blocks inside the
        # step; here just materialize on device
        return {k: jnp.asarray(v) for k, v in batch.items()}

    def make_forward(self, final_tensors=None, training: bool = False):
        finals = final_tensors or [self.model.ops[-1].outputs[0]]
        exports = self._compute_exports(finals)
        fwd_jits = [jax.jit(self._group_forward_fn(g, training, exports[i]))
                    for i, g in enumerate(self.groups)]

        def fwd(params, state, batch, rng=None):
            vals: Dict[str, Any] = {}
            for g, f in zip(self.groups, fwd_jits):
                ins = self._group_inputs(g, vals, batch)
                p_g = self._group_params(g, params)
                s_g = {op.name: state[op.name] for op in g.ops
                       if op.name in state}
                outs, _ = f(p_g, s_g, ins, rng)
                vals.update(outs)
            return [vals[t.name] for t in finals]

        return fwd

    def make_eval_step(self, loss_type: LossType,
                       metric_types: List[MetricsType], final_tensor,
                       label_key="label"):
        fwd = self.make_forward([final_tensor], training=False)
        final_group = self._op_group[final_tensor.owner_op.name]

        def loss_mets(logits, labels):
            loss = compute_loss(loss_type, logits, labels)
            mets = batch_metrics(
                loss_type, metric_types, logits, labels,
                ignore_index=getattr(self.model.config,
                                     "metrics_ignore_index", None))
            return loss, mets

        loss_jit = jax.jit(loss_mets)

        def step(params, state, batch):
            logits = fwd(params, state, batch)[0]
            labels = self._put(batch[label_key], final_group)
            loss, mets = loss_jit(logits, labels)
            return loss, mets, logits

        return step

    def make_train_step(self, optimizer, loss_type: LossType,
                        metric_types: List[MetricsType], final_tensor,
                        label_key="label"):
        aux_tensors = list(getattr(self.model, "_aux_tensors", ()))
        exports = self._compute_exports([final_tensor] + aux_tensors)
        final_group = self._op_group[final_tensor.owner_op.name]

        fwd_fns = [self._group_forward_fn(g, True, exports[i])
                   for i, g in enumerate(self.groups)]
        fwd_jits = [jax.jit(f) for f in fwd_fns]

        # per-group backward: rematerialize the forward inside jax.vjp
        def make_bwd(gi):
            def bwd(params_g, state_g, ins, rng, cots):
                def f(p, i):
                    outs, _ = fwd_fns[gi](p, state_g, i, rng)
                    return outs
                _, vjp = jax.vjp(f, params_g, ins)
                return vjp(cots)
            return jax.jit(bwd)

        bwd_jits = [make_bwd(i) for i in range(len(self.groups))]

        def loss_and_grad_logits(logits, labels, aux_vals):
            def f(lg):
                loss = compute_loss(loss_type, lg, labels)
                for a in aux_vals:
                    loss = loss + a
                return loss
            loss, dlogits = jax.value_and_grad(f)(logits)
            mets = batch_metrics(
                loss_type, metric_types, logits, labels,
                ignore_index=getattr(self.model.config,
                                     "metrics_ignore_index", None))
            return loss, dlogits, mets

        loss_jit = jax.jit(loss_and_grad_logits)

        # tensor name -> producer group (None for graph inputs)
        tensor_group: Dict[str, Optional[PlacementGroup]] = {}
        for op in self.model.ops:
            for t in op.outputs:
                tensor_group[t.name] = None if isinstance(op, InputOp) \
                    else self._op_group[op.name]

        def step(params, opt_state, state, batch, rng):
            # ---- forward ----
            vals: Dict[str, Any] = {}
            group_ins = []
            group_ps = []  # reused by the backward loop: a cross-block
            # tied source is device_put to the dest block ONCE per step
            new_state: Dict[str, Dict] = {}
            for g, f in zip(self.groups, fwd_jits):
                ins = self._group_inputs(g, vals, batch)
                group_ins.append(ins)
                p_g = self._group_params(g, params)
                group_ps.append(p_g)
                s_g = {op.name: state[op.name] for op in g.ops
                       if op.name in state}
                outs, ns = f(p_g, s_g, ins, rng)
                vals.update(outs)
                new_state.update(ns)
            # ---- loss on the final group's block ----
            labels = self._put(batch[label_key], final_group)
            aux_vals = [self._put(vals[t.name], final_group)
                        for t in aux_tensors]
            loss, dlogits, mets = loss_jit(vals[final_tensor.name], labels,
                                           aux_vals)
            # ---- backward, groups in reverse; cotangents accumulate on the
            # producer group's block ----
            cots: Dict[str, Any] = {final_tensor.name: dlogits}
            for t in aux_tensors:
                # d(loss)/d(aux) = 1 (aux losses are added to the loss)
                cots[t.name] = self._put(jnp.ones(()), tensor_group[t.name])
            grads: Dict[str, Dict] = {}
            for gi in range(len(self.groups) - 1, -1, -1):
                g = self.groups[gi]
                p_g = group_ps[gi]
                s_g = {op.name: state[op.name] for op in g.ops
                       if op.name in state}
                g_cots = {}
                for name in sorted(exports[gi]):
                    if name in cots:
                        g_cots[name] = self._put(cots[name], g)
                    else:  # exported but unused downstream of the loss
                        ref = vals[name]
                        g_cots[name] = self._put(
                            jnp.zeros(ref.shape, ref.dtype), g)
                dp, dins = bwd_jits[gi](p_g, s_g, group_ins[gi], rng, g_cots)
                for op_name, ws in dp.items():
                    # tie-source grads computed on a DIFFERENT block than
                    # the weight's owner (cross-block tie) move home
                    # before accumulating, so the sum — and the optimizer
                    # state it feeds — lives with the source weight
                    owner = self._op_group[op_name]
                    if not self._same_block(owner, g):
                        ws = {w: self._put(gv, owner) for w, gv in ws.items()}
                    if op_name not in grads:
                        grads[op_name] = dict(ws)
                        continue
                    # tie source: this group's contribution sums with the
                    # source group's own gradients
                    acc = grads[op_name]
                    for w_name, gv in ws.items():
                        acc[w_name] = (acc[w_name] + gv
                                       if w_name in acc else gv)
                for name, ct in dins.items():
                    pg = tensor_group.get(name)
                    if pg is None:
                        continue  # graph input: no gradient needed
                    ct = self._put(ct, pg)
                    cots[name] = cots[name] + ct if name in cots else ct
            # ---- optimizer update (per-op states live on their blocks) ----
            new_params, new_opt_state = optimizer.update(params, grads,
                                                         opt_state)
            return new_params, new_opt_state, new_state, loss, mets

        return step
