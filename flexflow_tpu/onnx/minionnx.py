"""Minimal self-contained ONNX protobuf codec.

The `onnx` pip package is not bundled in this environment, but the ONNX wire
format is plain protobuf with a small, frozen schema (the field numbers below
are fixed by the public onnx.proto3 spec). This module implements just enough
of it — ModelProto / GraphProto / NodeProto / AttributeProto / TensorProto /
ValueInfoProto — to (a) parse real .onnx files produced elsewhere and
(b) construct + serialize models offline, so the ONNX frontend
(flexflow_tpu/onnx/model.py, reference python/flexflow/onnx/model.py) and its
examples run without the package. Objects are duck-type compatible with the
subset of the onnx package API the importer uses (`model.graph.node`,
`node.attribute`, `tensor.dims`, ...), plus `helper`-style constructors
(make_node / make_tensor / make_graph / make_model) and numpy conversion
(to_array / from_array).

No code here derives from the onnx project; it is a from-scratch protobuf
reader/writer for the documented message layout.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

# ---- AttributeProto.AttributeType / TensorProto.DataType enums (spec) ------
FLOAT, INT, STRING, TENSOR, FLOATS, INTS, STRINGS = 1, 2, 3, 4, 6, 7, 8
DT_FLOAT, DT_INT32, DT_INT64 = 1, 6, 7

_NP_TO_DT = {np.dtype(np.float32): DT_FLOAT, np.dtype(np.int32): DT_INT32,
             np.dtype(np.int64): DT_INT64}
_DT_TO_NP = {v: k for k, v in _NP_TO_DT.items()}


# ---- message objects --------------------------------------------------------

@dataclasses.dataclass
class AttributeProto:
    name: str = ""
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional["TensorProto"] = None
    floats: List[float] = dataclasses.field(default_factory=list)
    ints: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class TensorProto:
    name: str = ""
    dims: List[int] = dataclasses.field(default_factory=list)
    data_type: int = DT_FLOAT
    raw_data: bytes = b""
    float_data: List[float] = dataclasses.field(default_factory=list)
    int32_data: List[int] = dataclasses.field(default_factory=list)
    int64_data: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _TensorTypeProto:
    elem_type: int = DT_FLOAT
    shape_dims: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ValueInfoProto:
    name: str = ""
    type: _TensorTypeProto = dataclasses.field(default_factory=_TensorTypeProto)


@dataclasses.dataclass
class NodeProto:
    op_type: str = ""
    name: str = ""
    input: List[str] = dataclasses.field(default_factory=list)
    output: List[str] = dataclasses.field(default_factory=list)
    attribute: List[AttributeProto] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class GraphProto:
    name: str = ""
    node: List[NodeProto] = dataclasses.field(default_factory=list)
    initializer: List[TensorProto] = dataclasses.field(default_factory=list)
    input: List[ValueInfoProto] = dataclasses.field(default_factory=list)
    output: List[ValueInfoProto] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ModelProto:
    ir_version: int = 8
    producer_name: str = "flexflow_tpu.minionnx"
    opset_version: int = 13
    graph: GraphProto = dataclasses.field(default_factory=GraphProto)


# ---- protobuf wire primitives ----------------------------------------------

def _w_varint(out: bytearray, v: int) -> None:
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _w_tag(out: bytearray, field: int, wire: int) -> None:
    _w_varint(out, (field << 3) | wire)


def _w_len(out: bytearray, field: int, payload: bytes) -> None:
    _w_tag(out, field, 2)
    _w_varint(out, len(payload))
    out.extend(payload)


def _w_str(out: bytearray, field: int, s) -> None:
    _w_len(out, field, s if isinstance(s, bytes) else s.encode())


def _w_int(out: bytearray, field: int, v: int) -> None:
    _w_tag(out, field, 0)
    _w_varint(out, v)


def _w_f32(out: bytearray, field: int, v: float) -> None:
    _w_tag(out, field, 5)
    out.extend(struct.pack("<f", v))


def _r_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _scan(buf: bytes) -> Dict[int, List[Tuple[int, object]]]:
    """Parse one message's fields into {field_num: [(wire, value), ...]}."""
    fields: Dict[int, List[Tuple[int, object]]] = {}
    pos = 0
    while pos < len(buf):
        tag, pos = _r_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, pos = _r_varint(buf, pos)
        elif wire == 2:
            n, pos = _r_varint(buf, pos)
            v = buf[pos:pos + n]
            pos += n
        elif wire == 5:
            v = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        elif wire == 1:
            v = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append((wire, v))
    return fields


def _ints_of(fields, num) -> List[int]:
    """A repeated int64 field: packed (one length-delimited blob) or not."""
    out: List[int] = []
    for wire, v in fields.get(num, []):
        if wire == 0:
            out.append(_signed64(v))
        else:  # packed
            pos = 0
            while pos < len(v):
                x, pos = _r_varint(v, pos)
                out.append(_signed64(x))
    return out


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _floats_of(fields, num) -> List[float]:
    out: List[float] = []
    for wire, v in fields.get(num, []):
        if wire == 5:
            out.append(v)
        else:  # packed f32
            out.extend(struct.unpack(f"<{len(v) // 4}f", v))
    return out


def _str_of(fields, num, default="") -> str:
    vals = fields.get(num)
    if not vals:
        return default
    v = vals[-1][1]
    return v.decode() if isinstance(v, (bytes, bytearray)) else str(v)


def _int_of(fields, num, default=0) -> int:
    vals = fields.get(num)
    return _signed64(vals[-1][1]) if vals else default


# ---- per-message encode -----------------------------------------------------

def _enc_tensor(t: TensorProto) -> bytes:
    out = bytearray()
    for d in t.dims:
        _w_int(out, 1, d)
    _w_int(out, 2, t.data_type)
    for v in t.float_data:
        _w_f32(out, 4, v)
    for v in t.int32_data:
        _w_int(out, 5, v)
    for v in t.int64_data:
        _w_int(out, 7, v)
    if t.name:
        _w_str(out, 8, t.name)
    if t.raw_data:
        _w_len(out, 9, t.raw_data)
    return bytes(out)


def _enc_attr(a: AttributeProto) -> bytes:
    out = bytearray()
    _w_str(out, 1, a.name)
    if a.type == FLOAT:
        _w_f32(out, 2, a.f)
    elif a.type == INT:
        _w_int(out, 3, a.i)
    elif a.type == STRING:
        _w_str(out, 4, a.s)
    elif a.type == TENSOR and a.t is not None:
        _w_len(out, 5, _enc_tensor(a.t))
    elif a.type == FLOATS:
        for v in a.floats:
            _w_f32(out, 7, v)
    elif a.type == INTS:
        for v in a.ints:
            _w_int(out, 8, v)
    _w_int(out, 20, a.type)
    return bytes(out)


def _enc_node(n: NodeProto) -> bytes:
    out = bytearray()
    for s in n.input:
        _w_str(out, 1, s)
    for s in n.output:
        _w_str(out, 2, s)
    if n.name:
        _w_str(out, 3, n.name)
    _w_str(out, 4, n.op_type)
    for a in n.attribute:
        _w_len(out, 5, _enc_attr(a))
    return bytes(out)


def _enc_value_info(vi: ValueInfoProto) -> bytes:
    shape = bytearray()
    for d in vi.type.shape_dims:
        dim = bytearray()
        _w_int(dim, 1, d)  # Dimension.dim_value
        _w_len(shape, 1, bytes(dim))  # TensorShapeProto.dim
    tt = bytearray()
    _w_int(tt, 1, vi.type.elem_type)  # Tensor.elem_type
    _w_len(tt, 2, bytes(shape))  # Tensor.shape
    tp = bytearray()
    _w_len(tp, 1, bytes(tt))  # TypeProto.tensor_type
    out = bytearray()
    _w_str(out, 1, vi.name)
    _w_len(out, 2, bytes(tp))
    return bytes(out)


def _enc_graph(g: GraphProto) -> bytes:
    out = bytearray()
    for n in g.node:
        _w_len(out, 1, _enc_node(n))
    if g.name:
        _w_str(out, 2, g.name)
    for t in g.initializer:
        _w_len(out, 5, _enc_tensor(t))
    for vi in g.input:
        _w_len(out, 11, _enc_value_info(vi))
    for vi in g.output:
        _w_len(out, 12, _enc_value_info(vi))
    return bytes(out)


def serialize(m: ModelProto) -> bytes:
    out = bytearray()
    _w_int(out, 1, m.ir_version)
    _w_str(out, 2, m.producer_name)
    _w_len(out, 7, _enc_graph(m.graph))
    opset = bytearray()
    _w_str(opset, 1, "")  # default domain
    _w_int(opset, 2, m.opset_version)
    _w_len(out, 8, bytes(opset))
    return bytes(out)


# ---- per-message decode -----------------------------------------------------

def _dec_tensor(buf: bytes) -> TensorProto:
    f = _scan(buf)
    return TensorProto(
        name=_str_of(f, 8),
        dims=_ints_of(f, 1),
        data_type=_int_of(f, 2, DT_FLOAT),
        raw_data=bytes(f[9][-1][1]) if 9 in f else b"",
        float_data=_floats_of(f, 4),
        int32_data=_ints_of(f, 5),
        int64_data=_ints_of(f, 7),
    )


def _dec_attr(buf: bytes) -> AttributeProto:
    f = _scan(buf)
    a = AttributeProto(name=_str_of(f, 1), type=_int_of(f, 20))
    if 2 in f:
        a.f = float(f[2][-1][1])
        a.type = a.type or FLOAT
    if 3 in f:
        a.i = _int_of(f, 3)
        a.type = a.type or INT
    if 4 in f:
        a.s = bytes(f[4][-1][1])
        a.type = a.type or STRING
    if 5 in f:
        a.t = _dec_tensor(f[5][-1][1])
        a.type = a.type or TENSOR
    if 7 in f:
        a.floats = _floats_of(f, 7)
        a.type = a.type or FLOATS
    if 8 in f:
        a.ints = _ints_of(f, 8)
        a.type = a.type or INTS
    return a


def _dec_node(buf: bytes) -> NodeProto:
    f = _scan(buf)
    return NodeProto(
        op_type=_str_of(f, 4),
        name=_str_of(f, 3),
        input=[v.decode() for _, v in f.get(1, [])],
        output=[v.decode() for _, v in f.get(2, [])],
        attribute=[_dec_attr(v) for _, v in f.get(5, [])],
    )


def _dec_value_info(buf: bytes) -> ValueInfoProto:
    f = _scan(buf)
    vi = ValueInfoProto(name=_str_of(f, 1))
    if 2 in f:
        tf = _scan(f[2][-1][1])
        if 1 in tf:  # tensor_type
            tt = _scan(tf[1][-1][1])
            vi.type.elem_type = _int_of(tt, 1, DT_FLOAT)
            if 2 in tt:  # shape
                sh = _scan(tt[2][-1][1])
                for _, dimbuf in sh.get(1, []):
                    df = _scan(dimbuf)
                    vi.type.shape_dims.append(_int_of(df, 1, 0))
    return vi


def _dec_graph(buf: bytes) -> GraphProto:
    f = _scan(buf)
    return GraphProto(
        name=_str_of(f, 2),
        node=[_dec_node(v) for _, v in f.get(1, [])],
        initializer=[_dec_tensor(v) for _, v in f.get(5, [])],
        input=[_dec_value_info(v) for _, v in f.get(11, [])],
        output=[_dec_value_info(v) for _, v in f.get(12, [])],
    )


def parse(buf: bytes) -> ModelProto:
    f = _scan(buf)
    m = ModelProto(ir_version=_int_of(f, 1, 8), producer_name=_str_of(f, 2))
    if 7 in f:
        m.graph = _dec_graph(f[7][-1][1])
    return m


def load(path: str) -> ModelProto:
    with open(path, "rb") as fh:
        return parse(fh.read())


def save(model: ModelProto, path: str) -> None:
    with open(path, "wb") as fh:
        fh.write(serialize(model))


# ---- helper constructors (onnx.helper-style surface) ------------------------

def make_tensor_value_info(name: str, elem_type: int,
                           shape) -> ValueInfoProto:
    return ValueInfoProto(name=name, type=_TensorTypeProto(
        elem_type=elem_type, shape_dims=[int(d) for d in shape]))


def make_node(op_type: str, inputs, outputs, name: str = "",
              **attrs) -> NodeProto:
    alist = []
    for k, v in attrs.items():
        if isinstance(v, float):
            alist.append(AttributeProto(name=k, type=FLOAT, f=v))
        elif isinstance(v, bool) or isinstance(v, int):
            alist.append(AttributeProto(name=k, type=INT, i=int(v)))
        elif isinstance(v, str):
            alist.append(AttributeProto(name=k, type=STRING, s=v.encode()))
        elif isinstance(v, TensorProto):
            alist.append(AttributeProto(name=k, type=TENSOR, t=v))
        elif isinstance(v, (list, tuple)):
            def is_int(x):
                return (isinstance(x, (int, np.integer))
                        and not isinstance(x, bool))

            def is_num(x):
                return is_int(x) or isinstance(x, (float, np.floating))

            if all(is_int(x) for x in v):
                alist.append(AttributeProto(name=k, type=INTS,
                                            ints=[int(x) for x in v]))
            elif all(is_num(x) for x in v):
                alist.append(AttributeProto(name=k, type=FLOATS,
                                            floats=[float(x) for x in v]))
            else:
                raise TypeError(
                    f"attribute {k}: list must be all ints or all numeric "
                    f"(bools not allowed), got {v!r}")
        else:
            raise TypeError(f"unsupported attribute {k}={v!r}")
    return NodeProto(op_type=op_type, name=name, input=list(inputs),
                     output=list(outputs), attribute=alist)


def from_array(arr: np.ndarray, name: str = "") -> TensorProto:
    arr = np.asarray(arr)
    dt = _NP_TO_DT.get(arr.dtype)
    if dt is None:
        raise TypeError(f"unsupported dtype {arr.dtype}")
    return TensorProto(name=name, dims=list(arr.shape), data_type=dt,
                       raw_data=arr.tobytes())


def to_array(t: TensorProto) -> np.ndarray:
    np_dt = _DT_TO_NP.get(t.data_type)
    if np_dt is None:
        raise TypeError(
            f"tensor {t.name!r}: unsupported ONNX data_type {t.data_type} "
            f"(supported: float32/int32/int64)")
    if t.raw_data:
        return np.frombuffer(t.raw_data, dtype=np_dt).reshape(t.dims)
    if t.float_data:
        return np.asarray(t.float_data, np.float32).reshape(t.dims)
    if t.int32_data:
        return np.asarray(t.int32_data, np.int32).reshape(t.dims)
    return np.asarray(t.int64_data, np.int64).reshape(t.dims)


def make_graph(nodes, name, inputs, outputs,
               initializer=()) -> GraphProto:
    return GraphProto(name=name, node=list(nodes), input=list(inputs),
                      output=list(outputs), initializer=list(initializer))


def make_model(graph: GraphProto, opset_version: int = 13) -> ModelProto:
    return ModelProto(graph=graph, opset_version=opset_version)
