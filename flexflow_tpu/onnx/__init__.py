from flexflow_tpu.onnx.model import ONNXModel, ONNXModelKeras  # noqa: F401
