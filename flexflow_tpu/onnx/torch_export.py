"""torch.onnx.export without the `onnx` package.

The torch TorchScript exporter serializes the ModelProto itself (C++
protobuf writer) but unconditionally imports the `onnx` python package for
one post-pass, `_add_onnxscript_fn`, which deserializes the model only to
scan for custom onnx-script functions and returns the bytes UNCHANGED when
there are none (torch/onnx/_internal/torchscript_exporter/
onnx_proto_utils.py). Standard nn.Module exports carry no such functions,
so in this offline image we satisfy that import with a stub whose parsed
model reports zero nodes — the scan no-ops and the exporter writes the
exact bytes it produced. The resulting file is a normal ONNX protobuf that
flexflow_tpu.onnx.ONNXModel parses with the in-repo minionnx codec.

Role parity: the reference's *_pt.py onnx examples run torch.onnx.export
with the real onnx package installed (examples/python/onnx/mnist_mlp_pt.py).
"""

from __future__ import annotations

import sys
import types


class _StubGraph:
    node = ()


class _StubModel:
    graph = _StubGraph()
    functions: list = []


def _install_onnx_stub() -> None:
    mod = types.ModuleType("onnx")
    mod.__doc__ = ("flexflow_tpu minimal stand-in for the onnx package "
                   "(torch export custom-function scan only)")
    mod.load_model_from_string = lambda b: _StubModel()
    mod.__flexflow_tpu_stub__ = True
    sys.modules["onnx"] = mod


def export(model, args, path: str, input_names=None, output_names=None,
           **kwargs) -> None:
    """Drop-in for torch.onnx.export that works with or without the real
    onnx package. Forces the TorchScript exporter (dynamo=False): the
    dynamo exporter needs onnxscript, absent from this image. The stub is
    confined to this call — it is removed from sys.modules afterwards so a
    later `import onnx` elsewhere fails cleanly instead of hitting a
    two-attribute stand-in."""
    stub_installed = False
    try:
        import onnx  # noqa: F401 — real package present, nothing to do
    except ImportError:
        _install_onnx_stub()
        stub_installed = True
    import torch

    try:
        torch.onnx.export(model, args, path, input_names=input_names,
                          output_names=output_names, dynamo=False, **kwargs)
    finally:
        if stub_installed:
            sys.modules.pop("onnx", None)
