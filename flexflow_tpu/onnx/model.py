"""ONNX importer: walk an onnx.ModelProto and replay nodes onto an FFModel.

Reference: python/flexflow/onnx/model.py — `handleX` dispatch per node op_type,
including the Gemm->dense fusion pass (model.py:297) and the Keras-flavored
variant used by keras_exp (ONNXModelKeras).

The `onnx` package is not bundled in this environment; import is deferred to
construction so the rest of the framework works without it.
"""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.ffconst import ActiMode, DataType, PoolType


def _attrs(node) -> Dict[str, object]:
    out = {}
    for a in node.attribute:
        if a.type == 1:
            out[a.name] = a.f
        elif a.type == 2:
            out[a.name] = a.i
        elif a.type == 6:  # FLOATS
            out[a.name] = list(a.floats)
        elif a.type == 7:  # INTS
            out[a.name] = list(a.ints)
        elif a.type == 3:
            out[a.name] = a.s.decode()
        elif a.type == 4:
            out[a.name] = a.t
    return out


class ONNXModel:
    def __init__(self, filename):
        if isinstance(filename, str):
            try:
                import onnx
                if getattr(onnx, "__flexflow_tpu_stub__", False):
                    # torch_export installed its scan-only stand-in; it
                    # cannot parse files
                    raise ImportError("onnx is the torch-export stub")
                self.model = onnx.load(filename)
            except ImportError:
                # the in-repo minimal codec parses the same wire format, so
                # .onnx files load without the package (minionnx.py)
                from flexflow_tpu.onnx import minionnx

                self.model = minionnx.load(filename)
        else:
            self.model = filename  # ModelProto (or any duck-typed equivalent)
        self.symbol_table: Dict[str, object] = {}
        self.inputs: Dict[str, object] = {}
        for inp in self.model.graph.input:
            self.inputs[inp.name] = inp
        self.initializer = {t.name: t for t in self.model.graph.initializer}

    # ---- handlers (reference model.py:74-360) -------------------------------

    def handleAdd(self, ff, node):
        return ff.add(self.symbol_table[node.input[0]],
                      self.symbol_table[node.input[1]], name=node.name or None)

    def handleSub(self, ff, node):
        return ff.subtract(self.symbol_table[node.input[0]],
                           self.symbol_table[node.input[1]], name=node.name or None)

    def handleMul(self, ff, node):
        return ff.multiply(self.symbol_table[node.input[0]],
                           self.symbol_table[node.input[1]], name=node.name or None)

    def handleConcat(self, ff, node):
        a = _attrs(node)
        ts = [self.symbol_table[i] for i in node.input]
        return ff.concat(ts, int(a.get("axis", 1)), name=node.name or None)

    def handleSplit(self, ff, node):
        a = _attrs(node)
        t = self.symbol_table[node.input[0]]
        axis = int(a.get("axis", 0))
        sizes = a.get("split")
        outs = ff.split(t, [int(s) for s in sizes] if sizes
                        else len(node.output), axis)
        for name, out in zip(node.output, outs):
            self.symbol_table[name] = out
        return None  # outputs registered above

    def _pool(self, ff, node, pool_type):
        a = _attrs(node)
        k = a.get("kernel_shape", [2, 2])
        s = a.get("strides", [1, 1])
        p = a.get("pads", [0, 0, 0, 0])
        return ff.pool2d(self.symbol_table[node.input[0]], int(k[0]), int(k[1]),
                         int(s[0]), int(s[1]), int(p[0]), int(p[1]),
                         pool_type=pool_type, name=node.name or None)

    def handleAveragePool(self, ff, node):
        return self._pool(ff, node, PoolType.POOL_AVG)

    def handleMaxPool(self, ff, node):
        return self._pool(ff, node, PoolType.POOL_MAX)

    def handleGlobalAveragePool(self, ff, node):
        t = self.symbol_table[node.input[0]]
        h, w = t.dims[2], t.dims[3]
        return ff.pool2d(t, h, w, 1, 1, 0, 0, pool_type=PoolType.POOL_AVG,
                         name=node.name or None)

    def handleBatchNormalization(self, ff, node):
        return ff.batch_norm(self.symbol_table[node.input[0]], relu=False,
                             name=node.name or None)

    def handleConv(self, ff, node):
        a = _attrs(node)
        t = self.symbol_table[node.input[0]]
        w = self.initializer[node.input[1]]
        out_channels = w.dims[0]
        k = a.get("kernel_shape", [w.dims[2], w.dims[3]])
        s = a.get("strides", [1, 1])
        p = a.get("pads", [0, 0, 0, 0])
        group = int(a.get("group", 1))
        return ff.conv2d(t, int(out_channels), int(k[0]), int(k[1]),
                         int(s[0]), int(s[1]), int(p[0]), int(p[1]),
                         groups=group, use_bias=len(node.input) > 2,
                         name=node.name or None)

    def handleDropout(self, ff, node):
        a = _attrs(node)
        return ff.dropout(self.symbol_table[node.input[0]],
                          float(a.get("ratio", 0.5)), name=node.name or None)

    def handleFlatten(self, ff, node):
        return ff.flat(self.symbol_table[node.input[0]], name=node.name or None)

    def handleGemm(self, ff, node):
        w = self.initializer[node.input[1]]
        out_dim = w.dims[0]
        return ff.dense(self.symbol_table[node.input[0]], int(out_dim),
                        use_bias=len(node.input) > 2, name=node.name or None)

    def handleMatMul(self, ff, node):
        if node.input[1] in self.initializer:
            w = self.initializer[node.input[1]]
            return ff.dense(self.symbol_table[node.input[0]], int(w.dims[-1]),
                            use_bias=False, name=node.name or None)
        return ff.batch_matmul(self.symbol_table[node.input[0]],
                               self.symbol_table[node.input[1]],
                               name=node.name or None)

    def handleRelu(self, ff, node):
        return ff.relu(self.symbol_table[node.input[0]], name=node.name or None)

    def handleSigmoid(self, ff, node):
        return ff.sigmoid(self.symbol_table[node.input[0]], name=node.name or None)

    def handleTanh(self, ff, node):
        return ff.tanh(self.symbol_table[node.input[0]], name=node.name or None)

    def handleElu(self, ff, node):
        return ff.elu(self.symbol_table[node.input[0]], name=node.name or None)

    def handleSoftmax(self, ff, node):
        return ff.softmax(self.symbol_table[node.input[0]], name=node.name or None)

    def handlePad(self, ff, node):
        # reference: identity passthrough (model.py:223-228)
        return self.symbol_table[node.input[0]]

    def handleReshape(self, ff, node):
        shape_t = self.initializer.get(node.input[1])
        if shape_t is None:
            return self.symbol_table[node.input[0]]
        from flexflow_tpu.onnx import minionnx

        if isinstance(shape_t, minionnx.TensorProto):
            to_array = minionnx.to_array  # minionnx-built model object
        else:
            import onnx.numpy_helper as nph
            to_array = nph.to_array
        shape = [int(v) for v in to_array(shape_t)]
        return ff.reshape(self.symbol_table[node.input[0]], shape,
                          name=node.name or None)

    def handleTranspose(self, ff, node):
        a = _attrs(node)
        perm = a.get("perm")
        return ff.transpose(self.symbol_table[node.input[0]], perm,
                            name=node.name or None)

    def handleCast(self, ff, node):
        return self.symbol_table[node.input[0]]

    def handleUnsqueeze(self, ff, node):
        t = self.symbol_table[node.input[0]]
        a = _attrs(node)
        axes = a.get("axes", [0])
        shape = list(t.dims)
        for ax in sorted(int(x) for x in axes):
            shape.insert(ax, 1)
        return ff.reshape(t, shape, name=node.name or None)

    def handleIdentity(self, ff, node):
        return self.symbol_table[node.input[0]]

    # ---- driver -------------------------------------------------------------

    def apply(self, ffmodel, input_dict: Dict[str, object]):
        """input_dict: onnx graph input name -> FFModel tensor."""
        self.symbol_table = dict(input_dict)
        outputs = None
        for node in self.model.graph.node:
            # torch eval-mode exports route shared/folded weights through
            # Identity nodes whose input is an initializer, not a symbol —
            # alias the initializer under the output name and move on
            if node.op_type == "Identity" \
                    and node.input[0] in self.initializer:
                self.initializer[node.output[0]] = \
                    self.initializer[node.input[0]]
                continue
            handler = getattr(self, "handle" + node.op_type, None)
            if handler is None:
                raise AssertionError(f"unsupported ONNX op {node.op_type}")
            out = handler(ffmodel, node)
            if out is not None:
                self.symbol_table[node.output[0]] = out
                outputs = out
        graph_outs = [self.symbol_table[o.name]
                      for o in self.model.graph.output
                      if o.name in self.symbol_table]
        return graph_outs[0] if len(graph_outs) == 1 else (graph_outs or outputs)


class ONNXModelKeras(ONNXModel):
    """Variant used by the keras_exp path (reference model.py: ONNXModelKeras
    — same walker, Keras-exported Gemm/Dense naming)."""

    def __init__(self, filename, ffconfig=None, ffmodel=None):
        super().__init__(filename)

    handleDense = ONNXModel.handleGemm
