"""Pipelined transformer layer stack: graph-level pipeline parallelism.

Reference: pipelining exists only in the hand-rolled NMT subsystem — chunked
timesteps over per-(layer,timestep) device tables (nmt/rnn.h:21-63,
SharedVariable weight placement rnn.h:37-51). The TPU re-design is the
standard stacked-layer scheme: all L identical transformer blocks live in ONE
op whose weights carry a leading layer dim; under a 'pipe' mesh axis of size
S the stack reshapes to [S, L/S, ...], each pipe index owns L/S layers, and
microbatches ripple through the ring via the GPipe loop
(parallel/pipeline.py). Without a pipe axis the same op is a lax.scan over
layers — one compiled block body either way (XLA-friendly, no per-layer
unrolling).

This integrates PP with the strategy system: the stack's weights shard dim 0
over 'pipe' (weight_partition), batch stays partitionable over 'data'
(dp x pp composition), and the single-device path is numerically identical
(tests/test_pipeline_moe.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from flexflow_tpu.ffconst import DataType, OperatorType
from flexflow_tpu.ops.base import Op, WeightSpec


def _layer_norm(h, scale, bias, eps=1e-5):
    mu = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.var(h, axis=-1, keepdims=True)
    return (h - mu) * lax.rsqrt(var + eps) * scale + bias


def _block(p: Dict[str, jnp.ndarray], h: jnp.ndarray, num_heads: int,
           causal: bool, use_flash: bool = True) -> jnp.ndarray:
    """Pre-LN transformer block: MHA + residual, FFN(gelu) + residual.
    Attention runs the Pallas flash kernel on TPU (same selection rule as
    the MultiHeadAttention op; use_flash=False — the config opt-out — forces
    the einsum softmax)."""
    import os

    B, S, D = h.shape
    hd = D // num_heads
    a = _layer_norm(h, p["ln1_scale"], p["ln1_bias"])
    q = (a @ p["wq"] + p["bq"]).reshape(B, S, num_heads, hd)
    k = (a @ p["wk"] + p["bk"]).reshape(B, S, num_heads, hd)
    v = (a @ p["wv"] + p["bv"]).reshape(B, S, num_heads, hd)
    if use_flash and (jax.default_backend() == "tpu"
                      or os.environ.get("FF_FORCE_FLASH_ATTENTION") == "1") \
            and S % min(128, S) == 0:
        from flexflow_tpu.ops.pallas_kernels import flash_attention

        ctx = flash_attention(q, k, v, causal, 1.0 / np.sqrt(hd))
    else:
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
        if causal:
            mask = jnp.tril(jnp.ones((S, S), bool))
            logits = jnp.where(mask[None, None], logits, -1e30)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(logits, axis=-1), v)
    h = h + ctx.reshape(B, S, D) @ p["wo"] + p["bo"]
    f = _layer_norm(h, p["ln2_scale"], p["ln2_bias"])
    f = jax.nn.gelu(f @ p["w1"] + p["b1"])
    return h + f @ p["w2"] + p["b2"]


class TransformerPipelineStack(Op):
    """L identical transformer blocks with stacked weights [L, ...]."""

    op_type = OperatorType.OP_MULTIHEAD_ATTENTION
    wants_shard_ctx = True

    def __init__(self, model, name, inputs, num_layers: int, num_heads: int,
                 ffn_mult: int = 4, causal: bool = False,
                 num_microbatches: Optional[int] = None):
        super().__init__(model, name, inputs, num_layers=num_layers,
                         num_heads=num_heads, ffn_mult=ffn_mult,
                         causal=causal)
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_mult = ffn_mult
        self.causal = causal
        self.num_microbatches = num_microbatches
        d = inputs[0].dims[-1]
        assert d % num_heads == 0, f"hidden {d} % heads {num_heads}"
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [DataType.DT_FLOAT]

    def weights(self) -> List[WeightSpec]:
        L = self.num_layers
        D = self.inputs[0].dims[-1]
        F = D * self.ffn_mult
        specs = []
        for nm in ("wq", "wk", "wv", "wo"):
            specs.append(WeightSpec(nm, (L, D, D), fan=(D, D)))
        for nm in ("bq", "bk", "bv", "bo"):
            specs.append(WeightSpec(nm, (L, D), init="zero"))
        specs += [
            WeightSpec("w1", (L, D, F), fan=(D, F)),
            WeightSpec("b1", (L, F), init="zero"),
            WeightSpec("w2", (L, F, D), fan=(F, D)),
            WeightSpec("b2", (L, D), init="zero"),
            WeightSpec("ln1_scale", (L, D), init="one"),
            WeightSpec("ln1_bias", (L, D), init="zero"),
            WeightSpec("ln2_scale", (L, D), init="one"),
            WeightSpec("ln2_bias", (L, D), init="zero"),
        ]
        return specs

    # -- parallelization -------------------------------------------------------

    def pipeline_stages(self) -> int:
        # the search proposes {axis: STAGE} when the axis size divides this
        return self.num_layers

    def _stage_axis(self, axis_map, mesh_shape=None):
        """(axis_name, n_stages) the stack pipelines over: a STAGE
        assignment in the strategy's axis_map (search-discovered PP — any
        mesh axis name), else the legacy convention of a mesh axis literally
        named 'pipe'. (None, 1) = run serial.

        `mesh_shape` defaults to the model config's, but callers holding
        the authoritative mesh (forward's shard_ctx; a search over a
        mesh_shape override) pass theirs — a STAGE assignment must not be
        silently degraded just because config.mesh_shape lacks the axis."""
        from flexflow_tpu.parallel.pconfig import STAGE

        if mesh_shape is None:
            mesh_shape = getattr(self.model.config, "mesh_shape", None) or {}
        ax = next((a for a, d in (axis_map or {}).items() if d == STAGE),
                  None)
        if ax is None and mesh_shape.get("pipe", 1) > 1:
            ax = "pipe"
        if ax is None:
            return None, 1
        s = mesh_shape.get(ax, 1)
        if s > 1 and self.num_layers % s != 0:
            if not getattr(self, "_warned_pipe_mismatch", False):
                self._warned_pipe_mismatch = True
                from flexflow_tpu.logger import fflogger

                fflogger.warning(
                    "%s: num_layers=%d not divisible by stage axis %r "
                    "size %d — pipeline parallelism DISABLED, running "
                    "serial on replicated weights (the %d devices stay "
                    "idle)", self.name, self.num_layers, ax, s, s)
            return None, 1
        return (ax, s) if s > 1 else (None, 1)

    def weight_partition(self, axis_map):
        from jax.sharding import PartitionSpec as P
        from flexflow_tpu.parallel.pconfig import STAGE

        # a STAGE assignment shards the layer dim over its axis
        # UNCONDITIONALLY of config.mesh_shape: the proposer (search over a
        # possibly-overridden mesh) already validated divisibility, and the
        # cost model's grad-sync pricing keys off this spec — degrading to
        # replicated here would charge PP candidates DP's all-reduce
        ax = next((a for a, d in (axis_map or {}).items() if d == STAGE),
                  None)
        if ax is None:
            ax, stages = self._stage_axis(axis_map)
            if stages <= 1:
                return super().weight_partition(axis_map)
        # each stage owns its layers' weights (SharedVariable-per-node
        # analog, rnn.h:37-51)
        return {w.name: P(*([ax] + [None] * (len(w.shape) - 1)))
                for w in self.weight_specs()}

    def partitionable_output_dims(self):
        # dim 1 = sequence: exposing it gives the search a sequence-parallel
        # candidate (activations shard over seq between blocks; attention's
        # internal all-gather is priced by the cost model's resharding pass)
        return [0, 1]

    def single_axis_dims(self):
        # the seq dim lowers through a single named axis (ring attention /
        # all-gather lowering) — no multi-axis products
        return [1]

    def flops(self):
        B, S, D = self.inputs[0].dims
        per_layer = (4 * B * S * D * D + 2 * B * S * S * D
                     + 2 * B * S * D * D * self.ffn_mult)
        return 2 * per_layer * self.num_layers

    # -- execution -------------------------------------------------------------

    def forward(self, params, xs, *, training=False, rng=None, shard_ctx=None):
        x = xs[0]
        L, H, causal = self.num_layers, self.num_heads, self.causal
        use_flash = getattr(self.model.config, "use_flash_attention", True)
        axis_map = (shard_ctx.get("axis_map") or {}) if shard_ctx else {}
        mesh = shard_ctx["mesh"] if shard_ctx else None
        pipe_axis, stages = self._stage_axis(
            axis_map, dict(mesh.shape) if mesh is not None else None)

        if stages > 1 and mesh is not None and pipe_axis in mesh.shape:
            from flexflow_tpu.parallel.pipeline import pipeline

            per_stage = L // stages
            stacked = {k: v.reshape(stages, per_stage, *v.shape[1:])
                       for k, v in params.items()}

            def stage_fn(sp, h):
                # this stage's per_stage layers, scanned
                def body(hh, lp):
                    return _block(lp, hh, H, causal, use_flash), None

                out, _ = lax.scan(body, h, sp)
                return out

            num_micro = self.num_microbatches or stages
            # the axis sharding the batch dim comes from the strategy, not a
            # hardcoded name — a mesh calling its data axis something else
            # must still shard microbatches over it
            batch_axes = [ax for ax, d in axis_map.items()
                          if d == 0 and ax != pipe_axis
                          and mesh.shape.get(ax, 1) > 1]
            data_axis = batch_axes[0] if batch_axes else None
            return [pipeline(stage_fn, stacked, x, mesh,
                             axis_name=pipe_axis,
                             num_microbatches=num_micro,
                             data_axis=data_axis)]

        def body(hh, lp):
            return _block(lp, hh, H, causal, use_flash), None

        out, _ = lax.scan(body, x, params)
        return [out]
