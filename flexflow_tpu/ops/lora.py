"""Paged LoRA adapter pool — device side (ISSUE 14).

Multi-tenant serving wants N tenants' low-rank adapters live on one
replica with ZERO recompiles: adapter weights therefore live in a
fixed-geometry device POOL (the paged-KV design applied to weights) and
the adapter a slot applies is *data* — a per-slot page index gathered
inside the one compiled slot program, exactly like the KV page table.

Pool layout (one pool per served model): for every LoRA-targeted Linear
op, two arrays

    a: (pages, in_dim, rank)    b: (pages, rank, out_dim)

plus one shared ``"_scale"`` array (pages,) holding each adapter's
``alpha / rank``. Page 0 is the NULL adapter (all zeros, scale 0): a
request with no adapter indexes page 0 and its gathered delta is
exactly zero — the base model, at the cost of one rank-r matmul the
fixed program always executes. Pages are written by ONE fixed-shape
writer program when the host allocator (runtime/lora.py) faults an
adapter in; the gather below never changes shape, so admitting tenant
#1000 compiles nothing.

The gathered (batched/segmented) LoRA matmul: with x (B, S, in) and
per-slot pages (B,),

    delta[b] = (x[b] @ a[pages[b]]) @ b[pages[b]] * scale[pages[b]]

— two thin einsums whose inner dim is the rank, added to the base
``x @ W`` BEFORE bias/activation (ops/dense.py Linear.forward). The
delta computes in f32 (ranks are tiny; the base matmul's dtype
dominates cost) and casts to the base dtype at the add.
"""

from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp
import numpy as np


def init_lora_pool(targets: List, pages: int, rank: int) -> Dict:
    """Zero-filled adapter pool for ``targets`` (Linear ops): ``pages``
    usable pages PLUS the reserved null page 0. f32 storage — adapter
    tensors are rank-thin, so pool bytes are marginal next to the KV
    pool."""
    pool = {
        op.name: {
            "a": jnp.zeros((pages + 1, op.in_dim, rank), jnp.float32),
            "b": jnp.zeros((pages + 1, rank, op.out_dim), jnp.float32),
        }
        for op in targets}
    pool["_scale"] = jnp.zeros((pages + 1,), jnp.float32)
    return pool


def write_adapter_page(pool: Dict, page, payload: Dict, scale):
    """Scatter one adapter's weights into ``page`` of every target's
    pool arrays (the body of the engine's fixed-shape writer program;
    ``page`` is a traced scalar so one compile serves every fault-in).
    ``payload`` maps op name -> {"a", "b"}; ops the adapter does not
    target carry zeros."""
    out = {}
    for name, arrs in pool.items():
        if name == "_scale":
            continue
        sub = payload[name]
        out[name] = {
            "a": arrs["a"].at[page].set(sub["a"].astype(jnp.float32)),
            "b": arrs["b"].at[page].set(sub["b"].astype(jnp.float32)),
        }
    out["_scale"] = pool["_scale"].at[page].set(
        jnp.asarray(scale, jnp.float32))
    return out


def gather_op_lora(pool: Dict, op_name: str, pages):
    """Per-slot operands for one op's gathered LoRA matmul:
    (a (B, in, r), b (B, r, out), scale (B,)) — or None when the op is
    not LoRA-targeted."""
    arrs = pool.get(op_name)
    if arrs is None:
        return None
    pages = jnp.asarray(pages, jnp.int32)
    return (arrs["a"][pages], arrs["b"][pages], pool["_scale"][pages])


def lora_delta(x, a, b, scale):
    """The batched segmented LoRA delta: x (B, ..., in) with PER-ROW
    adapters a (B, in, r), b (B, r, out), scale (B,) ->
    (B, ..., out) in x.dtype. f32 accumulation through the thin rank
    dim; one slot's tokens only ever touch that slot's adapter rows —
    the segmented-matmul property that lets mixed tenants share one
    dispatch."""
    xf = x.astype(jnp.float32)
    h = jnp.einsum("b...i,bir->b...r", xf, a)
    d = jnp.einsum("b...r,bro->b...o", h, b)
    s = scale.reshape((-1,) + (1,) * (x.ndim - 1))
    return (d * s).astype(x.dtype)


def zero_payload(targets: List, rank: int) -> Dict:
    """Host-side all-zero payload template (np arrays) for the writer."""
    return {op.name: {"a": np.zeros((op.in_dim, rank), np.float32),
                      "b": np.zeros((rank, op.out_dim), np.float32)}
            for op in targets}
