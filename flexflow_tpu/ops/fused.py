"""FusedOp: a producer op plus a chain of fused-on elementwise followers.

Reference: src/ops/fused.cu (FusedOp dispatches member ops' kernels
back-to-back in one task) + FFModel::apply_fusion (model.cc:1404-1475), which
merges producer/consumer ops sharing an identical ParallelConfig.

On TPU, XLA already fuses elementwise chains into the producer's kernel, so
execution-level fusion is free; what this node buys is *graph-level* parity:

  * the strategy table and the search see ONE op per fused group (the
    reference's motivation — fewer strategy entries, fewer simulated tasks);
  * the cost model stops charging HBM round-trips for intermediates, which is
    what the hardware actually does post-XLA-fusion;
  * per-op profiling reports the group the way the reference's FusedOp
    profiling does.

Members must be weightless, stateless, single-input, shape-preserving ops
whose sole consumer is the next member — the conservative subset of the
reference's fusion condition (model.cc:1424-1475).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import Op


class FusedOp(Op):
    op_type = OperatorType.OP_FUSED

    def __init__(self, leader: Op, members: List[Op]):
        # Takes the leader's name so existing strategy entries / param keys
        # keep working (the group is searched and checkpointed as the leader).
        super().__init__(leader.model, leader.name, leader.inputs)
        self.leader = leader
        self.members = list(members)
        self.stateful = leader.stateful
        # the executor only passes shard_ctx when this attribute is set; a
        # leader that needs it (SP attention, pipeline stack) must keep it
        # visible through the fused wrapper
        self.wants_shard_ctx = getattr(leader, "wants_shard_ctx", False)
        self.needs_rng = leader.needs_rng or any(m.needs_rng for m in members)
        # graph output = the LAST member's tensors, so downstream consumers'
        # tensor-object lookups keep resolving (intermediates vanish from the
        # value map — the fused group has no externally visible intermediates)
        self.outputs = self.members[-1].outputs

    def finalize(self):  # outputs adopted from members; nothing to infer
        raise RuntimeError("FusedOp is built by apply_fusion, not finalize()")

    # -- execution ------------------------------------------------------------

    def _run_members(self, outs, *, training, rng):
        for j, m in enumerate(self.members):
            m_rng = jax.random.fold_in(rng, j + 1) if (
                m.needs_rng and rng is not None) else None
            outs = m.forward({}, outs, training=training, rng=m_rng)
        return outs

    def forward(self, params, xs, *, training=False, rng=None, **kw):
        lead_rng = jax.random.fold_in(rng, 0) if (
            self.leader.needs_rng and rng is not None) else None
        if getattr(self.leader, "wants_shard_ctx", False) and "shard_ctx" in kw:
            outs = self.leader.forward(params, xs, training=training,
                                       rng=lead_rng, shard_ctx=kw["shard_ctx"])
        else:
            outs = self.leader.forward(params, xs, training=training,
                                       rng=lead_rng)
        return self._run_members(outs, training=training, rng=rng)

    def forward_stateful(self, params, state, xs, *, training=False, rng=None):
        lead_rng = jax.random.fold_in(rng, 0) if (
            self.leader.needs_rng and rng is not None) else None
        outs, new_state = self.leader.forward_stateful(
            params, state, xs, training=training, rng=lead_rng)
        return self._run_members(outs, training=training, rng=rng), new_state

    def init_state(self):
        return self.leader.init_state()

    # -- weights / parallelization: delegate to the leader --------------------

    def weights(self):
        return self.leader.weights()

    def weight_partition(self, axis_map):
        return self.leader.weight_partition(axis_map)

    def partitionable_output_dims(self):
        dims = set(self.leader.partitionable_output_dims())
        for m in self.members:
            dims &= set(m.partitionable_output_dims())
        return sorted(dims)

    def input_axis_map(self, axis_map, input_idx):
        return self.leader.input_axis_map(axis_map, input_idx)

    _contracted_output_dims = property(
        lambda self: self.leader._contracted_output_dims)

    def flops(self):
        return self.leader.flops() + sum(m.flops() for m in self.members)

    def __repr__(self):
        chain = "+".join(type(m).__name__ for m in self.members)
        return f"FusedOp({self.leader!r}+{chain})"


def _fusable_follower(op: Op, producer_out, consumers: Dict[int, int]) -> bool:
    """op can be folded onto the group ending in `producer_out`."""
    return (len(op.inputs) == 1
            and op.inputs[0] is producer_out
            and not op.weight_specs()
            and not op.stateful
            and len(op.outputs) == 1
            and op.outputs[0].dims == op.inputs[0].dims
            and consumers.get(id(producer_out), 0) == 1)


def apply_fusion(model, protected=()) -> int:
    """Rewrite model.ops, folding fusable elementwise chains into FusedOp
    nodes (reference: FFModel::apply_fusion, model.cc:1404-1475 — repeated
    until fixpoint there; single left-to-right scan here since chains are the
    only shape we fuse). Returns the number of ops eliminated.

    `protected`: tensors that must stay externally visible (final tensor, aux
    losses) — a group never swallows one as an intermediate.

    Strategy compatibility (the reference's identical-ParallelConfig check):
    a follower with an explicit strategy entry different from the leader's
    blocks fusion.
    """
    from flexflow_tpu.ops.base import InputOp

    strategies = model.config.strategies
    protected_ids = {id(t) for t in protected}
    consumers: Dict[int, int] = {}
    for op in model.ops:
        for t in op.inputs:
            consumers[id(t)] = consumers.get(id(t), 0) + 1

    new_ops: List[Op] = []
    i, eliminated = 0, 0
    ops = list(model.ops)
    while i < len(ops):
        op = ops[i]
        if isinstance(op, InputOp):
            new_ops.append(op)
            i += 1
            continue
        leader, members = op, []
        j = i + 1
        while j < len(ops):
            tail_out = (members[-1] if members else leader).outputs[0]
            cand = ops[j]
            lead_strat = strategies.get(leader.name)
            cand_strat = strategies.get(cand.name)
            if (id(tail_out) not in protected_ids
                    and _fusable_follower(cand, tail_out, consumers)
                    and (cand_strat is None or cand_strat == lead_strat)):
                members.append(cand)
                j += 1
            else:
                break
        if members:
            new_ops.append(FusedOp(leader, members))
            for m in members:
                strategies.pop(m.name, None)
            eliminated += len(members)
            i = j
        else:
            new_ops.append(op)
            i += 1
    model.ops = new_ops
    return eliminated
