"""Elementwise unary/binary ops, scalar ops, activations-as-ops.

Reference: src/ops/element_unary.cu (cuDNN activation descriptors + custom
kernels), src/ops/element_binary.cu (cudnnOpTensor add/sub/mul/div). On TPU
these are single jnp calls that XLA fuses into neighbors; they exist as graph
nodes only so strategies/importers can reference them by name.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import DataType, OperatorType
from flexflow_tpu.ops.base import Op

_UNARY_FNS = {
    OperatorType.OP_RELU: jax.nn.relu,
    OperatorType.OP_SIGMOID: jax.nn.sigmoid,
    OperatorType.OP_TANH: jnp.tanh,
    OperatorType.OP_ELU: jax.nn.elu,
    OperatorType.OP_GELU: jax.nn.gelu,
    OperatorType.OP_EXP: jnp.exp,
    OperatorType.OP_SIN: jnp.sin,
    OperatorType.OP_COS: jnp.cos,
    OperatorType.OP_RSQRT: jax.lax.rsqrt,
    OperatorType.OP_IDENTITY: lambda x: x,
}

_BINARY_FNS = {
    OperatorType.OP_EW_ADD: jnp.add,
    OperatorType.OP_EW_SUB: jnp.subtract,
    OperatorType.OP_EW_MUL: jnp.multiply,
    OperatorType.OP_EW_DIV: jnp.divide,
    OperatorType.OP_EW_MAX: jnp.maximum,
    OperatorType.OP_EW_MIN: jnp.minimum,
}


class ElementUnary(Op):
    def __init__(self, model, name, inputs, op_type: OperatorType,
                 scalar: float = None):
        self.op_type = op_type
        super().__init__(model, name, inputs)
        self.scalar = scalar
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]
        if self.op_type == OperatorType.OP_SCALAR_MULTIPLY:
            return [x * self.scalar]
        if self.op_type == OperatorType.OP_POW:
            return [jnp.power(x, self.scalar)]
        return [_UNARY_FNS[self.op_type](x)]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims))

    def flops(self):
        return self.outputs[0].volume()


class ElementBinary(Op):
    def __init__(self, model, name, inputs, op_type: OperatorType):
        self.op_type = op_type
        super().__init__(model, name, inputs)
        self.finalize()

    def output_shapes(self):
        a, b = self.inputs[0].dims, self.inputs[1].dims
        # numpy broadcast shape
        import numpy as np

        shape = np.broadcast_shapes(a, b)
        return [tuple(shape)], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [_BINARY_FNS[self.op_type](xs[0], xs[1])]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims))

    def flops(self):
        return self.outputs[0].volume()


class Cast(Op):
    op_type = OperatorType.OP_CAST

    def __init__(self, model, name, inputs, dtype: DataType):
        super().__init__(model, name, inputs)
        self.target_dtype = dtype
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.target_dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        from flexflow_tpu.ffconst import dtype_to_np

        return [xs[0].astype(dtype_to_np(self.target_dtype))]

    def flops(self):
        return 0


class Mean(Op):
    op_type = OperatorType.OP_MEAN

    def __init__(self, model, name, inputs, dims, keepdims=False):
        super().__init__(model, name, inputs)
        self.reduce_dims = tuple(dims)
        self.keepdims = keepdims
        self.finalize()

    def output_shapes(self):
        d = list(self.inputs[0].dims)
        if self.keepdims:
            for i in self.reduce_dims:
                d[i] = 1
        else:
            d = [v for i, v in enumerate(d) if i not in self.reduce_dims]
        return [tuple(d)], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [jnp.mean(xs[0], axis=self.reduce_dims, keepdims=self.keepdims)]

    def flops(self):
        return self.inputs[0].volume()
