"""Shape/layout ops: Reshape, Transpose, Reverse, Concat, Split, TopK,
Gather, Slice, Squeeze/Unsqueeze, Pad.

Reference: src/ops/{reshape,transpose,reverse,concat,split,topk}.cu — all
custom CUDA copy/stride kernels there; on TPU each is one XLA op that fuses.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu.ffconst import DataType, OperatorType
from flexflow_tpu.ops.base import Op


class Reshape(Op):
    op_type = OperatorType.OP_RESHAPE

    def __init__(self, model, name, inputs, shape: Sequence[int]):
        super().__init__(model, name, inputs)
        shape = list(shape)
        if -1 in shape:
            known = int(np.prod([s for s in shape if s != -1]))
            shape[shape.index(-1)] = self.inputs[0].volume() // known
        self.shape = tuple(shape)
        assert int(np.prod(self.shape)) == self.inputs[0].volume(), \
            f"reshape {self.inputs[0].dims} -> {self.shape}"
        self.finalize()

    def output_shapes(self):
        return [self.shape], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [xs[0].reshape(self.shape)]

    def flops(self):
        return 0


class Transpose(Op):
    op_type = OperatorType.OP_TRANSPOSE

    def __init__(self, model, name, inputs, perm: Sequence[int]):
        super().__init__(model, name, inputs)
        self.perm = tuple(perm)
        self.finalize()

    def output_shapes(self):
        d = self.inputs[0].dims
        return [tuple(d[p] for p in self.perm)], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [jnp.transpose(xs[0], self.perm)]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims))

    def flops(self):
        return 0


class Reverse(Op):
    op_type = OperatorType.OP_REVERSE

    def __init__(self, model, name, inputs, axis: int):
        super().__init__(model, name, inputs)
        self.axis = axis
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [jnp.flip(xs[0], self.axis)]

    def flops(self):
        return 0


class Concat(Op):
    op_type = OperatorType.OP_CONCAT

    def __init__(self, model, name, inputs, axis: int):
        super().__init__(model, name, inputs)
        self.axis = axis if axis >= 0 else len(inputs[0].dims) + axis
        self.finalize()

    def output_shapes(self):
        d = list(self.inputs[0].dims)
        d[self.axis] = sum(t.dims[self.axis] for t in self.inputs)
        return [tuple(d)], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [jnp.concatenate(xs, axis=self.axis)]

    def partitionable_output_dims(self):
        return [i for i in range(self.outputs[0].num_dims) if i != self.axis]

    def flops(self):
        return 0


class Split(Op):
    op_type = OperatorType.OP_SPLIT

    def __init__(self, model, name, inputs, sizes: Sequence[int], axis: int):
        super().__init__(model, name, inputs)
        self.sizes = tuple(sizes)
        self.axis = axis
        assert sum(sizes) == inputs[0].dims[axis]
        self.finalize()

    def output_shapes(self):
        shapes = []
        for s in self.sizes:
            d = list(self.inputs[0].dims)
            d[self.axis] = s
            shapes.append(tuple(d))
        return shapes, [self.inputs[0].dtype] * len(self.sizes)

    def forward(self, params, xs, *, training=False, rng=None):
        offsets = np.cumsum((0,) + self.sizes)
        return [jax.lax.slice_in_dim(xs[0], int(offsets[i]), int(offsets[i + 1]),
                                     axis=self.axis)
                for i in range(len(self.sizes))]

    def partitionable_output_dims(self):
        return [i for i in range(self.outputs[0].num_dims) if i != self.axis]

    def flops(self):
        return 0


class TopK(Op):
    """Reference: src/ops/topk.cu (custom heap-based GPU kernels, 745 LoC);
    on TPU lax.top_k lowers to an XLA sort."""

    op_type = OperatorType.OP_TOPK

    def __init__(self, model, name, inputs, k: int, sorted: bool = True):
        super().__init__(model, name, inputs)
        self.k = k
        self.sorted = sorted
        self.finalize()

    def output_shapes(self):
        d = list(self.inputs[0].dims)
        d[-1] = self.k
        return [tuple(d), tuple(d)], [self.inputs[0].dtype, DataType.DT_INT32]

    def forward(self, params, xs, *, training=False, rng=None):
        vals, idxs = jax.lax.top_k(xs[0], self.k)
        return [vals, idxs.astype(jnp.int32)]

    def flops(self):
        d = self.inputs[0].dims
        n = d[-1]
        return self.inputs[0].volume() * int(np.log2(max(n, 2)))


class Gather(Op):
    op_type = OperatorType.OP_GATHER

    def __init__(self, model, name, inputs, axis: int):
        super().__init__(model, name, inputs)
        self.axis = axis
        self.finalize()

    def output_shapes(self):
        return [self.inputs[1].dims], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [jnp.take_along_axis(xs[0], xs[1].astype(jnp.int32), axis=self.axis)]


class Pad(Op):
    op_type = OperatorType.OP_PAD

    def __init__(self, model, name, inputs, pads: Sequence[Tuple[int, int]],
                 value: float = 0.0):
        super().__init__(model, name, inputs)
        self.pads = tuple(tuple(p) for p in pads)
        self.value = value
        self.finalize()

    def output_shapes(self):
        d = [s + lo + hi for s, (lo, hi) in zip(self.inputs[0].dims, self.pads)]
        return [tuple(d)], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [jnp.pad(xs[0], self.pads, constant_values=self.value)]
