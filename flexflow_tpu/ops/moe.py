"""Mixture-of-Experts op (expert parallelism over the 'expert' mesh axis).

Net-new vs the reference (SURVEY §2.5: "EP — absent, no MoE ops"). GShard-
style capacity-based top-k routing with two dispatch lowerings:

  * dense: (N, E, C) one-hot dispatch/combine einsums — under GSPMD,
    sharding the expert dim over the 'expert' axis turns these into
    all-to-alls over ICI; chosen whenever the mesh actually shards experts.
  * sort: tokens sorted by expert id, gathered into the (E*C, D) expert
    buffer and scatter-added back — O(N*k) routing state instead of the
    dense path's O(N*E*C), the practical choice at real token counts when
    experts are not mesh-sharded (single chip / pure dp).

FFModel.moe(dispatch="auto"|"dense"|"sort") selects; both share the router
and produce identical outputs when capacity does not bind (tested).
Includes the standard load-balancing auxiliary loss (Shazeer et al.),
surfaced through the op-aux mechanism so the executor folds it into the
training loss.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flexflow_tpu.ffconst import DataType, OperatorType
from flexflow_tpu.ops.base import Op, WeightSpec


class MoE(Op):
    op_type = OperatorType.OP_MOE
    has_aux = True  # second output = scalar load-balancing loss

    def __init__(self, model, name, inputs, num_experts: int, hidden_dim: int,
                 k: int = 2, capacity_factor: float = 1.25,
                 aux_weight: float = 1e-2, dispatch: str = "auto"):
        super().__init__(model, name, inputs)
        self.num_experts = num_experts
        self.hidden_dim = hidden_dim
        self.k = min(k, num_experts)
        self.capacity_factor = capacity_factor
        self.aux_weight = aux_weight
        if dispatch not in ("auto", "dense", "sort"):
            raise ValueError(f"dispatch must be auto|dense|sort, got {dispatch!r}")
        self.dispatch = dispatch
        self.dim = inputs[0].dims[-1]
        ntokens = 1
        for s in inputs[0].dims[:-1]:
            ntokens *= s
        self.capacity = max(
            1, int(capacity_factor * ntokens * self.k / num_experts))
        self.finalize()

    def output_shapes(self):
        return ([self.inputs[0].dims, ()],
                [self.inputs[0].dtype, DataType.DT_FLOAT])

    def weights(self) -> List[WeightSpec]:
        E, D, F = self.num_experts, self.dim, self.hidden_dim
        return [
            WeightSpec("router", (D, E), init="glorot", fan=(D, E)),
            WeightSpec("w_in", (E, D, F), init="glorot", fan=(D, F)),
            WeightSpec("w_out", (E, F, D), init="glorot", fan=(F, D)),
        ]

    def _use_sort_dispatch(self) -> bool:
        if self.dispatch != "auto":
            return self.dispatch == "sort"
        mesh = getattr(self.model, "mesh", None)
        # same condition as weight_partition: dense pays off only when the
        # experts actually shard over the 'expert' axis (all-to-all lowering)
        ep = (mesh is not None and "expert" in getattr(mesh, "axis_names", ())
              and mesh.shape["expert"] > 1
              and self.num_experts % mesh.shape["expert"] == 0)
        return not ep

    def forward(self, params, xs, *, training=False, rng=None,
                capacity=None):
        """`capacity` overrides the build-time training capacity. The
        inference path (runtime/generation.py) passes N (the slab's token
        count): a token never picks the same expert twice, so per-expert
        assignments are <= N and C=N guarantees ZERO drops — standard
        inference semantics, and the row-independence the decode path
        promises (a row's output can never depend on other rows through
        capacity competition)."""
        x = xs[0]
        orig_shape = x.shape
        D, E = self.dim, self.num_experts
        t = x.reshape(-1, D)  # (N, D)
        N = t.shape[0]
        C = capacity if capacity is not None else self.capacity

        logits = t @ params["router"].astype(t.dtype)       # (N, E)
        gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

        if self._use_sort_dispatch():
            return self._forward_sort(params, t, gates, orig_shape,
                                      capacity=C)

        # top-k routing with capacity (GShard): iteratively take the best
        # expert per token, mask, repeat k times
        combine = jnp.zeros((N, E, C), jnp.float32)
        remaining = gates
        aux_me = jnp.mean(gates, axis=0)                    # (E,)
        ce = jnp.zeros((E,), jnp.float32)
        slots_used = jnp.zeros((E,), jnp.float32)  # carried across k rounds so
        # round r's assignments start after round r-1's (distinct slots, total
        # capacity C per expert — not C per round)
        for _ in range(self.k):
            choice = jnp.argmax(remaining, axis=-1)          # (N,)
            onehot = jax.nn.one_hot(choice, E, dtype=jnp.float32)
            ce = ce + jnp.mean(onehot, axis=0)
            pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # rank in round
            pos_in_e = (jnp.sum(pos, axis=-1)
                        + jnp.sum(onehot * slots_used, axis=-1)).astype(jnp.int32)
            fits = (pos_in_e < C).astype(jnp.float32)
            keep = fits * jnp.max(onehot * remaining, axis=-1)  # gate value
            slot = jax.nn.one_hot(jnp.clip(pos_in_e, 0, C - 1), C,
                                  dtype=jnp.float32)
            combine = combine + keep[:, None, None] * onehot[:, :, None] \
                * slot[:, None, :]
            slots_used = slots_used + jnp.sum(onehot * fits[:, None], axis=0)
            remaining = remaining * (1.0 - onehot)

        # renormalize kept gates over selected experts
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = jnp.where(denom > 0, combine / jnp.maximum(denom, 1e-9),
                            combine)
        dispatch = (combine > 0).astype(t.dtype)             # (N, E, C)

        expert_in = jnp.einsum("nec,nd->ecd", dispatch, t)   # (E, C, D)
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in,
                                   params["w_in"].astype(t.dtype)))
        expert_out = jnp.einsum("ecf,efd->ecd", h,
                                params["w_out"].astype(t.dtype))  # (E, C, D)
        y = jnp.einsum("nec,ecd->nd", combine.astype(t.dtype), expert_out)

        # load-balancing aux loss: E * sum(mean_gate * mean_assignment)
        aux = self.aux_weight * E * jnp.sum(aux_me * (ce / self.k))
        return [y.reshape(orig_shape), aux.astype(jnp.float32)]

    def _forward_sort(self, params, t, gates, orig_shape, capacity):
        """Sort-based dispatch: O(N*k) routing state. Token assignments are
        ordered round-major (all round-0 picks first, in token order) so
        capacity drops match the dense path's position rule exactly.
        `capacity` is resolved by forward() — the single resolution site."""
        D, E, k = self.dim, self.num_experts, self.k
        C = capacity
        N = t.shape[0]

        topk_gates, topk_idx = jax.lax.top_k(gates, k)      # (N, k)
        flat_e = topk_idx.T.reshape(-1)                     # (k*N,) round-major
        flat_g = topk_gates.T.reshape(-1)

        order = jnp.argsort(flat_e)                         # stable
        sorted_e = flat_e[order]
        counts = jnp.bincount(flat_e, length=E)             # (E,)
        starts = jnp.cumsum(counts) - counts
        rank = jnp.arange(k * N) - starts[sorted_e]         # pos within expert
        keep = (rank < C).astype(jnp.float32)
        dest = sorted_e * C + jnp.clip(rank, 0, C - 1)      # (k*N,)
        token = order % N                                   # round-major flatten
        gate = flat_g[order] * keep

        # renormalize kept gates over each token's surviving experts
        denom = jnp.zeros((N,), jnp.float32).at[token].add(gate)
        gate = gate / jnp.maximum(denom[token], 1e-9)

        # gather tokens into the expert buffer (each kept assignment owns a
        # distinct slot; dropped ones contribute zero to a clipped slot)
        buf = jnp.zeros((E * C, D), t.dtype)
        buf = buf.at[dest].add(t[token] * keep[:, None].astype(t.dtype))
        expert_in = buf.reshape(E, C, D)
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in,
                                   params["w_in"].astype(t.dtype)))
        expert_out = jnp.einsum("ecf,efd->ecd", h,
                                params["w_out"].astype(t.dtype))
        flat_out = expert_out.reshape(E * C, D)
        y = jnp.zeros((N, D), t.dtype).at[token].add(
            flat_out[dest] * gate[:, None].astype(t.dtype))

        me = jnp.mean(gates, axis=0)
        ce = counts.astype(jnp.float32) / N
        aux = self.aux_weight * E * jnp.sum(me * (ce / k))
        return [y.reshape(orig_shape), aux.astype(jnp.float32)]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims - 1))

    def expert_parallel_size(self):
        return self.num_experts

    def weight_partition(self, axis_map):
        from flexflow_tpu.parallel.pconfig import EXPERT

        # searched expert parallelism: any axis the strategy mapped to the
        # EXPERT sentinel shards the expert dim of w_in/w_out
        eaxes = [ax for ax, d in (axis_map or {}).items() if d == EXPERT]
        if not eaxes:
            # legacy convention: shard over a literal 'expert' mesh axis if
            # present, regardless of activation sharding
            mesh_axes = getattr(self.model, "mesh", None)
            if (mesh_axes is not None
                    and "expert" in getattr(mesh_axes, "axis_names", ())
                    and mesh_axes.shape["expert"] > 1
                    and self.num_experts % mesh_axes.shape["expert"] == 0):
                eaxes = ["expert"]
        e = None if not eaxes else (eaxes[0] if len(eaxes) == 1
                                    else tuple(eaxes))
        return {
            "router": P(None, None),
            "w_in": P(e, None, None),
            "w_out": P(e, None, None),
        }

    def flops(self):
        ntokens = self.inputs[0].volume() // self.dim
        return 2 * 2 * ntokens * self.k * self.dim * self.hidden_dim

    def input_axis_map(self, axis_map, input_idx):
        # negative sentinels (CONTRACT/STAGE/EXPERT) must not leak into the
        # input map: the input arrives replicated over those axes
        ndims = self.inputs[input_idx].num_dims
        return {ax: (d if d is not None and 0 <= d < ndims - 1 else None)
                for ax, d in (axis_map or {}).items()}
