"""Pallas TPU kernels.

Hand-tiled kernels for ops where XLA's default lowering leaves MXU/VMEM
performance on the table (the role src/ops/*.cu kernels played in the
reference; role parity with the tuned cuDNN MHA kernel the reference calls
at attention.cu:244). Currently: flash attention forward (online softmax)
and the FlashAttention-2 style backward (logsumexp saved from the forward;
per-tile recompute of the probs; separate dq and dk/dv kernels so each
output tile is written once).

Streaming design (round-3 rework): the opposing sequence is NOT staged in
VMEM. Every kernel runs on a 3-D grid (batch*heads, own-side blocks,
opposing-side blocks) whose innermost axis streams opposing-side tiles
through VMEM while f32 scratch accumulators (persistent across the
sequential inner grid axis) carry the online-softmax / gradient state.
VMEM use is therefore O(block^2) regardless of sequence length — the 4k
sequence cap of the staged round-2 kernels is gone.

On CPU (tests/emulated meshes) kernels run with interpret=True.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# m/l scratch rows are stored broadcast across one f32 lane tile
LANES = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# jax renamed TPUCompilerParams -> CompilerParams across 0.4/0.5; resolve
# whichever this build ships (interpret mode never constructs one, which is
# why the old hard reference compiled everywhere CI runs but would have
# broken on a real-TPU 0.4.37 build)
_COMPILER_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams", None)


def _compiler_params(semantics=("parallel", "parallel", "arbitrary")):
    """Outer grid axes are parallel (independent (bh, own-block) tiles); the
    innermost axis streams opposing-side tiles and must run sequentially —
    the scratch accumulators carry state across it."""
    if _interpret() or _COMPILER_PARAMS_CLS is None:
        return None
    return _COMPILER_PARAMS_CLS(dimension_semantics=semantics)


def _pick_block(seq: int, want: int) -> int:
    """Largest tile size <= want that divides seq (the guard in
    attention._flash_ok only promises 128-divisibility, so a 512 default
    must degrade for e.g. seq 640). This is the STATIC heuristic — the
    cold fallback when the measured-cost autotune table
    (search/kernel_tune.py) has no entry for the shape."""
    for b in (want, 256, 128, 64, 32, 16, 8):
        if b <= seq and seq % b == 0:
            return b
    return seq


def _resolve_blocks(kernel: str, sq: int, sk: int, d: int, dtype,
                    want_q, want_k, *, batch: int = 1, heads: int = 1,
                    causal: bool = True):
    """(block_q, block_k) for a flash kernel call. want_q/want_k = None
    (the public API's default) means AUTO: the measured-cost autotune
    table (search/kernel_tune.py, keyed by kernel/shape incl. dtype,
    batch, heads, causality/device kind/jax version) wins when it has a
    legal entry for this exact configuration, else the static
    _pick_block heuristic from the 512 default (legality and hit/miss
    accounting live in lookup_blocks). Explicit wants (the tuner's own
    candidate sweep, callers pinning a block) bypass the table
    entirely. Round-5 context: the static 512 default lost to XLA at
    h4096 — a tuned table turns that into a re-measurable decision
    instead of a hardcoded loss. Resolution happens at TRACE time
    (shapes are static), so a warm program pays nothing."""
    if want_q is None and want_k is None:
        from flexflow_tpu.search import kernel_tune

        hit = kernel_tune.lookup_blocks(kernel, seq_q=sq, seq_k=sk,
                                        head_dim=d, dtype=dtype,
                                        batch=batch, heads=heads,
                                        causal=causal)
        if hit is not None:
            return hit
        want_q = want_k = 512
    return (_pick_block(sq, want_q if want_q is not None else 512),
            _pick_block(sk, want_k if want_k is not None else 512))


def _maybe_when(cond, fn):
    """Run fn under pl.when(cond), or directly when the guard is statically
    always-true (non-causal paths) — no branch emitted in the kernel."""
    if cond is None:
        fn()
    else:
        pl.when(cond)(fn)


def _causal_mask(s, qi, ki, block_q, block_k, offset):
    """Causal mask with the cross-attention diagonal offset: row q attends
    k_pos <= q_pos + offset, offset = sk - sq (bottom-right alignment, the
    same convention as the einsum path's tril(k=sk-sq) — reference vendor
    kernel handled distinct q/kv lengths, attention.cu:533-570). offset is
    a static python int; offset=0 is plain self-attention causality."""
    bq, bk = s.shape
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_pos + offset >= k_pos, s, NEG_INF)


# ---------------------------------------------------------------- forward


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, block_q: int,
                      block_k: int, causal: bool, scale: float,
                      need_lse: bool, offset: int = 0):
    if need_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: a k tile strictly after the (offset-shifted) last row of this
    # q tile is dead
    live = (qi + 1) * block_q + offset > ki * block_k if causal else None

    def _step():
        q = q_ref[0]  # (block_q, d) — native dtype into the MXU (bf16 fast
        # path; accumulation stays f32 via preferred_element_type)
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        m_prev = m_scr[:, 0:1]                      # (bq, 1)
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                      # (bq, bk)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    _maybe_when(live, _step)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, 0:1]
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        if need_lse:
            # lse lives in an 8-lane padded layout: Mosaic wants the last two
            # block dims divisible by (8, 128) OR equal to the array dims, and
            # a last dim of exactly 8 satisfies the 'equal' clause at 16x less
            # HBM than padding to a full 128-lane tile
            m = m_scr[:, 0:1]
            lse_ref[0] = jnp.broadcast_to(m + jnp.log(l),
                                          (q_ref.shape[1], 8))


def flash_attention_fwd_pallas(q, k, v, causal: bool, scale: float,
                               block_q: Optional[int] = None,
                               block_k: Optional[int] = None,
                               need_lse: bool = True):
    """q,k,v: (B, S, H, D) -> (out, lse|None).
    Grid: (B*H, S_q/block_q, S_k/block_k) — K/V tiles stream through the
    innermost axis. block_q/block_k default to AUTO (the kernel_tune
    table, static 512-down heuristic cold); explicit values pin the tile
    (degraded to a divisor of seq) and skip the table. need_lse=False
    (inference) skips materializing the logsumexp residual — it exists
    only for the VJP and costs more HBM writes than the output itself at
    small head dims."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q, block_k = _resolve_blocks("flash_fwd", sq, sk, d, q.dtype,
                                       block_q, block_k, batch=b,
                                       heads=h, causal=causal)
    assert sq % block_q == 0 and sk % block_k == 0
    # cross-attention diagonal offset (bottom-right aligned causality);
    # sq > sk with causal would leave the first rows keyless (0/0 in the
    # online softmax) — refused upstream in attention._flash_ok
    offset = sk - sq
    assert not (causal and offset < 0), "causal flash needs sq <= sk"

    # (B, S, H, D) -> (B*H, S, D)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    kernel = functools.partial(_flash_fwd_kernel, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale,
                               need_lse=need_lse, offset=offset)
    if causal:
        # clamp dead (fully-masked) inner steps to the last live tile: the
        # revisited block is already VMEM-resident, so masked steps cost no
        # DMA (pl.when(live) already skips their compute)
        def kv_map(i, j, t):
            return (i, jnp.minimum(
                t, ((j + 1) * block_q - 1 + offset) // block_k), 0)
    else:
        def kv_map(i, j, t):
            return (i, t, 0)
    out_specs = [pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0))]
    out_shape = [jax.ShapeDtypeStruct((b * h, sq, d), q.dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec((1, block_q, 8),
                                      lambda i, j, t: (i, j, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b * h, sq, 8), jnp.float32))
    outs = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running sum
            pltpu.VMEM((block_q, d), jnp.float32),       # output accumulator
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(qt, kt, vt)
    return (outs[0], outs[1]) if need_lse else (outs[0], None)


# ---------------------------------------------------------------- backward


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, block_q: int, block_k: int,
                         causal: bool, scale: float, offset: int = 0):
    """One q tile, k/v tiles streaming: dq = scale * sum_j ds_j @ k_j,
    ds = p * (do @ v^T - delta)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = (qi + 1) * block_q + offset > ki * block_k if causal else None

    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0:1]     # (block_q, 1) — lane-padded layout
        delta = delta_ref[0, :, 0:1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        p = jnp.exp(s - lse)                                # (bq, bk)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_scr[...] = dq_scr[...] + jnp.dot(
            ds, k, preferred_element_type=jnp.float32)

    _maybe_when(live, _step)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *, block_q: int,
                          block_k: int, causal: bool, scale: float,
                          offset: int = 0):
    """One k tile, q/do tiles streaming:
    dv = sum_i p_i^T @ do_i; dk = scale * sum_i ds_i^T @ q_i."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # causal: a q tile strictly before the (offset-shifted) first row of
    # this k tile sees nothing of it
    live = (qi + 1) * block_q + offset > ki * block_k if causal else None

    def _step():
        k = k_ref[0]   # (block_k, d)
        v = v_ref[0]
        q = q_ref[0]   # (block_q, d)
        do = do_ref[0]
        lse = lse_ref[0, :, 0:1]
        delta = delta_ref[0, :, 0:1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        p = jnp.exp(s - lse)                               # (bq, bk)
        dv_scr[...] = dv_scr[...] + jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_scr[...] = dk_scr[...] + jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32)

    _maybe_when(live, _step)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def flash_attention_bwd_pallas(q, k, v, o, lse, do, causal: bool,
                               scale: float, block_q: Optional[int] = None,
                               block_k: Optional[int] = None, dlse=None,
                               delta_precomputed=None):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q, block_k = _resolve_blocks("flash_bwd", sq, sk, d, q.dtype,
                                       block_q, block_k, batch=b,
                                       heads=h, causal=causal)
    assert sq % block_q == 0 and sk % block_k == 0
    offset = sk - sq
    assert not (causal and offset < 0), "causal flash needs sq <= sk"

    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    dot = do.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    ot = o.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    # delta_i = rowsum(do_i * o_i) — the softmax-normalization term of ds;
    # an lse cotangent (if the lse output is ever differentiated) folds in
    # as ds = p * (dp - delta + dlse), i.e. delta -= dlse. Loop callers
    # (the ring backward) pass delta_precomputed to hoist this out of their
    # scan body.
    if delta_precomputed is not None:
        delta = delta_precomputed.reshape(b * h, sq).astype(jnp.float32)
    else:
        delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                        axis=-1)
    if dlse is not None:
        delta = delta - dlse.reshape(b * h, sq).astype(jnp.float32)
    # broadcast into the same 8-lane padded layout as lse
    delta = jnp.broadcast_to(delta[..., None], (b * h, sq, 8))

    if causal:
        # dead-tile clamps (see forward): masked inner steps re-reference a
        # resident block instead of fetching one
        def kv_map(i, j, t):
            return (i, jnp.minimum(
                t, ((j + 1) * block_q - 1 + offset) // block_k), 0)

        def q_map(i, j, t):
            # first q tile whose last row reaches this k tile: q_pos >=
            # j*block_k - offset (floor div handles the negative numerator)
            return (i, jnp.maximum(t, (j * block_k - offset) // block_q), 0)
    else:
        def kv_map(i, j, t):
            return (i, t, 0)

        q_map = kv_map

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale,
                          offset=offset),
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_q, 8), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_q, 8), lambda i, j, t: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(qt, kt, vt, dot, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale,
                          offset=offset),
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_k, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_q, 8), q_map),
            pl.BlockSpec((1, block_q, 8), q_map),
        ],
        out_specs=[pl.BlockSpec((1, block_k, d), lambda i, j, t: (i, j, 0)),
                   pl.BlockSpec((1, block_k, d), lambda i, j, t: (i, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(qt, kt, vt, dot, lse, delta)

    def back(x, s):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    return back(dq, sq), back(dk, sk), back(dv, sk)


# ----------------------------------------------------- fused add+layernorm


def _add_ln_fwd_kernel(x_ref, r_ref, scale_ref, bias_ref, s_ref, y_ref,
                       *stat_refs, eps: float, need_stats: bool):
    x = x_ref[...]
    r = r_ref[...]
    s = x + r                                   # residual stream out
    sf = s.astype(jnp.float32)
    mean = jnp.mean(sf, axis=-1, keepdims=True)          # (bn, 1)
    # two-pass variance: E[(s-mean)^2], not E[s^2]-mean^2 — the one-pass
    # form catastrophically cancels in f32 when the row mean dwarfs its
    # spread (large residual streams in deep nets). The row is already in
    # registers, so the second pass costs no HBM traffic
    centered = sf - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = centered * rstd * scale_ref[...] + bias_ref[...]
    s_ref[...] = s
    y_ref[...] = y.astype(y_ref.dtype)
    if need_stats:
        mean_ref, rstd_ref = stat_refs
        bn = x.shape[0]
        mean_ref[...] = jnp.broadcast_to(mean, (bn, 8))
        rstd_ref[...] = jnp.broadcast_to(rstd, (bn, 8))


def fused_add_layernorm_fwd_pallas(x, r, scale, bias, eps: float,
                                   block_n: int = 256,
                                   need_stats: bool = True):
    """(N, D) x + r -> (s, ln(s)) in ONE HBM pass (the unfused graph writes
    s, re-reads it for the norm, and re-reads it again on the next block's
    residual path). need_stats=False (inference / no-grad primal) skips
    materializing the (N, 8) mean/rstd residuals, which exist only for the
    VJP — same pattern as the flash kernel's need_lse."""
    n, d = x.shape
    block_n = _pick_block(n, block_n)
    grid = (n // block_n,)
    scale2 = scale.reshape(1, d)
    bias2 = bias.reshape(1, d)
    out_specs = [pl.BlockSpec((block_n, d), lambda i: (i, 0)),
                 pl.BlockSpec((block_n, d), lambda i: (i, 0))]
    out_shape = [jax.ShapeDtypeStruct((n, d), x.dtype),
                 jax.ShapeDtypeStruct((n, d), x.dtype)]
    if need_stats:
        out_specs += [pl.BlockSpec((block_n, 8), lambda i: (i, 0)),
                      pl.BlockSpec((block_n, 8), lambda i: (i, 0))]
        out_shape += [jax.ShapeDtypeStruct((n, 8), jnp.float32),
                      jax.ShapeDtypeStruct((n, 8), jnp.float32)]
    outs = pl.pallas_call(
        functools.partial(_add_ln_fwd_kernel, eps=eps,
                          need_stats=need_stats),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i: (i, 0)),
            pl.BlockSpec((block_n, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(x, r, scale2, bias2)
    if need_stats:
        return outs
    return outs[0], outs[1], None, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_add_layernorm(x, r, scale, bias, eps: float = 1e-5):
    """(s, y) = (x + r, layernorm(x + r) * scale + bias) fused: the residual
    add never round-trips HBM before the norm reads it. Backward is pure
    JAX (bandwidth-bound elementwise+reduce; XLA fuses it well)."""
    s, y, _, _ = fused_add_layernorm_fwd_pallas(x, r, scale, bias, eps,
                                                need_stats=False)
    return s, y


def _add_ln_fwd_rule(x, r, scale, bias, eps):
    s, y, mean, rstd = fused_add_layernorm_fwd_pallas(x, r, scale, bias, eps)
    return (s, y), (s, mean[:, 0:1], rstd[:, 0:1], scale)


def _add_ln_bwd_rule(eps, res, g):
    s, mean, rstd, scale = res
    gs, gy = g
    sf = s.astype(jnp.float32)
    gyf = gy.astype(jnp.float32)
    xhat = (sf - mean) * rstd
    dbias = jnp.sum(gyf, axis=0).astype(scale.dtype)
    dscale = jnp.sum(gyf * xhat, axis=0).astype(scale.dtype)
    t = gyf * scale.astype(jnp.float32)
    dsn = (t - jnp.mean(t, axis=-1, keepdims=True)
           - xhat * jnp.mean(t * xhat, axis=-1, keepdims=True)) * rstd
    d = (dsn + gs.astype(jnp.float32)).astype(s.dtype)
    return d, d, dscale, dbias


fused_add_layernorm.defvjp(_add_ln_fwd_rule, _add_ln_bwd_rule)


# ------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Flash attention: Pallas forward + FlashAttention-2 Pallas backward
    (logsumexp residual; per-tile prob recompute; no S x S materialization
    in either direction)."""
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, _ = flash_attention_fwd_pallas(q, k, v, causal, s, need_lse=False)
    b, sq, h, d = q.shape
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _flash_fwd_rule(q, k, v, causal, scale):
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, lse = flash_attention_fwd_pallas(q, k, v, causal, s)
    b, sq, h, d = q.shape
    o = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, scale, res, g):
    q, k, v, o, lse = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    dq, dk, dv = flash_attention_bwd_pallas(q, k, v, o, lse, g, causal, s)
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ----------------------------------------------------- paged attention
#
# Serving-side decode/verify attention over the paged KV pool
# (runtime/serving.py). The einsum path reassembles the ENTIRE pool into a
# dense (B, max_len, KVH, Dh) logical cache with ck[page_table].reshape(...)
# on every step — an HBM round-trip that grows with POOL size, not with the
# tokens a slot actually holds. This kernel does the page-table lookup
# inside the grid instead (scalar prefetch: the table is in SMEM before the
# body runs, and each inner step's BlockSpec index_map picks the slot's
# t-th pool page directly), so only the slot's LIVE pages —
# ceil((max(write_pos)+1)/page_size) of them — ever stream through VMEM,
# with an online-softmax accumulator carrying state across the page axis.
# The Flex-TPU analogue (PAPERS.md 2407.08700): keep the data resident in
# the compute unit; don't materialize the logical view in HBM.
#
# One kernel serves both serving shapes: S=1 is the continuous-batching
# decode step, S=K+1 the speculative-verify slab (per-position write
# frontiers). The live rule is exactly the einsum path's:
#   j < row_len  OR  prompt_pad <= j <= write_pos[b, i]
# and GQA grouping matches _grouped_cache_attention (query head h reads kv
# head h // group). The einsum page-gather stays as the parity oracle
# (tests/test_pallas_paged.py).


def _paged_attn_kernel(pt_ref, lp_ref, wp_ref, rl_ref, pp_ref, *rest,
                       s: int, kvh: int, grp: int, ps: int, scale: float,
                       quantized: bool = False):
    """One (slot, page) grid step: score the slot's (S, H, Dh) query slab
    against this page's (ps, KVH, Dh) k/v and fold into the running
    online softmax. Scalar-prefetch refs: page table (B, P), last live
    page (B,), per-position write frontier (B, S), row_len (B,),
    prompt_pad (B,) — and, for a quantized pool, the per-(pool page,
    kv head) f32 k/v scales (P_pool, KVH): the quantized payload streams
    through VMEM and dequantizes HERE, against the scalar-prefetched
    scale of the pool page this grid step fetched — the full-width KV
    never exists in HBM (the Flex-TPU keep-it-resident rule applied to
    quantization). Scratch rows are kv-head-major: row
    kh*(S*G) + i*G + g accumulates query head kh*G+g at slab position i."""
    if quantized:
        ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref, \
            m_scr, l_scr, acc_scr = rest
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    t = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(t == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # pages past the slot's write frontier are dead: skip their compute
    # (the index_map already clamps their DMA to the resident last live
    # page, so a dead step costs nothing — same trick as the causal
    # clamp in the flash kernels)
    @pl.when(t <= lp_ref[b])
    def _step():
        q = q_ref[0]                                # (S, H, Dqk)
        k = k_ref[0]                                # (ps, KVH, Dqk)
        v = v_ref[0]                                # (ps, KVH, Dv)
        rl = rl_ref[b]
        pp = pp_ref[b]
        # the pool page this step's k/v block came from (same lookup as
        # kv_map's clamped DMA) — indexes the scale rows when quantized
        pg = pt_ref[b, jnp.minimum(t, lp_ref[b])]
        # live mask rows in (slab position, group) order — each slab
        # position i attends at its OWN frontier wp[b, i], which gives
        # in-slab causality for the verify slab (position i's window
        # holds exactly the slab writes <= i plus committed history)
        rows = []
        for i in range(s):
            j = t * ps + jax.lax.broadcasted_iota(jnp.int32, (1, ps), 1)
            live = (j < rl) | ((j >= pp) & (j <= wp_ref[b, i]))
            rows.append(jnp.broadcast_to(live, (grp, ps)))
        live = jnp.concatenate(rows, axis=0)        # (S*G, ps)
        for kh in range(kvh):
            sl = slice(kh * s * grp, (kh + 1) * s * grp)
            qk = q[:, kh * grp:(kh + 1) * grp, :].reshape(s * grp, -1)
            kk = k[:, kh, :]                        # (ps, Dqk)
            vv = v[:, kh, :]                        # (ps, Dv)
            if quantized:
                # in-VMEM dequant: one scalar per (page, head), read
                # from SMEM — the int8/fp8 tile was the only HBM read
                kk = kk.astype(jnp.float32) * ks_ref[pg, kh]
                vv = vv.astype(jnp.float32) * vs_ref[pg, kh]
            elif kk.dtype != q.dtype:
                # mixed-width pool (kv_cache_dtype='bf16' under f32
                # compute): upcast in VMEM so the probs matmul runs at
                # query precision, matching the einsum oracle's cast
                kk = kk.astype(q.dtype)
                vv = vv.astype(q.dtype)
            sc = jnp.dot(qk, kk.T,
                         preferred_element_type=jnp.float32) * scale
            sc = jnp.where(live, sc, NEG_INF)
            m_prev = m_scr[sl, 0:1]
            l_prev = l_scr[sl, 0:1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(sc, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(sc - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[sl, :] = acc_scr[sl, :] * alpha + jnp.dot(
                p.astype(vv.dtype), vv,
                preferred_element_type=jnp.float32)
            m_scr[sl, :] = jnp.broadcast_to(m_new, (s * grp, LANES))
            l_scr[sl, :] = jnp.broadcast_to(l_new, (s * grp, LANES))

    @pl.when(t == nt - 1)
    def _finish():
        # every slab row has >= 1 live position (its own write frontier:
        # prompt_pad <= write_pos always holds, and the inactive-slot
        # zeros satisfy j == 0 <= write_pos == 0), so l > 0 — no guard
        for kh in range(kvh):
            sl = slice(kh * s * grp, (kh + 1) * s * grp)
            o = acc_scr[sl, :] / l_scr[sl, 0:1]
            o_ref[0, :, kh * grp:(kh + 1) * grp, :] = \
                o.reshape(s, grp, -1).astype(o_ref.dtype)


def paged_attention_fwd_pallas(q, k_pages, v_pages, page_table, write_pos,
                               row_len, prompt_pad, scale: float,
                               k_scales=None, v_scales=None,
                               interpret: Optional[bool] = None):
    """Paged-pool attention: q (B, S, H, Dqk) against k_pages/v_pages
    ((P_pool, page_size, KVH, D)) through per-slot page tables
    ((B, pages_per_slot) int32) -> (B, S, H, Dv) context.

    write_pos (B, S) int32 is each slab position's logical write
    frontier (host-clamped, nondecreasing over S); row_len / prompt_pad
    (B,) the ragged-prompt live-rule bounds. Grid is (slots, pages_per_
    slot) with the page axis sequential; pages past a slot's frontier
    are skipped (clamped DMA + pl.when), so the per-step HBM traffic is
    the slot's LIVE pages, not the pool. Inference-only: no VJP (the
    serving engine never differentiates through decode).

    ``k_scales``/``v_scales`` ((P_pool, KVH) f32, both or neither) mark
    a QUANTIZED pool (int8/fp8 payload, ISSUE 11): they ride the
    scalar-prefetch stream into SMEM next to the page table, and each
    grid step dequantizes its VMEM-resident tile against its own page's
    scale before the score/context matmuls — per-page HBM traffic is
    the quantized bytes, and the full-width KV is never materialized
    anywhere. The einsum page-gather path applies the same dequant
    after its gather, staying the parity oracle.

    `interpret` defaults to the module rule (interpret off-TPU), which
    is how FFConfig.paged_attention_impl='pallas' executes the REAL
    kernel code path in every CPU CI tier."""
    b, s, h, dqk = q.shape
    ps, kvh = k_pages.shape[1], k_pages.shape[2]
    dv = v_pages.shape[3]
    assert h % kvh == 0, f"heads {h} not a multiple of kv heads {kvh}"
    assert (k_scales is None) == (v_scales is None), \
        "quantized pools carry BOTH k and v scales"
    quantized = k_scales is not None
    grp = h // kvh
    pps = page_table.shape[1]
    # last live page per slot: the live rule's bound is max(write
    # frontier, prompt tail) — a serving dispatch always has write_pos
    # >= prompt_pad >= row_len, but the kernel honors the FULL rule so
    # a direct caller querying inside the prompt (write_pos < row_len)
    # still streams the prompt's pages. The slab's max frontier is its
    # final position's (host-built nondecreasing; jnp.max guards the
    # clamp-equal tail anyway).
    last_idx = jnp.maximum(jnp.max(write_pos, axis=1), row_len - 1)
    last_page = (last_idx // ps).astype(jnp.int32)

    # extra trailing prefetch refs (the quantized scales) ride into the
    # index maps as *_ — the maps only ever read the table + last page
    def q_map(bi, t, pt, lp, *_):
        return (bi, 0, 0, 0)

    def kv_map(bi, t, pt, lp, *_):
        # the paged lookup: this grid step's k/v block IS pool page
        # page_table[slot, t], fetched straight from HBM — dead steps
        # (t past the frontier) clamp to the already-resident last live
        # page so they trigger no DMA
        return (pt[bi, jnp.minimum(t, lp[bi])], 0, 0, 0)

    prefetch = [page_table.astype(jnp.int32), last_page,
                write_pos.astype(jnp.int32), row_len.astype(jnp.int32),
                prompt_pad.astype(jnp.int32)]
    if quantized:
        prefetch += [k_scales.astype(jnp.float32),
                     v_scales.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(b, pps),
        in_specs=[
            pl.BlockSpec((1, s, h, dqk), q_map),
            pl.BlockSpec((1, ps, kvh, dqk), kv_map),
            pl.BlockSpec((1, ps, kvh, dv), kv_map),
        ],
        out_specs=pl.BlockSpec((1, s, h, dv), q_map),
        scratch_shapes=[
            pltpu.VMEM((s * h, LANES), jnp.float32),   # running max
            pltpu.VMEM((s * h, LANES), jnp.float32),   # running sum
            pltpu.VMEM((s * h, dv), jnp.float32),      # ctx accumulator
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_attn_kernel, s=s, kvh=kvh, grp=grp,
                          ps=ps, scale=scale, quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s, h, dv), q.dtype),
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        interpret=_interpret() if interpret is None else interpret,
    )(*prefetch, q, k_pages, v_pages)


def paged_prefill_write_pallas(cache, kh, vh, pages,
                               interpret: Optional[bool] = None):
    """Prefill/append page scatter: write a (1, S, KVH, D) KV slab into
    the paged pool page-at-a-time from VMEM (ISSUE 18 tentpole (c)).

    The einsum oracle (attention.paged_prefill_write) materializes the
    page-reshaped slab and issues one big ``pool.at[pages].set`` —
    XLA's scatter lowering stages the whole slab through HBM. Here the
    grid is (n_pages,): each step DMAs ONE page-sized slab tile into
    VMEM and writes it (quantizing in-register when the pool is
    int8/fp8) to its pool page, so peak on-chip footprint is one page
    regardless of prompt length. ``pages`` rides the scalar-prefetch
    stream and drives the output index map — the paged-pool idiom of
    paged_attention_fwd_pallas, pointed at the write path.

    The pool (and, when quantized, the per-page scale planes) are
    aliased input->output so untouched pages survive: the grid only
    visits the scatter list, and every non-visited output block must
    retain the incoming pool bytes. Alias indices count the scalar-
    prefetch operand (pallas initializes outputs from the FULL operand
    list, prefetch included).

    Quantized pools recompute attention.page_scale / page_quantize
    inside the kernel via the imported helpers themselves — elementwise
    f32 ops, so interpret mode is BITWISE against the einsum oracle and
    the PR 11 published-state contract (scales + payload) holds.

    `interpret` defaults to the module rule (interpret off-TPU), which
    is how FFConfig.paged_attention_impl='pallas' executes the real
    kernel code path in every CPU CI tier. Returns a new cache dict
    with the k/v pools (and scales) replaced."""
    from flexflow_tpu.ops.attention import (page_quantize, page_scale,
                                            storage_qmax)

    pool_k, pool_v = cache["k"], cache["v"]
    ps, kvh = pool_k.shape[1], pool_k.shape[2]
    dk, dv = pool_k.shape[3], pool_v.shape[3]
    n_pages = len(pages)
    quantized = "k_scale" in cache
    qmax = storage_qmax(pool_k.dtype) if quantized else 0.0

    def paged(x, d):
        # identical host-side prep to the einsum oracle: pad the slab
        # tail to a page boundary, reshape to page-major tiles
        s = x.shape[1]
        pad = n_pages * ps - s
        x = x[0]
        if pad:
            x = jnp.pad(x, ((0, pad), (0, 0), (0, 0)))
        return x.reshape(n_pages, ps, kvh, d)

    kp = paged(kh, dk)
    vp = paged(vh, dv)
    pages = jnp.asarray(pages, jnp.int32)

    def slab_map(t, pages_ref):
        return (t, 0, 0, 0)

    def pool_map(t, pages_ref):
        return (pages_ref[t], 0, 0, 0)

    def scale_map(t, pages_ref):
        return (pages_ref[t], 0)

    def kernel(pages_ref, *refs):
        if quantized:
            (kp_ref, vp_ref, _pk, _pv, _ks, _vs,
             pk_out, pv_out, ks_out, vs_out) = refs
            for x_ref, p_out, s_out in ((kp_ref, pk_out, ks_out),
                                        (vp_ref, pv_out, vs_out)):
                pf = x_ref[...].astype(jnp.float32)   # (1, ps, kvh, d)
                scale = page_scale(pf, qmax)          # (1, kvh)
                p_out[...] = page_quantize(pf, scale, qmax, p_out.dtype)
                s_out[...] = scale
        else:
            kp_ref, vp_ref, _pk, _pv, pk_out, pv_out = refs
            pk_out[...] = kp_ref[...].astype(pk_out.dtype)
            pv_out[...] = vp_ref[...].astype(pv_out.dtype)

    in_specs = [
        pl.BlockSpec((1, ps, kvh, dk), slab_map),
        pl.BlockSpec((1, ps, kvh, dv), slab_map),
        pl.BlockSpec((1, ps, kvh, dk), pool_map),
        pl.BlockSpec((1, ps, kvh, dv), pool_map),
    ]
    out_specs = [
        pl.BlockSpec((1, ps, kvh, dk), pool_map),
        pl.BlockSpec((1, ps, kvh, dv), pool_map),
    ]
    out_shape = [jax.ShapeDtypeStruct(pool_k.shape, pool_k.dtype),
                 jax.ShapeDtypeStruct(pool_v.shape, pool_v.dtype)]
    inputs = [kp, vp, pool_k, pool_v]
    # alias index = position in (prefetch + inputs); output index is
    # positional in out_shape
    aliases = {3: 0, 4: 1}
    if quantized:
        ksc, vsc = cache["k_scale"], cache["v_scale"]
        in_specs += [pl.BlockSpec((1, kvh), scale_map),
                     pl.BlockSpec((1, kvh), scale_map)]
        out_specs += [pl.BlockSpec((1, kvh), scale_map),
                      pl.BlockSpec((1, kvh), scale_map)]
        out_shape += [jax.ShapeDtypeStruct(ksc.shape, ksc.dtype),
                      jax.ShapeDtypeStruct(vsc.shape, vsc.dtype)]
        inputs += [ksc, vsc]
        aliases = {3: 0, 4: 1, 5: 2, 6: 3}

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_pages,),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        compiler_params=_compiler_params(("arbitrary",)),
        interpret=_interpret() if interpret is None else interpret,
    )(pages, *inputs)
    out = dict(cache)
    out["k"], out["v"] = outs[0], outs[1]
    if quantized:
        out["k_scale"], out["v_scale"] = outs[2], outs[3]
    return out
