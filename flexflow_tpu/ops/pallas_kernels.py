"""Pallas TPU kernels.

Hand-tiled kernels for ops where XLA's default lowering leaves MXU/VMEM
performance on the table (the role src/ops/*.cu kernels played in the
reference). Currently: flash attention forward (online softmax, q-block grid,
k-block inner loop in VMEM) with a recompute-based custom VJP that reuses the
pure-JAX blockwise path for the backward.

On CPU (tests/emulated meshes) kernels run with interpret=True.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                      causal: bool, scale: float, q_block: int, seq_k: int):
    qi = pl.program_id(1)  # q block index
    q = q_ref[0].astype(jnp.float32)  # (block_q, d)
    bq, d = q.shape
    nk = seq_k // block_k

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    o0 = jnp.zeros((bq, d), jnp.float32)

    def body(j, carry):
        m, l, o = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[:, None] + jnp.dot(p, v,
                                             preferred_element_type=jnp.float32)
        return m_new, l_new, o_new

    m, l, o = jax.lax.fori_loop(0, nk, body, (m0, l0, o0))
    o_ref[0] = (o / l[:, None]).astype(o_ref.dtype)


def flash_attention_fwd_pallas(q, k, v, causal: bool, scale: float,
                               block_q: int = 128, block_k: int = 128):
    """q,k,v: (B, S, H, D) -> (B, S, H, D). Grid: (B*H, S_q/block_q)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0

    # (B, S, H, D) -> (B*H, S, D)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale, q_block=block_q,
                               seq_k=sk)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=_interpret(),
    )(qt, kt, vt)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Flash attention with Pallas forward and recompute backward.

    The backward pass re-runs the memory-efficient blockwise recurrence under
    jax.vjp (FLOPs-for-memory trade, same spirit as jax.checkpoint)."""
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return flash_attention_fwd_pallas(q, k, v, causal, s)


def _flash_fwd_rule(q, k, v, causal, scale):
    out = flash_attention(q, k, v, causal, scale)
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, res, g):
    from flexflow_tpu.parallel.ring_attention import blockwise_attention

    q, k, v = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=causal,
                                               scale=s), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
