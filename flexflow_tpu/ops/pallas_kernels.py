"""Pallas TPU kernels.

Hand-tiled kernels for ops where XLA's default lowering leaves MXU/VMEM
performance on the table (the role src/ops/*.cu kernels played in the
reference; role parity with the tuned cuDNN MHA kernel the reference calls
at attention.cu:244). Currently: flash attention forward (online softmax,
q-block grid, k-block inner loop in VMEM) and the FlashAttention-2 style
backward (logsumexp saved from the forward; per-tile recompute of the probs;
separate dq and dk/dv kernels so each output tile is written once).

On CPU (tests/emulated meshes) kernels run with interpret=True.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(seq: int, want: int) -> int:
    """Largest tile size <= want that divides seq (the guard in
    attention._flash_ok only promises 128-divisibility, so a 512 default
    must degrade for e.g. seq 640). Long sequences also shrink the tile to
    reduce the block_q x block_k fp32 intermediates — a partial mitigation
    only: the backward kernels stage the FULL opposing sequence in VMEM
    regardless of tile size, so the hard sequence cap lives in
    attention.FLASH_MAX_SEQ (dense path) and in ring_attention's per-shard
    use_flash gate, both of which route oversized sequences to the pure-JAX
    blockwise path instead."""
    if seq > 4096:
        want = min(want, 256)
    for b in (want, 256, 128, 64, 32, 16, 8):
        if b <= seq and seq % b == 0:
            return b
    return seq


# ---------------------------------------------------------------- forward


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *,
                      block_k: int, causal: bool, scale: float, q_block: int,
                      seq_k: int, need_lse: bool = True):
    qi = pl.program_id(1)  # q block index
    q = q_ref[0]  # (block_q, d) — native dtype into the MXU (bf16 fast path;
    # accumulation stays f32 via preferred_element_type)
    bq, d = q.shape
    nk = seq_k // block_k

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    o0 = jnp.zeros((bq, d), jnp.float32)

    def body(j, carry):
        m, l, o = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[:, None] + jnp.dot(p.astype(v.dtype), v,
                                             preferred_element_type=jnp.float32)
        return m_new, l_new, o_new

    if causal:
        # only k blocks at or before this q block contribute
        nk_eff = jnp.minimum(nk, (qi + 1) * q_block // block_k
                             + (1 if q_block % block_k else 0))
    else:
        nk_eff = nk
    m, l, o = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, o0))
    o_ref[0] = (o / l[:, None]).astype(o_ref.dtype)
    if need_lse:
        # lse lives in an 8-lane padded layout: Mosaic wants the last two
        # block dims divisible by (8, 128) OR equal to the array dims, and
        # a last dim of exactly 8 satisfies the 'equal' clause at 16x less
        # HBM than padding to a full 128-lane tile
        lse_ref[0] = jnp.broadcast_to((m + jnp.log(l))[:, None], (bq, 8))


def flash_attention_fwd_pallas(q, k, v, causal: bool, scale: float,
                               block_q: int = 512, block_k: int = 512,
                               need_lse: bool = True):
    """q,k,v: (B, S, H, D) -> (out, lse|None). Grid: (B*H, S_q/block_q).
    need_lse=False (inference) skips materializing the logsumexp residual —
    it exists only for the VJP and costs more HBM writes than the output
    itself at small head dims."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    assert sq % block_q == 0 and sk % block_k == 0

    # (B, S, H, D) -> (B*H, S, D)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale, q_block=block_q,
                               seq_k=sk, need_lse=need_lse)
    out_specs = [pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0))]
    out_shape = [jax.ShapeDtypeStruct((b * h, sq, d), q.dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec((1, block_q, 8),
                                      lambda i, j: (i, j, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b * h, sq, 8), jnp.float32))
    outs = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(qt, kt, vt)
    return (outs[0], outs[1]) if need_lse else (outs[0], None)


# ---------------------------------------------------------------- backward


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool, scale: float,
                         q_block: int, seq_k: int):
    """One q tile: dq = scale * sum_j ds_j @ k_j,
    ds = p * (do @ v^T - delta)."""
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0]    # (block_q,) — lane-padded layout
    delta = delta_ref[0, :, 0]
    bq, d = q.shape
    nk = seq_k // block_k

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                       # (bq, bk)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(k.dtype)
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    if causal:
        nk_eff = jnp.minimum(nk, (qi + 1) * q_block // block_k
                             + (1 if q_block % block_k else 0))
    else:
        nk_eff = nk
    dq = jax.lax.fori_loop(0, nk_eff, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          scale: float, k_block: int, seq_q: int):
    """One k tile: dv = sum_i p_i^T @ do_i; dk = scale * sum_i ds_i^T @ q_i."""
    ki = pl.program_id(1)
    k = k_ref[0]   # (block_k, d)
    v = v_ref[0]
    bk, d = k.shape
    nq = seq_q // block_q

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_pos = ki * k_block + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                      # (bq, bk)
        dv = dv + jnp.dot(p.astype(do.dtype).T, do,
                          preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(q.dtype)
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # q blocks strictly before this k tile see nothing of it
        i0 = (ki * k_block) // block_q
    else:
        i0 = 0
    dk, dv = jax.lax.fori_loop(i0, nq, body,
                               (jnp.zeros((bk, d), jnp.float32),
                                jnp.zeros((bk, d), jnp.float32)))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def flash_attention_bwd_pallas(q, k, v, o, lse, do, causal: bool,
                               scale: float, block_q: int = 512,
                               block_k: int = 512, dlse=None,
                               delta_precomputed=None):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    assert sq % block_q == 0 and sk % block_k == 0

    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    dot = do.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    ot = o.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    # delta_i = rowsum(do_i * o_i) — the softmax-normalization term of ds;
    # an lse cotangent (if the lse output is ever differentiated) folds in
    # as ds = p * (dp - delta + dlse), i.e. delta -= dlse. Loop callers
    # (the ring backward) pass delta_precomputed to hoist this out of their
    # scan body.
    if delta_precomputed is not None:
        delta = delta_precomputed.reshape(b * h, sq).astype(jnp.float32)
    else:
        delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                        axis=-1)
    if dlse is not None:
        delta = delta - dlse.reshape(b * h, sq).astype(jnp.float32)
    # broadcast into the same 8-lane padded layout as lse
    delta = jnp.broadcast_to(delta[..., None], (b * h, sq, 8))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale, q_block=block_q,
                          seq_k=sk),
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 8), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 8), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=_interpret(),
    )(qt, kt, vt, dot, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale, k_block=block_k,
                          seq_q=sq),
        grid=(b * h, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sq, 8), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sq, 8), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
                   pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk, d), v.dtype)],
        interpret=_interpret(),
    )(qt, kt, vt, dot, lse, delta)

    def back(x, s):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    return back(dq, sq), back(dk, sk), back(dv, sk)


# ------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Flash attention: Pallas forward + FlashAttention-2 Pallas backward
    (logsumexp residual; per-tile prob recompute; no S x S materialization
    in either direction)."""
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, _ = flash_attention_fwd_pallas(q, k, v, causal, s, need_lse=False)
    b, sq, h, d = q.shape
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _flash_fwd_rule(q, k, v, causal, scale):
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, lse = flash_attention_fwd_pallas(q, k, v, causal, s)
    b, sq, h, d = q.shape
    o = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, scale, res, g):
    q, k, v, o, lse = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    dq, dk, dv = flash_attention_bwd_pallas(q, k, v, o, lse, g, causal, s)
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
