"""Per-slot sampling for the fixed-shape serving programs (ISSUE 14).

The serving engine runs ONE compiled slot-decode program for its whole
life; per-request sampling configs therefore cannot be trace-time
constants (a program per temperature would recompile per tenant). This
module makes sampling *data*: temperature / top-p / top-k / seed ride
the dispatch as per-slot scalar arrays — exactly like ``write_pos`` —
and every function here is shape-stable in the slot dimension, so N
tenants with N different sampling configs share one XLA program.

Counter-based RNG: a request's sample stream is a pure function of
``(seed, stream tag, draw index)`` — ``fold_in(fold_in(PRNGKey(seed),
tag), index)`` — never of the engine's key state, the slot index, or
the replica. Draw index = the position of the token being sampled
(``len(request.tokens)`` at dispatch), so a request replayed after
failover resubmission, or admitted into a different slot, reproduces
its stream bit-for-bit. Four independent streams per request:

  TAG_TARGET   — the non-speculative sampler's token draws (draw i
                 samples token i; the prefill's first token is draw 0)
  TAG_DRAFT    — the draft model's proposal draws under speculation
  TAG_ACCEPT   — the rejection-sampling accept uniforms (host rule)
  TAG_RESAMPLE — the residual re-draw after a rejection (in-graph)

Greedy is the ``temperature == 0`` degenerate case, not a separate
program: rows with temperature 0 return ``argmax(logits)`` computed
exactly as the pre-sampling greedy path did (f32 cast then argmax), so
greedy streams are bitwise-identical to a greedy-only engine.

Warping semantics (shared by the sampler and ``sampling_probs`` — the
rejection-sampling accept rule depends on the two agreeing): logits are
divided by temperature, then the top-k and top-p keep-sets are computed
independently on that warped distribution and intersected; the top-1
token always survives. The sampling distribution is the softmax over
the surviving logits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# stream tags (fold_in domain separators): see module docstring
TAG_TARGET = 1
TAG_DRAFT = 2
TAG_ACCEPT = 3
TAG_RESAMPLE = 4


def validate_sampling(temperature, top_p, top_k, where: str = "sampling"):
    """Shared host-side validation (FFConfig, engine, router, submit):
    temperature >= 0 (0 = greedy), 0 < top_p <= 1 (1 = off),
    top_k >= 0 (0 = off)."""
    t = float(temperature)
    p = float(top_p)
    k = int(top_k)
    if not t >= 0.0:        # catches NaN too
        raise ValueError(
            f"{where}: temperature={temperature}: must be >= 0 "
            f"(0 = greedy argmax)")
    if not (0.0 < p <= 1.0):
        raise ValueError(
            f"{where}: top_p={top_p}: must be in (0, 1] "
            f"(1 = no nucleus filter)")
    if k < 0:
        raise ValueError(
            f"{where}: top_k={top_k}: must be >= 0 (0 = no top-k filter)")
    return t, p, k


def slot_keys(seeds, counters, tag: int):
    """(B,) seeds + (B,) draw indices -> (B, 2) uint32 PRNG keys on the
    ``tag`` stream. Pure per-row: row b's key depends only on
    (seeds[b], tag, counters[b])."""

    def one(s, c):
        k = jax.random.PRNGKey(s)
        k = jax.random.fold_in(k, tag)
        return jax.random.fold_in(k, c)

    return jax.vmap(one)(jnp.asarray(seeds, jnp.int32),
                         jnp.asarray(counters, jnp.int32))


def _masked_warped(logits, temps, top_ps, top_ks):
    """(B, V) f32 masked warped logits for the temperature>0 rows (rows
    with temperature 0 are resolved by the callers via argmax). The
    surviving set is (top-k keep) AND (top-p keep), computed on the
    warped distribution; rank 0 always survives."""
    logits = logits.astype(jnp.float32)
    temps = temps.astype(jnp.float32)
    safe_t = jnp.where(temps > 0.0, temps, 1.0)[:, None]
    warped = logits / safe_t
    # rank every vocab position by warped value (jnp.argsort is stable,
    # so ties break by vocab index — the lax.top_k order)
    order = jnp.argsort(-warped, axis=-1)
    ranks = jnp.argsort(order, axis=-1)
    k = jnp.asarray(top_ks, jnp.int32)[:, None]
    keep_k = (k <= 0) | (ranks < k)
    probs = jax.nn.softmax(warped, axis=-1)
    sorted_probs = jnp.take_along_axis(probs, order, axis=-1)
    csum = jnp.cumsum(sorted_probs, axis=-1)
    # keep sorted position j iff the mass strictly BEFORE it is < top_p:
    # the smallest prefix reaching top_p survives, rank 0 always does
    keep_sorted = (csum - sorted_probs) < top_ps.astype(jnp.float32)[:, None]
    keep_p = jnp.take_along_axis(keep_sorted, ranks, axis=-1)
    keep = keep_k & keep_p
    return jnp.where(keep, warped, -jnp.inf)


def sampling_probs(logits, temps, top_ps, top_ks):
    """The per-row sampling distribution as (B, V) f32 probabilities —
    the operand of the rejection-sampling accept rule (``p`` for the
    target, ``q`` for the draft). Rows with temperature 0 are the
    degenerate one-hot at argmax (their "distribution" is the greedy
    choice)."""
    logits = logits.astype(jnp.float32)
    masked = _masked_warped(logits, temps, top_ps, top_ks)
    probs = jax.nn.softmax(masked, axis=-1)
    greedy = jax.nn.one_hot(jnp.argmax(logits, axis=-1),
                            logits.shape[-1], dtype=jnp.float32)
    return jnp.where((temps > 0.0)[:, None], probs, greedy)


def sample_tokens(logits, temps, top_ps, top_ks, seeds, counters,
                  tag: int = TAG_TARGET):
    """One token per row from the warped distribution; (B,) int32.
    temperature-0 rows take ``argmax(f32(logits))`` — bitwise the
    pre-sampling greedy decode. Draw b is a pure function of
    (seeds[b], tag, counters[b]): slot- and replica-invariant."""
    logits = logits.astype(jnp.float32)
    temps = jnp.asarray(temps, jnp.float32)
    masked = _masked_warped(logits, temps, top_ps, top_ks)
    keys = slot_keys(seeds, counters, tag)
    sampled = jax.vmap(
        lambda k, row: jax.random.categorical(k, row))(keys, masked)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)


def accept_uniforms(seeds, counters, k: int):
    """(B, k) accept-rule uniforms: row b, proposal i draws from the
    ACCEPT stream at index counters[b] + i. The host compares
    ``u * q(d) <= p(d)`` — accept with probability min(1, p/q)."""
    seeds = jnp.asarray(seeds, jnp.int32)
    counters = jnp.asarray(counters, jnp.int32)

    def one(s, c):
        def per_i(i):
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(s), TAG_ACCEPT),
                c + i)
            return jax.random.uniform(key, ())

        return jax.vmap(per_i)(jnp.arange(k, dtype=jnp.int32))

    return jax.vmap(one)(seeds, counters)


def residual_sample(p, q, seeds, counters):
    """The in-graph rejection re-draw: sample from the residual
    distribution ``norm(max(p - q, 0))`` — what makes accept/resample
    speculation distribution-identical to sampling from ``p`` directly.
    ``p``/``q`` are (B, V) sampling distributions (the draft's q is all
    zeros for the bonus position after a fully accepted window, so the
    residual degenerates to ``p`` itself). A numerically-empty residual
    (q >= p everywhere — only reachable when p == q up to float error,
    where rejection has probability ~0) falls back to ``p``. Draws ride
    the RESAMPLE stream at the emitting token's index."""
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    r = jnp.maximum(p - q, 0.0)
    norm = jnp.sum(r, axis=-1, keepdims=True)
    dist = jnp.where(norm > 1e-12, r / jnp.maximum(norm, 1e-12), p)
    keys = slot_keys(seeds, counters, TAG_RESAMPLE)
    logits = jnp.log(jnp.maximum(dist, 1e-38))
    return jax.vmap(
        lambda k, row: jax.random.categorical(k, row))(keys, logits
                                                       ).astype(jnp.int32)
