"""Softmax, Dropout, LayerNorm, RMSNorm.

Reference: src/ops/softmax.cu (cuDNN softmax, sample-parallel only),
src/ops/dropout.cu (cuDNN dropout w/ reserve space). LayerNorm/RMSNorm are
net-new ops the reference lacks (its Transformer example builds LN from
primitives); first-class here because every modern transformer needs them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import Op, WeightSpec


class Softmax(Op):
    op_type = OperatorType.OP_SOFTMAX

    def __init__(self, model, name, inputs, axis: int = -1):
        super().__init__(model, name, inputs)
        self.axis = axis
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [jax.nn.softmax(xs[0], axis=self.axis)]

    def partitionable_output_dims(self):
        nd = self.outputs[0].num_dims
        ax = self.axis % nd
        return [i for i in range(nd) if i != ax]

    def flops(self):
        return 5 * self.outputs[0].volume()


class Dropout(Op):
    op_type = OperatorType.OP_DROPOUT
    needs_rng = True

    def __init__(self, model, name, inputs, rate: float, seed: int = 0):
        super().__init__(model, name, inputs)
        self.rate = rate
        self.seed = seed
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]
        if not training or self.rate <= 0.0:
            return [x]
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return [jnp.where(mask, x / keep, 0.0)]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims))

    def flops(self):
        return self.outputs[0].volume()


class LayerNorm(Op):
    op_type = OperatorType.OP_LAYERNORM

    def __init__(self, model, name, inputs, eps: float = 1e-5,
                 elementwise_affine: bool = True):
        super().__init__(model, name, inputs)
        self.eps = eps
        self.affine = elementwise_affine
        self.dim = inputs[0].dims[-1]
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def weights(self):
        if not self.affine:
            return []
        return [WeightSpec("scale", (self.dim,), init="one"),
                WeightSpec("bias", (self.dim,), init="zero")]

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            y = y * params["scale"] + params["bias"]
        return [y]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims - 1))

    def flops(self):
        return 8 * self.outputs[0].volume()


class RMSNorm(Op):
    op_type = OperatorType.OP_RMSNORM

    def __init__(self, model, name, inputs, eps: float = 1e-6):
        super().__init__(model, name, inputs)
        self.eps = eps
        self.dim = inputs[0].dims[-1]
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def weights(self):
        return [WeightSpec("scale", (self.dim,), init="one")]

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]
        ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        return [x * jax.lax.rsqrt(ms + self.eps) * params["scale"]]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims - 1))

    def flops(self):
        return 4 * self.outputs[0].volume()
