"""Softmax, Dropout, LayerNorm, RMSNorm.

Reference: src/ops/softmax.cu (cuDNN softmax, sample-parallel only),
src/ops/dropout.cu (cuDNN dropout w/ reserve space). LayerNorm/RMSNorm are
net-new ops the reference lacks (its Transformer example builds LN from
primitives); first-class here because every modern transformer needs them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import Op, WeightSpec


class Softmax(Op):
    op_type = OperatorType.OP_SOFTMAX

    def __init__(self, model, name, inputs, axis: int = -1):
        super().__init__(model, name, inputs)
        self.axis = axis
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [jax.nn.softmax(xs[0], axis=self.axis)]

    def partitionable_output_dims(self):
        nd = self.outputs[0].num_dims
        ax = self.axis % nd
        return [i for i in range(nd) if i != ax]

    def flops(self):
        return 5 * self.outputs[0].volume()


class Dropout(Op):
    op_type = OperatorType.OP_DROPOUT
    needs_rng = True

    def __init__(self, model, name, inputs, rate: float, seed: int = 0):
        super().__init__(model, name, inputs)
        self.rate = rate
        self.seed = seed
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]
        if not training or self.rate <= 0.0:
            return [x]
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return [jnp.where(mask, x / keep, 0.0)]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims))

    def flops(self):
        return self.outputs[0].volume()


class LayerNorm(Op):
    op_type = OperatorType.OP_LAYERNORM

    def __init__(self, model, name, inputs, eps: float = 1e-5,
                 elementwise_affine: bool = True):
        super().__init__(model, name, inputs)
        self.eps = eps
        self.affine = elementwise_affine
        self.dim = inputs[0].dims[-1]
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def weights(self):
        if not self.affine:
            return []
        return [WeightSpec("scale", (self.dim,), init="one"),
                WeightSpec("bias", (self.dim,), init="zero")]

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            y = y * params["scale"] + params["bias"]
        return [y]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims - 1))

    def flops(self):
        return 8 * self.outputs[0].volume()


class AddLayerNorm(Op):
    """Fused residual-add + LayerNorm: (s, y) = (x + r, LN(x + r)).

    The unfused graph writes the sum to HBM, re-reads it for the norm, and
    re-reads it again on the next residual hop; the fused op makes one pass
    (Pallas kernel on TPU, plain JAX elsewhere — XLA fuses the fallback
    too, so numerics are identical everywhere). Net-new op, same rationale
    as LayerNorm; enabled in the transformer blocks by
    FFConfig.use_fused_ln."""

    op_type = OperatorType.OP_LAYERNORM
    wants_shard_ctx = True  # per-shard kernel under sharding (see forward)

    def __init__(self, model, name, inputs, eps: float = 1e-5):
        super().__init__(model, name, inputs)
        self.eps = eps
        self.dim = inputs[0].dims[-1]
        assert inputs[0].dims == inputs[1].dims, \
            f"{name}: add_layer_norm inputs must agree, got " \
            f"{inputs[0].dims} vs {inputs[1].dims}"
        self.finalize()

    def output_shapes(self):
        d = self.inputs[0].dims
        t = self.inputs[0].dtype
        return [d, d], [t, t]

    def weights(self):
        return [WeightSpec("scale", (self.dim,), init="one"),
                WeightSpec("bias", (self.dim,), init="zero")]

    def _fused_ok(self) -> bool:
        """Kernel eligibility, mirroring attention._flash_ok: lane-aligned
        hidden dim, kill switch (FF_FUSED_LN_DISABLE=1) for deployments
        whose Mosaic build rejects a shape — ineligible shapes fall back to
        the plain-JAX branch, never fail to compile."""
        import os

        if os.environ.get("FF_FUSED_LN_DISABLE") == "1":
            return False
        if self.dim % 128 != 0:
            return False
        return (jax.default_backend() == "tpu"
                or os.environ.get("FF_FORCE_FLASH_ATTENTION") == "1")

    def forward(self, params, xs, *, training=False, rng=None,
                shard_ctx=None):
        x, r = xs[0], xs[1]
        scale, bias = params["scale"], params["bias"]
        if self._fused_ok():
            from flexflow_tpu.ops.pallas_kernels import fused_add_layernorm

            def run(x_, r_, scale_, bias_):
                shape = x_.shape
                s2, y2 = fused_add_layernorm(
                    x_.reshape(-1, self.dim), r_.reshape(-1, self.dim),
                    scale_, bias_, self.eps)
                return s2.reshape(shape), y2.reshape(shape)

            # a pallas_call is a Mosaic custom call GSPMD cannot partition:
            # under a sharded strategy run the kernel per-shard inside
            # shard_map over whichever sharded non-last dims divide evenly
            # (same pattern as attention._flash_dense); the op is row-wise,
            # so shards need no collectives
            mesh = (shard_ctx or {}).get("mesh")
            if mesh is not None:
                from jax.sharding import PartitionSpec as P

                from flexflow_tpu.parallel import (shard_entries,
                                                   shard_map_compat)

                axis_map = (shard_ctx or {}).get("axis_map") or {}
                ent = shard_entries(mesh, axis_map, x.shape,
                                    range(x.ndim - 1))
                entries = [ent[d] for d in range(x.ndim - 1)]
                if any(e is not None for e in entries):
                    spec = P(*entries, None)
                    w_spec = P(None)
                    s2, y2 = shard_map_compat(
                        run, mesh, (spec, spec, w_spec, w_spec),
                        (spec, spec))(x, r, scale, bias)
                    return [s2, y2]
            s2, y2 = run(x, r, scale, bias)
            return [s2, y2]
        s = x + r
        # f32 stats like the Pallas kernel, so bf16 numerics validated on
        # the fallback transfer to the TPU path
        sf = s.astype(jnp.float32)
        mean = jnp.mean(sf, axis=-1, keepdims=True)
        var = jnp.var(sf, axis=-1, keepdims=True)
        y = ((sf - mean) * jax.lax.rsqrt(var + self.eps)
             * scale.astype(jnp.float32) + bias.astype(jnp.float32))
        return [s, y.astype(s.dtype)]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims - 1))

    def flops(self):
        return 9 * self.outputs[0].volume()


class RMSNorm(Op):
    op_type = OperatorType.OP_RMSNORM

    def __init__(self, model, name, inputs, eps: float = 1e-6):
        super().__init__(model, name, inputs)
        self.eps = eps
        self.dim = inputs[0].dims[-1]
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def weights(self):
        return [WeightSpec("scale", (self.dim,), init="one")]

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]
        ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        return [x * jax.lax.rsqrt(ms + self.eps) * params["scale"]]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims - 1))

    def flops(self):
        return 4 * self.outputs[0].volume()
