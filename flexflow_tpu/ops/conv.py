"""Conv2D, Pool2D, BatchNorm, Flat.

Reference: src/ops/conv_2d.cu (cuDNN conv + algo search, 4D sample+spatial
partitioning), src/ops/pool_2d.cu, src/ops/batch_norm.cu, src/ops/flat.cu.

TPU re-design: user-facing tensors are NCHW to match the reference API
(conv_2d.cu ctor signature), but convs execute via lax.conv_general_dilated
with explicit dimension_numbers — XLA picks the MXU-friendly internal layout.
Spatial (attribute) parallelism = shard H/W dims; XLA GSPMD inserts halo
exchange automatically, replacing the reference's implicit Legion region
intersections (simulator.cc:360-380 costs them explicitly).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from flexflow_tpu.ffconst import ActiMode, DataType, OperatorType, PoolType
from flexflow_tpu.ops.base import Op, WeightSpec
from flexflow_tpu.ops.dense import apply_activation


class Conv2D(Op):
    op_type = OperatorType.OP_CONV2D

    def __init__(self, model, name, inputs, out_channels: int,
                 kernel_h: int, kernel_w: int, stride_h: int, stride_w: int,
                 padding_h: int, padding_w: int,
                 activation: ActiMode = ActiMode.AC_MODE_NONE,
                 groups: int = 1, use_bias: bool = True):
        super().__init__(model, name, inputs)
        self.out_channels = out_channels
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.padding = (padding_h, padding_w)
        self.activation = activation
        self.groups = groups
        self.use_bias = use_bias
        self.in_channels = inputs[0].dims[1]
        self.finalize()

    def output_shapes(self):
        n, c, h, w = self.inputs[0].dims
        oh = (h + 2 * self.padding[0] - self.kernel[0]) // self.stride[0] + 1
        ow = (w + 2 * self.padding[1] - self.kernel[1]) // self.stride[1] + 1
        return [(n, self.out_channels, oh, ow)], [self.inputs[0].dtype]

    def weights(self) -> List[WeightSpec]:
        kh, kw = self.kernel
        cin_g = self.in_channels // self.groups
        fan_in = cin_g * kh * kw
        fan_out = (self.out_channels // self.groups) * kh * kw
        ws = [WeightSpec("kernel", (self.out_channels, cin_g, kh, kw),
                         init="glorot", fan=(fan_in, fan_out))]
        if self.use_bias:
            ws.append(WeightSpec("bias", (self.out_channels,), init="zero"))
        return ws

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]
        y = lax.conv_general_dilated(
            x, params["kernel"],
            window_strides=self.stride,
            padding=[(self.padding[0], self.padding[0]),
                     (self.padding[1], self.padding[1])],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.groups,
            preferred_element_type=x.dtype,
        )
        if self.use_bias:
            y = y + params["bias"][None, :, None, None]
        return [apply_activation(y, self.activation)]

    _contracted_output_dims = (1,)  # out-channel comes from the kernel

    def partitionable_output_dims(self):
        return [0, 1, 2, 3]  # sample, out-channel(param), H, W (attribute)

    def contract_size(self):
        # row-parallel conv: kernel sharded on its INPUT-channel dim, input
        # sharded on C, output psum-replicated (the Megatron pair for CNNs:
        # an out-channel-sharded producer feeds this with no resharding)
        return self.in_channels if self.groups == 1 else None

    def weight_partition(self, axis_map):
        from flexflow_tpu.parallel.pconfig import CONTRACT

        ax = self.axes_for_dim(axis_map, 1)
        cax = self.axes_for_dim(axis_map, CONTRACT)
        out = {"kernel": P(ax, cax, None, None)}
        if self.use_bias:
            out["bias"] = P(ax)
        return out

    def contract_input_dim(self, input_idx):
        return 1  # input channel dim

    def flops(self):
        n, c, oh, ow = self.outputs[0].dims
        kh, kw = self.kernel
        return 2 * n * c * oh * ow * (self.in_channels // self.groups) * kh * kw


class Pool2D(Op):
    op_type = OperatorType.OP_POOL2D

    def __init__(self, model, name, inputs, kernel_h, kernel_w,
                 stride_h, stride_w, padding_h, padding_w,
                 pool_type: PoolType = PoolType.POOL_MAX,
                 activation: ActiMode = ActiMode.AC_MODE_NONE):
        super().__init__(model, name, inputs)
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.padding = (padding_h, padding_w)
        self.pool_type = pool_type
        self.activation = activation
        self.finalize()

    def output_shapes(self):
        n, c, h, w = self.inputs[0].dims
        oh = (h + 2 * self.padding[0] - self.kernel[0]) // self.stride[0] + 1
        ow = (w + 2 * self.padding[1] - self.kernel[1]) // self.stride[1] + 1
        if oh < 1 or ow < 1:
            raise ValueError(
                f"{self.name}: pool2d kernel {self.kernel} stride "
                f"{self.stride} padding {self.padding} on a {h}x{w} input "
                f"yields an empty {oh}x{ow} output — shrink the kernel or "
                f"the stride")
        return [(n, c, oh, ow)], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]
        kh, kw = self.kernel
        window = (1, 1, kh, kw)
        strides = (1, 1) + self.stride
        pads = ((0, 0), (0, 0),
                (self.padding[0], self.padding[0]),
                (self.padding[1], self.padding[1]))
        if self.pool_type == PoolType.POOL_MAX:
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            y = s / (kh * kw)
        return [apply_activation(y, self.activation)]

    def partitionable_output_dims(self):
        return [0, 1, 2, 3]

    def flops(self):
        return int(np.prod(self.outputs[0].dims)) * self.kernel[0] * self.kernel[1]


class BatchNorm(Op):
    """BatchNorm2D over NCHW with running stats (reference: batch_norm.cu,
    cuDNN BN; scale init to one / bias to zero via BATCHNORM_INIT_PARA task)."""

    op_type = OperatorType.OP_BATCHNORM
    stateful = True

    def __init__(self, model, name, inputs, relu: bool = True,
                 momentum: float = 0.9, eps: float = 1e-5):
        super().__init__(model, name, inputs)
        self.relu = relu
        self.momentum = momentum
        self.eps = eps
        self.channels = inputs[0].dims[1]
        self.finalize()

    def output_shapes(self):
        return [self.inputs[0].dims], [self.inputs[0].dtype]

    def weights(self):
        return [WeightSpec("scale", (self.channels,), init="one"),
                WeightSpec("bias", (self.channels,), init="zero")]

    def init_state(self):
        return {"mean": np.zeros((self.channels,), np.float32),
                "var": np.ones((self.channels,), np.float32)}

    def init_state_for_shapes(self, in_shapes):
        c = in_shapes[0][1]  # per-shard channel count
        return {"mean": np.zeros((c,), np.float32),
                "var": np.ones((c,), np.float32)}

    def forward_stateful(self, params, state, xs, *, training=False, rng=None):
        x = xs[0]
        if training:
            # batch stats over N,H,W — under data parallelism GSPMD turns these
            # means into cross-replica psums (i.e. sync BN for free)
            mean = jnp.mean(x, axis=(0, 2, 3))
            var = jnp.var(x, axis=(0, 2, 3))
            new_state = {
                "mean": self.momentum * state["mean"] + (1 - self.momentum) * mean,
                "var": self.momentum * state["var"] + (1 - self.momentum) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps) * params["scale"]
        y = (x - mean[None, :, None, None]) * inv[None, :, None, None] \
            + params["bias"][None, :, None, None]
        if self.relu:
            y = jax.nn.relu(y)
        return [y], new_state

    def partitionable_output_dims(self):
        # channel (dim 1) shards cleanly: BN statistics reduce over N,H,W
        # only, so per-channel mean/var/scale/bias stay local to the shard —
        # this lets a channel-sharded conv feed BN without an all-gather
        return [0, 1, 2, 3]

    def weight_partition(self, axis_map):
        ax = self.axes_for_dim(axis_map, 1)
        return {"scale": P(ax), "bias": P(ax)}


class Flat(Op):
    op_type = OperatorType.OP_FLAT

    def __init__(self, model, name, inputs):
        super().__init__(model, name, inputs)
        self.finalize()

    def output_shapes(self):
        d = self.inputs[0].dims
        return [(d[0], int(np.prod(d[1:])))], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [xs[0].reshape(xs[0].shape[0], -1)]

    def flops(self):
        return 0
