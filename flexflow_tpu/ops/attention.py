"""MultiHeadAttention.

Reference: src/ops/attention.cu (745 LoC, cuDNN cudnnMultiHeadAttnForward;
partitioning asserted batch-only at attention.cu:118-120).

TPU re-design supersedes that restriction: attention here is partitionable on
batch, heads ('model' axis — Megatron-style), and sequence ('seq' axis — ring
attention, flexflow_tpu/parallel/ring_attention.py). The dense path uses the
hand-tiled Pallas flash kernel (ops/pallas_kernels.py) when the backend is TPU
and the block grid divides the sequence (_flash_ok), falling back to an
einsum-built softmax that XLA fuses; the ring/Ulysses SP lowering is selected
when the strategy shards `seq`.

API parity: FFModel.multihead_attention mirrors flexflow_c.h's
flexflow_model_add_multihead_attention signature.
"""

from __future__ import annotations

import functools
import math
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import Op, WeightSpec

# past this sequence length, the non-flash dense path (CPU backend, attention
# dropout, mismatched head dims) switches from the fused einsum to the
# pure-JAX blockwise online-softmax scan — an einsum would materialize the
# S x S probability tensor. The Pallas flash kernels themselves stream K/V
# tiles through the grid (round-3 rework) and have NO sequence cap: VMEM use
# is O(block^2) regardless of S.
BLOCKWISE_SEQ_THRESHOLD = 4096


def resolve_paged_attention_impl(impl=None, config=None) -> str:
    """Resolve an ``auto|pallas|einsum`` request (per-engine override
    first, then FFConfig.paged_attention_impl) to the concrete decode
    attention path:

      * ``pallas`` — the paged-attention kernel (ops/pallas_kernels.py
        paged_attention_fwd_pallas): page-table lookup inside the grid,
        only a slot's live pages stream through VMEM. Off-TPU it runs in
        interpret mode, so forcing it executes the REAL kernel code path
        in every CPU CI tier.
      * ``einsum`` — the page-gather + grouped einsum path, bitwise the
        dense-cache attention: the parity oracle, and the default where
        no native Mosaic backend exists.
      * ``auto`` — pallas on a TPU backend, einsum elsewhere.
    """
    if impl in (None, "", "auto"):
        impl = getattr(config, "paged_attention_impl", "auto") or "auto"
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "einsum"
    if impl not in ("pallas", "einsum"):
        raise ValueError(
            f"paged_attention_impl={impl!r}: must be 'auto', 'pallas' "
            f"or 'einsum'")
    return impl


#: quantized KV-page storage (ISSUE 11): the paged pool stores int8 or
#: fp8 payload with one f32 scale per (page, kv head), so each page holds
#: 2-4x more tokens per HBM byte — the allocator, COW rule, radix trie
#: and router affinity are page-granular and never look inside a page.
#: Dequantization happens where the data is consumed (inside the Pallas
#: kernel's VMEM tiles, or fused into the einsum gather); wide KV is
#: never materialized in HBM.


def kv_storage_dtype(kv_dtype):
    """Resolve an FFConfig.kv_cache_dtype value to ``(storage_dtype,
    qmax)``. ``(None, None)`` = native (the compute dtype); a non-None
    dtype with ``qmax=None`` (bf16) is a plain cast — no scales; a qmax
    means symmetric scale quantization with per-page-per-head scales.
    Raises on unknown values and on 'fp8' under a jax build without
    ``jnp.float8_e4m3fn`` (the no-new-deps gate: fail loudly at engine
    construction, never on a silent fallback)."""
    if kv_dtype in (None, "", "native"):
        return None, None
    if kv_dtype in ("bf16", "bfloat16"):
        return jnp.bfloat16, None
    if kv_dtype == "int8":
        return jnp.int8, 127.0
    if kv_dtype == "fp8":
        fp8 = getattr(jnp, "float8_e4m3fn", None)
        if fp8 is None:
            raise ValueError(
                "kv_cache_dtype='fp8' needs a jax build with "
                "jnp.float8_e4m3fn; this build lacks it — use 'int8'")
        return fp8, float(jnp.finfo(fp8).max)
    raise ValueError(
        f"kv_cache_dtype={kv_dtype!r}: must be 'native', 'bf16', "
        f"'int8' or 'fp8'")


def storage_qmax(dtype) -> float:
    """The symmetric quantization ceiling of a storage dtype (127 for
    int8, finfo.max for fp8)."""
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        return float(jnp.iinfo(dtype).max)
    return float(jnp.finfo(dtype).max)


def page_scale(pf, qmax: float):
    """Per-(page, kv-head) scale for a (..., page_size, KVH, D) float
    slab: amax over the page's positions and head dim."""
    return jnp.max(jnp.abs(pf.astype(jnp.float32)), axis=(-3, -1)) / qmax


def page_quantize(pf, scale, qmax: float, dtype):
    """Quantize (..., page_size, KVH, D) float against per-(page, head)
    ``scale`` (..., KVH). Values are clipped BEFORE the cast: an fp8
    overflow cast produces nan, not saturation. int8 rounds to nearest;
    fp8 rounding is the cast's. Requantization at an UNCHANGED scale is
    exact (round((q*s)/s) == q for |q| <= qmax), which is what makes the
    append path's unconditional page requant safe."""
    s = jnp.maximum(scale, 1e-12)[..., None, :, None]
    q = jnp.clip(pf.astype(jnp.float32) / s, -qmax, qmax)
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        q = jnp.round(q)
    return q.astype(dtype)


def page_dequantize(q, scale):
    """(..., page_size, KVH, D) storage payload x (..., KVH) scales ->
    f32. The inverse of page_quantize; the einsum gather fuses this into
    the page lookup, the Pallas kernel applies it per VMEM tile."""
    return q.astype(jnp.float32) * scale[..., None, :, None]


def flash_seq_cap() -> int:
    """FF_FLASH_MAX_SEQ: deployment escape hatch capping flash-kernel
    sequence length (0/unset/garbage = unlimited). Consulted by the dense
    path (_flash_ok) and the ring/sequence-parallel per-shard gate."""
    import os

    try:
        return int(os.environ.get("FF_FLASH_MAX_SEQ", "0") or 0)
    except ValueError:
        return 0


def _apply_rope(x, theta: float, offset=0):
    """Rotary position embedding (rotate-half convention) on (B,S,H,Dh).
    Angles are computed from absolute positions in f32 and the rotation is
    applied in f32 regardless of compute dtype (bf16 angles at position
    ~1000+ would lose the low-order bits that distinguish neighbors).
    `offset` shifts the absolute positions — the KV-cache decode path
    rotates a new token at its true position. Scalar (python int or
    traced) applies to every row; a (B,) array gives per-row offsets
    (ragged right-padded prompts)."""
    s, d = x.shape[1], x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    off = jnp.asarray(offset, jnp.float32)
    pos = off[..., None] + jnp.arange(s, dtype=jnp.float32)  # (S,) or (B,S)
    ang = pos[..., None] * freqs  # (..., S, half)
    if ang.ndim == 2:  # scalar offset: broadcast over batch
        ang = ang[None]
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class MultiHeadAttention(Op):
    op_type = OperatorType.OP_MULTIHEAD_ATTENTION
    needs_rng = True
    wants_shard_ctx = True  # executor passes (mesh, axis_map) for SP lowering

    def __init__(self, model, name, inputs, embed_dim: int, num_heads: int,
                 kdim: int = 0, vdim: int = 0, dropout: float = 0.0,
                 bias: bool = True, add_bias_kv: bool = False,
                 add_zero_attn: bool = False, causal: bool = False,
                 num_kv_heads: int = 0, rope: bool = False,
                 rope_theta: float = 10000.0):
        super().__init__(model, name, inputs)
        if add_bias_kv or add_zero_attn:
            raise NotImplementedError(
                "add_bias_kv/add_zero_attn are not supported yet "
                "(reference cuDNN MHA also lacked them)")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        # grouped-query attention (net-new vs the reference's cuDNN MHA):
        # k/v project to num_kv_heads and are broadcast to num_heads query
        # groups before the score matmul — k/v params and gradient-sync
        # volume shrink by heads/kv_heads
        self.num_kv_heads = num_kv_heads or num_heads
        assert num_heads % self.num_kv_heads == 0, (
            f"num_heads {num_heads} must be a multiple of num_kv_heads "
            f"{self.num_kv_heads}")
        # rotary position embedding, applied to q/k after projection and
        # BEFORE the attention-path dispatch: the op sees GLOBAL (B,S,H,D)
        # tensors, so positions are absolute even when a strategy shards
        # the sequence dim (ring/Ulysses lowering happens further down)
        self.rope = rope
        self.rope_theta = rope_theta
        # kdim/vdim are total projection sizes (reference kProjSize*num_heads
        # semantics via cudnnSetAttnDescriptor, attention.cu:533-570)
        self.kdim = kdim if kdim > 0 else embed_dim
        self.vdim = vdim if vdim > 0 else embed_dim
        self.dropout = dropout
        self.bias = bias
        self.causal = causal
        assert embed_dim % num_heads == 0
        assert self.kdim % num_heads == 0 and self.vdim % num_heads == 0
        self.head_dim = embed_dim // num_heads
        self.qk_head_dim = self.kdim // num_heads
        self.v_head_dim = self.vdim // num_heads
        if rope:
            assert self.qk_head_dim % 2 == 0, "RoPE needs an even head dim"
        self.q_in = inputs[0].dims[-1]
        self.k_in = inputs[1].dims[-1]
        self.v_in = inputs[2].dims[-1]
        self.finalize()

    def output_shapes(self):
        q = self.inputs[0].dims
        return [tuple(q[:-1]) + (self.embed_dim,)], [self.inputs[0].dtype]

    def weights(self) -> List[WeightSpec]:
        kvh = self.num_kv_heads
        ws = [
            WeightSpec("wq", (self.q_in, self.num_heads, self.qk_head_dim),
                       init="glorot", fan=(self.q_in, self.kdim)),
            WeightSpec("wk", (self.k_in, kvh, self.qk_head_dim),
                       init="glorot",
                       fan=(self.k_in, kvh * self.qk_head_dim)),
            WeightSpec("wv", (self.v_in, kvh, self.v_head_dim),
                       init="glorot",
                       fan=(self.v_in, kvh * self.v_head_dim)),
            WeightSpec("wo", (self.num_heads, self.v_head_dim, self.embed_dim),
                       init="glorot", fan=(self.vdim, self.embed_dim)),
        ]
        if self.bias:
            ws += [WeightSpec("bias_q", (self.num_heads, self.qk_head_dim), init="zero"),
                   WeightSpec("bias_k", (kvh, self.qk_head_dim), init="zero"),
                   WeightSpec("bias_v", (kvh, self.v_head_dim), init="zero"),
                   WeightSpec("bias_o", (self.embed_dim,), init="zero")]
        return ws

    def _project_qkv(self, params, q, k, v, rope_offset=0):
        """Shared projection: (B,S,D) x (D,H,Hd) -> (B,S,H,Hd) for q and
        (B,S,KVH,Hd) for k/v, bias and RoPE applied, BEFORE any GQA
        broadcast — the KV cache stores this pre-broadcast layout."""
        qh = jnp.einsum("bsd,dhk->bshk", q, params["wq"])
        kh = jnp.einsum("bsd,dhk->bshk", k, params["wk"])
        vh = jnp.einsum("bsd,dhk->bshk", v, params["wv"])
        if self.bias:
            qh = qh + params["bias_q"]
            kh = kh + params["bias_k"]
            vh = vh + params["bias_v"]
        if self.rope:
            qh = _apply_rope(qh, self.rope_theta, rope_offset)
            kh = _apply_rope(kh, self.rope_theta, rope_offset)
        return qh, kh, vh

    def _broadcast_kv(self, kh, vh):
        if self.num_kv_heads != self.num_heads:
            # GQA: broadcast each kv head to its query group; downstream
            # paths (flash / ring / einsum) then see plain MHA shapes
            rep = self.num_heads // self.num_kv_heads
            kh = jnp.repeat(kh, rep, axis=2)
            vh = jnp.repeat(vh, rep, axis=2)
        return kh, vh

    def _out_proj(self, params, ctx):
        out = jnp.einsum("bqhk,hkd->bqd", ctx, params["wo"])
        if self.bias:
            out = out + params["bias_o"]
        return out

    def forward(self, params, xs, *, training=False, rng=None, shard_ctx=None):
        q, k, v = xs[0], xs[1], xs[2]
        qh, kh, vh = self._project_qkv(params, q, k, v)
        kh, vh = self._broadcast_kv(kh, vh)
        scale = 1.0 / math.sqrt(self.qk_head_dim)

        seq_axes = []
        if shard_ctx is not None:
            seq_axes = [ax for ax, d in (shard_ctx.get("axis_map") or {}).items()
                        if d == 1 and shard_ctx["mesh"].shape[ax] > 1]
        if seq_axes:
            ctx = self._sp_attention(qh, kh, vh, shard_ctx, seq_axes, scale,
                                     training, rng)
        else:
            ctx = self._dense_attention(qh, kh, vh, scale, training, rng,
                                        shard_ctx)
        return [self._out_proj(params, ctx)]

    # ---- KV-cache inference path (runtime/generation.py) -------------------
    #
    # Net-new vs the reference: its inference story is CompMode::
    # COMP_MODE_INFERENCE (ffconst.h:1-130) — the training graph run
    # forward-only, re-attending the full prefix every step. The TPU
    # rebuild adds the modern O(1)-per-token path: a static-shape KV cache
    # updated with lax.dynamic_update_slice (XLA-friendly: one program for
    # every decode step) storing PRE-broadcast kv heads, so GQA shrinks
    # cache HBM by heads/kv_heads.

    def init_cache(self, batch: int, max_len: int, dtype):
        return {
            "k": jnp.zeros((batch, max_len, self.num_kv_heads,
                            self.qk_head_dim), dtype),
            "v": jnp.zeros((batch, max_len, self.num_kv_heads,
                            self.v_head_dim), dtype),
        }

    def prefill_forward(self, params, xs, cache):
        """Full-prompt forward that also fills cache[:, :S]. Reuses the
        dense attention path (flash on TPU) for the prompt itself."""
        qh, kh, vh = self._project_qkv(params, xs[0], xs[1], xs[2])
        new_cache = {
            "k": jax.lax.dynamic_update_slice(
                cache["k"], kh.astype(cache["k"].dtype), (0, 0, 0, 0)),
            "v": jax.lax.dynamic_update_slice(
                cache["v"], vh.astype(cache["v"].dtype), (0, 0, 0, 0)),
        }
        kh, vh = self._broadcast_kv(kh, vh)
        scale = 1.0 / math.sqrt(self.qk_head_dim)
        ctx = self._dense_attention(qh, kh, vh, scale, False, None, None)
        return self._out_proj(params, ctx), new_cache

    def _grouped_cache_attention(self, qh, ck, cv, live):
        """Shared cache-attention body for the decode and chunked-prefill
        paths: q (B, C, H, Dh) against cached k/v (B, L, KVH, Dh) with a
        `live` mask broadcastable to (B, KVH, G, C, L). The GQA grouping
        reshapes q to (KVH, G) groups — consecutive query heads share a
        kv head, matching _broadcast_kv's jnp.repeat layout — so the
        broadcast is never materialized. f32 scores/softmax."""
        b, c = qh.shape[0], qh.shape[1]
        kvh = self.num_kv_heads
        grp = self.num_heads // kvh
        scale = 1.0 / math.sqrt(self.qk_head_dim)
        qg = qh.reshape(b, c, kvh, grp, self.qk_head_dim)
        logits = jnp.einsum("bqkgd,bskd->bkgqs", qg, ck.astype(qh.dtype),
                            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(live, logits, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(qh.dtype)
        ctx = jnp.einsum("bkgqs,bskd->bqkgd", probs, cv.astype(qh.dtype))
        return ctx.reshape(b, c, self.num_heads, self.v_head_dim)

    def chunk_forward(self, params, xs, cache, start):
        """Chunked prefill: a (B, C, D) slab of prompt positions
        [start, start+C) writes its k/v into the cache and attends the
        STATIC prefix slice [0, start+C) with the causal rule (position j
        attends idx <= start + j) — O(C * prefix) score memory, and the
        unwritten decode tail of the cache is never touched. Same mask
        and positions as the whole-prompt pass; logits are bitwise-equal
        to it on the einsum path (a flash-prefill backend accumulates in
        a different order, so there equality is within kernel tolerance —
        runtime/generation.py notes)."""
        qh, kh, vh = self._project_qkv(params, xs[0], xs[1], xs[2],
                                       rope_offset=start)
        ck = jax.lax.dynamic_update_slice(
            cache["k"], kh.astype(cache["k"].dtype), (0, start, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            cache["v"], vh.astype(cache["v"].dtype), (0, start, 0, 0))
        c = qh.shape[1]
        end = start + c  # python ints: a static slice of the live prefix
        live = (jnp.arange(end)[None, :]
                <= (start + jnp.arange(c))[:, None])        # (C, end)
        ctx = self._grouped_cache_attention(
            qh, ck[:, :end], cv[:, :end], live[None, None, None, :, :])
        return self._out_proj(params, ctx), {"k": ck, "v": cv}

    def encode_kv(self, params, enc):
        """Cross-attention's static k/v, projected ONCE from the encoder
        states at the start of a seq2seq decode (runtime/
        seq2seq_generation.py) — every decode step reuses them, so the
        per-token cost of cross-attention is one q projection + one
        (1 x S_src) attention, never a re-projection of the source."""
        _, kh, vh = self._project_qkv(params, enc, enc, enc)
        return {"k": kh, "v": vh}

    def cross_forward_cached(self, params, xs, kv):
        """Cross-attention over the static encoder k/v (encode_kv) for a
        (B, C) decoder slab — C = prompt length at prefill, 1 per decode
        step. Non-causal: every query attends the whole source."""
        qh, _, _ = self._project_qkv(params, xs[0], xs[0], xs[0])
        live = jnp.ones((1, 1, 1, 1, kv["k"].shape[1]), bool)
        ctx = self._grouped_cache_attention(qh, kv["k"], kv["v"], live)
        return self._out_proj(params, ctx)

    def query_forward(self, params, xs, cache, rope_pos, row_lengths):
        """Read-only cache query (ragged CHUNKED prefill's gather pass,
        runtime/generation.py): a (B, 1) slab holding each row's LAST
        prompt token, whose k/v the chunk passes already wrote — compute
        only q at the row's own position (`rope_pos` = row_lengths - 1)
        and attend the row's live prefix idx < row_lengths. The cache is
        returned untouched (re-writing the slot would be idempotent but
        pointless work)."""
        qh, _, _ = self._project_qkv(params, xs[0], xs[1], xs[2],
                                     rope_offset=rope_pos)
        idx = jnp.arange(cache["k"].shape[1])
        live = idx[None, :] < row_lengths[:, None]
        ctx = self._grouped_cache_attention(
            qh, cache["k"], cache["v"], live[:, None, None, None, :])
        return self._out_proj(params, ctx), cache

    def decode_forward(self, params, xs, cache, pos, rope_pos=None,
                       row_lengths=None, prompt_len=None):
        """One-token step: write this token's k/v at slot `pos` (traced
        scalar), attend q over the live cache prefix.

        Ragged right-padded prompts (runtime/generation.py): `row_lengths`
        (B,) marks each row's true prompt length and `prompt_len` the
        padded width; slots in [row_length, prompt_len) hold garbage k/v
        from pad positions and are masked out, and `rope_pos` (B,) rotates
        the new token at its LOGICAL position (row_length + step), not its
        cache slot."""
        qh, kh, vh = self._project_qkv(
            params, xs[0], xs[1], xs[2],
            rope_offset=pos if rope_pos is None else rope_pos)
        ck = jax.lax.dynamic_update_slice(
            cache["k"], kh.astype(cache["k"].dtype), (0, pos, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            cache["v"], vh.astype(cache["v"].dtype), (0, pos, 0, 0))
        idx = jnp.arange(ck.shape[1])
        if row_lengths is None:
            live = (idx <= pos)[None, :]
        else:
            live = (idx[None, :] < row_lengths[:, None]) \
                | ((idx[None, :] >= prompt_len) & (idx[None, :] <= pos))
        ctx = self._grouped_cache_attention(
            qh, ck, cv, live[:, None, None, None, :])
        return self._out_proj(params, ctx), {"k": ck, "v": cv}

    # ---- paged KV cache (runtime/serving.py) ------------------------------
    #
    # Continuous-batching serving splits the cache into a POOL of fixed
    # (page_size, KVH, Dh) blocks shared by every slot; a per-slot page
    # table maps logical position j to pool page table[j // page_size],
    # offset j % page_size. Long and short requests then share HBM instead
    # of every slot preallocating max_len — the serving-side analog of the
    # partition-don't-pad philosophy the training side applies to sharding.

    def init_paged_cache(self, num_pages: int, page_size: int, dtype,
                         kv_dtype=None):
        """A pool of `num_pages` KV pages. Page 0 is reserved by the
        serving engine as a scratch page (inactive slots write there), so
        callers size num_pages as 1 + worst-case live pages.

        ``kv_dtype`` (FFConfig.kv_cache_dtype) picks the storage:
        None/'native' stores ``dtype`` (the pre-quant pool), 'bf16'
        stores bfloat16 (plain cast), 'int8'/'fp8' store quantized
        payload plus per-(page, kv-head) f32 scales alongside — the
        ``k_scale``/``v_scale`` entries ride the same page ids as the
        payload, so the allocator/trie/COW machinery is untouched."""
        sdtype, qmax = kv_storage_dtype(kv_dtype)
        store = sdtype if sdtype is not None else dtype
        pool = {
            "k": jnp.zeros((num_pages, page_size, self.num_kv_heads,
                            self.qk_head_dim), store),
            "v": jnp.zeros((num_pages, page_size, self.num_kv_heads,
                            self.v_head_dim), store),
        }
        if qmax is not None:
            pool["k_scale"] = jnp.zeros(
                (num_pages, self.num_kv_heads), jnp.float32)
            pool["v_scale"] = jnp.zeros(
                (num_pages, self.num_kv_heads), jnp.float32)
        return pool

    def paged_prefill_write(self, cache, kh, vh, pages, impl="einsum"):
        """Scatter a slot's contiguous prefill k/v (1, L, KVH, Dh) into
        pool pages `pages` ((n_pages,) int32, n_pages = ceil(L /
        page_size)). The tail of the last page beyond L holds junk; it is
        either overwritten by decode tokens or masked by the live rule.
        Quantized pools ('k_scale' present) compute each page's
        per-(page, head) scale over the whole just-written page — the
        zero pad tail never inflates an amax — and replace scale AND
        payload (prefill only ever targets the request's own fresh
        pages, so a wholesale replace can never touch shared state).

        ``impl``: 'einsum' is the big-scatter parity oracle below;
        'pallas' routes to pallas_kernels.paged_prefill_write_pallas,
        which scatters page-at-a-time from VMEM (ISSUE 18) and is
        bitwise against the oracle (tests/test_pallas_paged.py)."""
        if impl == "pallas":
            from flexflow_tpu.ops.pallas_kernels import \
                paged_prefill_write_pallas
            return paged_prefill_write_pallas(cache, kh, vh, pages)
        page_size = cache["k"].shape[1]
        n_pages = pages.shape[0]
        pad = n_pages * page_size - kh.shape[1]
        quantized = "k_scale" in cache
        out = dict(cache)

        def paged(x):
            x = x[0]                                        # (L, KVH, Dh)
            if pad:
                x = jnp.pad(x, ((0, pad), (0, 0), (0, 0)))
            return x.reshape(n_pages, page_size, *x.shape[1:])

        for name, x in (("k", kh), ("v", vh)):
            pool = cache[name]
            if not quantized:
                out[name] = pool.at[pages].set(paged(x).astype(pool.dtype))
                continue
            qmax = storage_qmax(pool.dtype)
            pf = paged(x).astype(jnp.float32)
            scale = page_scale(pf, qmax)                    # (n_pages, KVH)
            out[name] = pool.at[pages].set(
                page_quantize(pf, scale, qmax, pool.dtype))
            out[name + "_scale"] = cache[name + "_scale"].at[pages].set(
                scale)
        return out

    def _paged_append(self, cache, kh, vh, page_ids, offs):
        """Write ONE token per slot at ``(page_ids[b], offs[b])`` —
        the decode-append half of the pool-write protocol. Full-width
        pools scatter the position in place. Quantized pools re-quantize
        the TARGET page against a running-max per-(page, head) scale:
        gather the page, dequantize at the current scale, insert the new
        token, grow the scale to cover it, requantize, scatter back.
        Requantization at an unchanged scale is exact (page_quantize),
        so older tokens only re-round when a genuinely larger token
        arrives — part of the documented per-dtype divergence budget
        (docs/serving.md). Appends only ever land in a request's own
        private pages (write_pos >= prompt_pad > the shared prefix), so
        the copy-on-write rule is preserved: published pages are never
        gathered OR scattered here."""
        quantized = "k_scale" in cache
        out = dict(cache)
        rows = jnp.arange(page_ids.shape[0])
        for name, x in (("k", kh), ("v", vh)):
            pool = cache[name]
            if not quantized:
                out[name] = pool.at[page_ids, offs].set(
                    x.astype(pool.dtype))
                continue
            qmax = storage_qmax(pool.dtype)
            sc = cache[name + "_scale"]
            cur = sc[page_ids]                              # (B, KVH)
            pf = page_dequantize(pool[page_ids], cur)       # (B,ps,KVH,D)
            pf = pf.at[rows, offs].set(x.astype(jnp.float32))
            amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
            new = jnp.maximum(cur, amax / qmax)             # (B, KVH)
            out[name] = pool.at[page_ids].set(
                page_quantize(pf, new, qmax, pool.dtype))
            out[name + "_scale"] = sc.at[page_ids].set(new)
        return out

    def export_page(self, cache, page):
        """Slice pool page(s) out as the serializable migration payload
        — the unit both the prefill->decode fleet handoff and the
        HBM->host tier demotion move (runtime/serving.py). ``page`` is a
        scalar or a (n,) index array (ONE gather per pool array serves a
        whole demotion sweep). Returns device arrays (the caller starts
        ``copy_to_host_async`` and resolves to numpy off the hot path);
        quantized pools include the pages' per-kv-head scales so a
        re-imported page is BITWISE the donor's — dequantized attention
        on the importer sees exactly what the donor's decode saw."""
        out = {"k": cache["k"][page], "v": cache["v"][page]}
        for name in ("k_scale", "v_scale"):
            if name in cache:
                out[name] = cache[name][page]
        return out

    def import_page(self, cache, page, payload):
        """Write exported page payload(s) back into the pool at
        ``page`` (scalar, or a (n,) traced index vector — the serving
        engine pads batches to a fixed width with scratch page 0, so
        ONE compiled writer serves every promotion/import batch) — the
        decode half of the handoff and the H2D tier promotion. Payload
        bytes are copied verbatim (no requantization: scales ride the
        payload), so export -> import round-trips bitwise. Only ever
        targets freshly allocated pages (the copy-on-write rule: a
        published page is never written), so a wholesale replace cannot
        touch shared state."""
        out = dict(cache)
        for name, x in payload.items():
            pool = cache[name]
            out[name] = pool.at[page].set(
                jnp.asarray(x).astype(pool.dtype))
        return out

    def gather_paged_kv(self, cache, pages):
        """Read ``pages`` ((n,) int32) out of the pool as a full-width
        (1, n * page_size, KVH, Dh) k/v view — what a prefix-cache hit
        prefill mounts READ-ONLY at the front of its contiguous cache.
        Quantized pools dequantize against the pages' scales here, so
        the borrower attends exactly the (lossy) values the donor's
        decode attention sees."""
        out = {}
        for name in ("k", "v"):
            x = cache[name][pages]                          # (n,ps,KVH,D)
            if name + "_scale" in cache:
                x = page_dequantize(x, cache[name + "_scale"][pages])
            out[name] = x.reshape(1, -1, *x.shape[2:])
        return out

    def _paged_attention_ctx(self, qh, cache, page_table, write_pos,
                             row_len, prompt_pad, impl):
        """Shared attention body of the paged decode/verify paths: q
        (B, S, H, Dh) against the updated pool through the per-slot page
        tables, write_pos (B, S) per-position frontiers. Two impls behind
        FFConfig.paged_attention_impl (resolve_paged_attention_impl):

          * ``einsum`` — gather the slot's pages into a logical
            (B, L_max, KVH, Dh) cache and run _grouped_cache_attention:
            bitwise the dense-cache computation (tests/test_serving.py),
            the parity oracle. The gather re-materializes the ENTIRE
            pool view in HBM every step; on a quantized pool the
            dequant fuses into the same gather (this branch is also the
            dequant parity oracle).
          * ``pallas`` — paged_attention_fwd_pallas: the page-table
            lookup happens INSIDE the kernel grid, so only the slot's
            live pages stream through VMEM; online softmax replaces the
            materialized (B, L_max) score row. Quantized pages
            dequantize per VMEM tile against their scalar-prefetched
            scales — the wide KV never exists in HBM. Numerics match
            the einsum path to kernel tolerance (accumulation order
            differs); greedy token streams are pinned identical by
            tests/test_pallas_paged.py and test_quantized_serving.py."""
        resolved = resolve_paged_attention_impl(
            impl, getattr(self.model, "config", None))
        ck, cv = cache["k"], cache["v"]
        if resolved == "pallas":
            from flexflow_tpu.ops.pallas_kernels import \
                paged_attention_fwd_pallas

            scale = 1.0 / math.sqrt(self.qk_head_dim)
            return paged_attention_fwd_pallas(
                qh, ck, cv, page_table, write_pos, row_len, prompt_pad,
                scale, k_scales=cache.get("k_scale"),
                v_scales=cache.get("v_scale"))
        b = qh.shape[0]
        max_len = page_table.shape[1] * ck.shape[1]
        gk, gv = ck[page_table], cv[page_table]     # (B, P, ps, KVH, D)
        if "k_scale" in cache:
            gk = page_dequantize(gk, cache["k_scale"][page_table])
            gv = page_dequantize(gv, cache["v_scale"][page_table])
        gk = gk.reshape(b, max_len, *gk.shape[3:])
        gv = gv.reshape(b, max_len, *gv.shape[3:])
        idx = jnp.arange(max_len)
        live = (idx[None, None, :] < row_len[:, None, None]) \
            | ((idx[None, None, :] >= prompt_pad[:, None, None])
               & (idx[None, None, :] <= write_pos[:, :, None]))
        return self._grouped_cache_attention(
            qh, gk, gv, live[:, None, None, :, :])

    def paged_decode_forward(self, params, xs, cache, page_table, write_pos,
                             rope_pos, row_len, prompt_pad, impl=None):
        """One continuous-batching decode step over the paged pool.

        xs[0]: (B_slots, 1, D) — each slot's last sampled token embedding
        path. Per-slot (B,) int32 arrays: `write_pos` the logical cache
        position this token occupies, `rope_pos` its LOGICAL sequence
        position (true prompt length + emitted count — bucket padding does
        not shift RoPE), `row_len` the true prompt length and `prompt_pad`
        the bucket-padded prompt width. Live rule per slot (identical to
        decode_forward's ragged rule, per-slot prompt_pad instead of a
        shared prompt_len): j < row_len  OR  prompt_pad <= j <= write_pos.

        The new token's k/v scatters into the pool at (page_table[b,
        write_pos // page_size], write_pos % page_size) — through the
        quantized-append protocol when the pool carries scales
        (_paged_append); attention then runs through
        _paged_attention_ctx — `impl` picks the page-gather einsum
        oracle or the Pallas paged kernel."""
        page_size = cache["k"].shape[1]
        qh, kh, vh = self._project_qkv(params, xs[0], xs[1], xs[2],
                                       rope_offset=rope_pos)
        page_ids = jnp.take_along_axis(
            page_table, (write_pos // page_size)[:, None], axis=1)[:, 0]
        offs = write_pos % page_size
        cache = self._paged_append(cache, kh[:, 0], vh[:, 0], page_ids,
                                   offs)
        ctx = self._paged_attention_ctx(qh, cache, page_table,
                                        write_pos[:, None], row_len,
                                        prompt_pad, impl)
        return self._out_proj(params, ctx), cache

    def paged_verify_forward(self, params, xs, cache, page_table, write_pos,
                             rope_pos0, row_len, prompt_pad, impl=None):
        """Speculative-decode verify: a (B, S) slab of candidate tokens
        (S = K draft proposals + 1) scored against the paged pool in ONE
        dispatch (runtime/serving.py).

        Position i of the slab writes its k/v at logical position
        ``write_pos[b, i]`` (the host pre-computes write_pos0 + i clamped
        to the slot's budget) and attends with the decode live rule at its
        own frontier: j < row_len OR prompt_pad <= j <= write_pos[b, i] —
        causality within the slab falls out of the frontier, since slab
        position i's window includes exactly the slab writes <= i plus the
        committed history. k/v written for positions the host later
        REJECTS stay inside the slot's own pages past its write frontier;
        the next dispatch (verify or decode) overwrites them before any
        accepted position can attend them, so rejected-draft garbage is
        never observable. ``rope_pos0`` (B,) is the slab's first LOGICAL
        position; position i rotates at rope_pos0 + i. Attention runs
        through _paged_attention_ctx (same einsum-oracle/Pallas-kernel
        split as decode — the ONE kernel serves both shapes). On a
        quantized pool the slab's positions append SEQUENTIALLY through
        _paged_append (slab position i+1 may land in the page position i
        just requantized; the running-max scale must see them in order),
        so the final pool state is identical across impls — the
        bitwise-pool contract the parity tests pin."""
        page_size = cache["k"].shape[1]
        qh, kh, vh = self._project_qkv(params, xs[0], xs[1], xs[2],
                                       rope_offset=rope_pos0)
        page_ids = jnp.take_along_axis(
            page_table, write_pos // page_size, axis=1)       # (B, S)
        offs = write_pos % page_size
        if "k_scale" in cache:
            # S sequential single-token appends = S page round-trips per
            # op per dispatch. Bounded: each is one (B, ps, KVH, D) page
            # vs the table-wide attention that follows, and S = K+1 is
            # small. A single final-scale pass would halve the traffic
            # when the slab stays in one page, but slab positions can
            # span pages — the per-position form is the one that is
            # correct for every (write_pos, page boundary) layout.
            for i in range(kh.shape[1]):
                cache = self._paged_append(cache, kh[:, i], vh[:, i],
                                           page_ids[:, i], offs[:, i])
        else:
            cache = dict(cache)
            cache["k"] = cache["k"].at[page_ids, offs].set(
                kh.astype(cache["k"].dtype))
            cache["v"] = cache["v"].at[page_ids, offs].set(
                vh.astype(cache["v"].dtype))
        ctx = self._paged_attention_ctx(qh, cache, page_table, write_pos,
                                        row_len, prompt_pad, impl)
        return self._out_proj(params, ctx), cache

    def _flash_ok(self, qh, kh) -> bool:
        """Use the hand-tiled Pallas flash kernel (ops/pallas_kernels.py) on
        the dense path when the backend runs it natively and the block grid
        divides the sequence. Role parity with the reference's tuned vendor
        kernel (attention.cu:244 cudnnMultiHeadAttnForward)."""
        import os

        cfg = getattr(self.model, "config", None)
        if cfg is not None and not getattr(cfg, "use_flash_attention", True):
            return False
        force = os.environ.get("FF_FORCE_FLASH_ATTENTION") == "1"
        if jax.default_backend() != "tpu" and not force:
            return False  # interpret mode is for tests only
        sq, sk = qh.shape[1], kh.shape[1]
        if self.qk_head_dim != self.v_head_dim:
            return False
        if self.causal and sq > sk:
            # more queries than keys under bottom-right-aligned causality
            # leaves the first sq-sk rows with no live key (0/0 in the
            # online softmax); the einsum path's uniform-softmax answer for
            # such rows is equally meaningless, so don't pretend parity
            return False
        # escape hatch: the streaming kernels carry no architectural length
        # cap, but if a deployment's Mosaic build rejects some long-sequence
        # compile, FF_FLASH_MAX_SEQ routes those shapes to the blockwise
        # fallback without a code change (unset/0 = unlimited)
        cap = flash_seq_cap()
        if cap and max(sq, sk) > cap:
            return False
        for s in (sq, sk):
            if s % min(128, s) != 0:
                return False
        return True

    def _dense_attention(self, qh, kh, vh, scale, training, rng,
                         shard_ctx=None):
        use_dropout = training and self.dropout > 0.0 and rng is not None
        if not use_dropout and self._flash_ok(qh, kh):
            return self._flash_dense(qh, kh, vh, scale, shard_ctx)
        sq, sk = qh.shape[1], kh.shape[1]
        if max(sq, sk) > BLOCKWISE_SEQ_THRESHOLD \
                and self.qk_head_dim == self.v_head_dim:
            # long-context dense fallback for flash-refused shapes (CPU
            # backend, dropout, causal with sq > sk): pure-JAX blockwise
            # online-softmax scan (O(block) working set) with rematerialized
            # backward — an einsum here would materialize the S x S
            # probability tensor. Block size degrades to any divisor of sk
            # like _pick_block.
            from flexflow_tpu.parallel.ring_attention import blockwise_attention

            block = next((b for b in (512, 256, 128, 64, 32, 16, 8)
                          if sk % b == 0), sk)
            blk = functools.partial(blockwise_attention, causal=self.causal,
                                    scale=scale, block_size=block,
                                    dropout_rate=self.dropout if use_dropout
                                    else 0.0,
                                    dropout_rng=rng if use_dropout else None)
            return jax.checkpoint(blk)(qh, kh, vh)
        logits = jnp.einsum("bqhk,bshk->bhqs", qh, kh,
                            preferred_element_type=jnp.float32) * scale
        if self.causal:
            sq, sk = logits.shape[-2], logits.shape[-1]
            mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(qh.dtype)
        if training and self.dropout > 0.0 and rng is not None:
            keep = 1.0 - self.dropout
            probs = jnp.where(jax.random.bernoulli(rng, keep, probs.shape),
                              probs / keep, 0.0)
        return jnp.einsum("bhqs,bshk->bqhk", probs, vh)

    def _flash_dense(self, qh, kh, vh, scale, shard_ctx):
        """Dense flash with multi-chip awareness. A pallas_call is a Mosaic
        custom call the XLA SPMD partitioner cannot split: left inside the
        GSPMD-partitioned program it would be replicated (all-gathers around
        attention — silent loss of data/tensor-parallel scaling). When the
        strategy shards the batch or head dim over a >1 mesh axis, run the
        kernel per-shard inside shard_map (embarrassingly parallel — no
        collectives), the same pattern the ring path uses for seq."""
        from flexflow_tpu.ops.pallas_kernels import flash_attention

        mesh = (shard_ctx or {}).get("mesh")
        if mesh is None:
            return flash_attention(qh, kh, vh, self.causal, scale)
        from flexflow_tpu.parallel import shard_entries, shard_map_compat

        axis_map = (shard_ctx or {}).get("axis_map") or {}
        # indivisible groups drop out alone (GSPMD pads that dim instead),
        # keeping whatever parallelism remains valid
        ent = shard_entries(mesh, axis_map, qh.shape, (0, 2))
        if ent[0] is None and ent[2] is None:
            return flash_attention(qh, kh, vh, self.causal, scale)

        spec = P(ent[0], None, ent[2], None)

        def inner(q, k, v):
            return flash_attention(q, k, v, self.causal, scale)

        return shard_map_compat(inner, mesh, (spec, spec, spec), spec)(
            qh, kh, vh)

    def _sp_attention(self, qh, kh, vh, shard_ctx, seq_axes, scale,
                      training=False, rng=None):
        """Sequence-parallel lowering: ring attention (default) or Ulysses
        over the mesh axes sharding the sequence dim. Attention dropout is
        applied inside the online-softmax recurrence (the Bernoulli mask hits
        the unnormalized probs, so strategy choice does not change model
        semantics)."""
        from jax.sharding import PartitionSpec as P

        from flexflow_tpu.parallel import shard_map_compat
        from flexflow_tpu.parallel.ring_attention import (ring_attention,
                                                          ulysses_attention)

        mesh = shard_ctx["mesh"]
        axis_map = shard_ctx.get("axis_map") or {}
        mode = shard_ctx.get("sp_mode", "ring")
        if mode not in ("ring", "ulysses"):
            raise ValueError(f"sp_mode must be 'ring' or 'ulysses', got {mode!r}")
        if len(seq_axes) > 1:
            raise ValueError(
                f"sequence dim sharded over multiple mesh axes {seq_axes}; "
                f"ring/ulysses attention needs a single 'seq' axis — merge "
                f"them in the mesh or adjust the strategy")
        from flexflow_tpu.parallel import shard_entries

        # batch/head groups degrade alone when indivisible, like the dense
        # path; the seq axis is the SP lowering itself and stays
        ent = shard_entries(mesh, axis_map, qh.shape, (0, 2))
        spec = P(ent[0], seq_axes[0], ent[2], None)
        seq_axis = seq_axes[0]
        fn = ring_attention if mode == "ring" else ulysses_attention
        dropout_rate = self.dropout if (training and rng is not None) else 0.0

        if dropout_rate > 0.0:
            def inner(q, k, v, key):
                return fn(q, k, v, axis_name=seq_axis, causal=self.causal,
                          scale=scale, dropout_rate=dropout_rate,
                          dropout_rng=key)

            key_spec = P(*([None] * jnp.asarray(rng).ndim))
            return shard_map_compat(inner, mesh, (spec, spec, spec, key_spec),
                                    spec)(qh, kh, vh, rng)

        def inner(q, k, v):
            return fn(q, k, v, axis_name=seq_axis, causal=self.causal,
                      scale=scale)

        return shard_map_compat(inner, mesh, (spec, spec, spec), spec)(
            qh, kh, vh)

    _contracted_output_dims = (2,)  # hidden dim comes from the wo contraction

    def partitionable_output_dims(self):
        # batch, seq (ring attention), hidden (head split)
        return [0, 1, 2]

    def single_axis_dims(self):
        # the ring/Ulysses lowering rotates around ONE named mesh axis; a
        # seq dim sharded over two axes is rejected at execution
        # (_sp_attention), so the search must not propose it
        return [1]

    def weight_partition(self, axis_map):
        # hidden-dim sharding => split heads (Megatron): shard the H dim of
        # wq/wk/wv and of wo's input side.
        ax = self.axes_for_dim(axis_map, 2)
        if ax is None:
            return super().weight_partition(axis_map)
        # GQA: k/v weights have num_kv_heads on their head dim; when the
        # head-shard degree does not divide it, those weights stay
        # replicated (their kv heads are broadcast to query groups in
        # forward anyway) while q/o still shard
        kv_ax = ax
        if self.num_kv_heads != self.num_heads and self.model.mesh is not None:
            from flexflow_tpu.parallel.mesh import mesh_shape_dict

            shape = mesh_shape_dict(self.model.mesh)
            deg = 1
            for a in (ax if isinstance(ax, tuple) else (ax,)):
                deg *= shape.get(a, 1)
            if self.num_kv_heads % deg != 0:
                kv_ax = None
        out = {
            "wq": P(None, ax, None),
            "wk": P(None, kv_ax, None),
            "wv": P(None, kv_ax, None),
            "wo": P(ax, None, None),
        }
        if self.bias:
            out["bias_q"] = P(ax, None)
            out["bias_k"] = P(kv_ax, None)
            out["bias_v"] = P(kv_ax, None)
            out["bias_o"] = P(None)
        return out

    def flops(self):
        b, sq = self.inputs[0].dims[0], self.inputs[0].dims[1]
        sk = self.inputs[1].dims[1]
        d = self.embed_dim
        kv_frac = self.num_kv_heads / self.num_heads  # GQA shrinks k/v proj
        proj = 2 * b * sq * self.q_in * d \
            + int(2 * b * sk * (self.k_in + self.v_in) * d * kv_frac) \
            + 2 * b * sq * d * d
        attn = 2 * b * self.num_heads * sq * sk * self.head_dim * 2
        return proj + attn
