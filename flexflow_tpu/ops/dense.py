"""Linear (dense), Embedding, BatchMatmul.

Reference: src/ops/linear.cu (1115 LoC: cuBLAS GEMM + replica-tensor TP
machinery), src/ops/embedding.cu (custom gather/scatter-add kernels),
src/ops/batch_matmul.cu (cuBLAS strided batched GEMM).

TPU re-design: Linear is one jnp.einsum feeding the MXU; all outer dims are
batch (the reference does the same flattening, linear.cu:158). Parameter
parallelism = shard the kernel's out-feature dim over the 'model' mesh axis;
sharded autodiff inserts the psum that replaces the reference's replica tensor
+ backward2 reduction (linear.cu:774-835). Embedding's vocab-partitioned
lookup (DLRM's key strategy) shards the table on dim 0; XLA lowers the gather
to an all-gather-free one-hot matmul or dynamic-slice + psum under GSPMD.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flexflow_tpu.ffconst import ActiMode, AggrMode, DataType, OperatorType
from flexflow_tpu.ops.base import Op, WeightSpec


def apply_activation(x, acti: ActiMode):
    import jax

    if acti == ActiMode.AC_MODE_NONE:
        return x
    if acti == ActiMode.AC_MODE_RELU:
        return jax.nn.relu(x)
    if acti == ActiMode.AC_MODE_SIGMOID:
        return jax.nn.sigmoid(x)
    if acti == ActiMode.AC_MODE_TANH:
        return jnp.tanh(x)
    if acti == ActiMode.AC_MODE_GELU:
        return jax.nn.gelu(x)
    raise ValueError(f"unknown activation {acti}")


class Linear(Op):
    op_type = OperatorType.OP_LINEAR

    def __init__(self, model, name, inputs, out_dim: int,
                 activation: ActiMode = ActiMode.AC_MODE_NONE,
                 use_bias: bool = True):
        super().__init__(model, name, inputs)
        self.out_dim = out_dim
        self.activation = activation
        self.use_bias = use_bias
        self.in_dim = inputs[0].dims[-1]
        self.finalize()

    def output_shapes(self):
        ishape = self.inputs[0].dims
        return [tuple(ishape[:-1]) + (self.out_dim,)], [self.inputs[0].dtype]

    def weights(self) -> List[WeightSpec]:
        ws = [WeightSpec("kernel", (self.in_dim, self.out_dim), init="glorot",
                         fan=(self.in_dim, self.out_dim))]
        if self.use_bias:
            ws.append(WeightSpec("bias", (self.out_dim,), init="zero"))
        return ws

    def forward(self, params, xs, *, training=False, rng=None, lora=None):
        x = xs[0]
        y = jnp.einsum("...i,io->...o", x, params["kernel"],
                       preferred_element_type=x.dtype)
        if lora is not None:
            # gathered per-row LoRA delta (ops/lora.py): added BEFORE
            # bias/activation so it composes exactly like a merged
            # W + a@b*scale kernel would
            from flexflow_tpu.ops.lora import lora_delta

            a, b, scale = lora
            y = y + lora_delta(x, a, b, scale)
        if self.use_bias:
            y = y + params["bias"]
        return [apply_activation(y, self.activation)]

    @property
    def _contracted_output_dims(self):
        return (self.outputs[0].num_dims - 1,)

    def partitionable_output_dims(self):
        # sample dim(s) + out-channel (the reference's parameter-parallel dim,
        # linear.cu:144-269, gated by --enable-parameter-parallel)
        nd = self.outputs[0].num_dims
        return list(range(nd))

    def contract_size(self):
        # row-parallel: kernel sharded on in_dim, input sharded on its last
        # dim (a column-parallel producer's layout), output psum-replicated —
        # the Megatron pair that makes TP resharding-free. Reference analog:
        # replica-input Linear (linear.cu:171-192) + backward2 (:774-835).
        return self.in_dim

    def weight_partition(self, axis_map):
        from flexflow_tpu.parallel.pconfig import CONTRACT

        ax = self.axes_for_dim(axis_map, self.outputs[0].num_dims - 1)
        cax = self.axes_for_dim(axis_map, CONTRACT)
        out = {"kernel": P(cax, ax)}
        if self.use_bias:
            # bias adds after the psum; replicated over contract axes
            out["bias"] = P(ax)
        return out

    def contract_input_dim(self, input_idx):
        return self.inputs[input_idx].num_dims - 1

    def flops(self):
        batch = int(np.prod(self.outputs[0].dims[:-1]))
        return 2 * batch * self.in_dim * self.out_dim


class Embedding(Op):
    op_type = OperatorType.OP_EMBEDDING

    def __init__(self, model, name, inputs, num_entries: int, out_dim: int,
                 aggr: AggrMode = AggrMode.AGGR_MODE_NONE):
        super().__init__(model, name, inputs)
        self.num_entries = num_entries
        self.out_dim = out_dim
        self.aggr = aggr
        self.finalize()

    def output_shapes(self):
        ishape = self.inputs[0].dims
        if self.aggr == AggrMode.AGGR_MODE_NONE:
            shape = tuple(ishape) + (self.out_dim,)
        else:
            # bag aggregation over the last input dim (reference AGGR_MODE_SUM/AVG,
            # embedding.cu:165-226)
            shape = tuple(ishape[:-1]) + (self.out_dim,)
        return [shape], [DataType.DT_FLOAT]

    def weights(self):
        return [WeightSpec("kernel", (self.num_entries, self.out_dim),
                           init="glorot", fan=(self.num_entries, self.out_dim))]

    def forward(self, params, xs, *, training=False, rng=None):
        idx = xs[0].astype(jnp.int32)
        emb = jnp.take(params["kernel"], idx, axis=0)
        if self.aggr == AggrMode.AGGR_MODE_SUM:
            emb = jnp.sum(emb, axis=-2)
        elif self.aggr == AggrMode.AGGR_MODE_AVG:
            emb = jnp.mean(emb, axis=-2)
        return [emb]

    @property
    def _contracted_output_dims(self):
        return (self.outputs[0].num_dims - 1,)

    def partitionable_output_dims(self):
        nd = self.outputs[0].num_dims
        return [0, nd - 1]  # sample + embedding-channel (vocab-split table)

    def weight_partition(self, axis_map):
        ax = self.axes_for_dim(axis_map, self.outputs[0].num_dims - 1)
        return {"kernel": P(None, ax)}

    def flops(self):
        return 0  # memory-bound gather

    def input_axis_map(self, axis_map, input_idx):
        # index input has no channel dim; keep only sample-dim mappings
        ndims = self.inputs[input_idx].num_dims
        return {ax: (d if d is not None and d < ndims else None)
                for ax, d in (axis_map or {}).items()}


class BatchMatmul(Op):
    op_type = OperatorType.OP_BATCHMATMUL

    def __init__(self, model, name, inputs):
        super().__init__(model, name, inputs)
        self.finalize()

    def output_shapes(self):
        a, b = self.inputs[0].dims, self.inputs[1].dims
        assert a[:-2] == b[:-2], f"batch dims mismatch {a} @ {b}"
        assert a[-1] == b[-2], f"contraction mismatch {a} @ {b}"
        return [tuple(a[:-1]) + (b[-1],)], [self.inputs[0].dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        return [jnp.matmul(xs[0], xs[1])]

    def partitionable_output_dims(self):
        return list(range(self.outputs[0].num_dims - 2))

    def flops(self):
        a, b = self.inputs[0].dims, self.inputs[1].dims
        return 2 * int(np.prod(a)) * b[-1]
