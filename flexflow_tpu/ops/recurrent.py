"""Recurrent ops: LSTM, GRU.

Reference: the NMT subsystem's cuDNN-RNN LSTM cells (nmt/lstm.cu, 574 LoC,
descriptors rnn.h:198-210). TPU design: `lax.scan` over time with fused
gate matmuls — the per-timestep (B,D)x(D,4H) GEMM rides the MXU and XLA
pipelines the scan; sequence chunking across devices (the reference's
LSTM_PER_NODE_LENGTH pipelining) is expressed with the 'pipe' axis utilities
in parallel/pipeline.py instead of per-timestep device tables.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import Op, WeightSpec


class LSTM(Op):
    op_type = OperatorType.OP_LSTM

    def __init__(self, model, name, inputs, hidden_size: int,
                 return_sequences: bool = True):
        super().__init__(model, name, inputs)
        self.hidden_size = hidden_size
        self.return_sequences = return_sequences
        self.in_dim = inputs[0].dims[-1]
        self.finalize()

    def output_shapes(self):
        b, s = self.inputs[0].dims[0], self.inputs[0].dims[1]
        h = self.hidden_size
        shape = (b, s, h) if self.return_sequences else (b, h)
        return [shape], [self.inputs[0].dtype]

    def weights(self) -> List[WeightSpec]:
        d, h = self.in_dim, self.hidden_size
        return [
            WeightSpec("wx", (d, 4 * h), init="glorot", fan=(d, 4 * h)),
            WeightSpec("wh", (h, 4 * h), init="glorot", fan=(h, 4 * h)),
            WeightSpec("bias", (4 * h,), init="zero"),
        ]

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]  # (B, S, D)
        b = x.shape[0]
        h = self.hidden_size
        wx, wh, bias = params["wx"], params["wh"], params["bias"]
        # precompute input contributions for all timesteps in one big GEMM
        xg = jnp.einsum("bsd,dk->bsk", x, wx) + bias  # (B, S, 4H)

        def cell(carry, xg_t):
            h_prev, c_prev = carry
            gates = xg_t + h_prev @ wh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = (jax.nn.sigmoid(t) for t in (i, f, o))
            g = jnp.tanh(g)
            c = f * c_prev + i * g
            h_new = o * jnp.tanh(c)
            return (h_new, c), h_new

        h0 = jnp.zeros((b, h), x.dtype)
        c0 = jnp.zeros((b, h), x.dtype)
        (_, _), hs = lax.scan(cell, (h0, c0), xg.transpose(1, 0, 2))
        out = hs.transpose(1, 0, 2)  # (B, S, H)
        return [out if self.return_sequences else out[:, -1]]

    def partitionable_output_dims(self):
        return [0]  # batch only; seq is the recurrence, hidden in weights

    def flops(self):
        b, s = self.inputs[0].dims[0], self.inputs[0].dims[1]
        return 2 * b * s * 4 * self.hidden_size * (self.in_dim + self.hidden_size)


class GRU(Op):
    op_type = OperatorType.OP_GRU

    def __init__(self, model, name, inputs, hidden_size: int,
                 return_sequences: bool = True):
        super().__init__(model, name, inputs)
        self.hidden_size = hidden_size
        self.return_sequences = return_sequences
        self.in_dim = inputs[0].dims[-1]
        self.finalize()

    def output_shapes(self):
        b, s = self.inputs[0].dims[0], self.inputs[0].dims[1]
        h = self.hidden_size
        shape = (b, s, h) if self.return_sequences else (b, h)
        return [shape], [self.inputs[0].dtype]

    def weights(self) -> List[WeightSpec]:
        d, h = self.in_dim, self.hidden_size
        return [
            WeightSpec("wx", (d, 3 * h), init="glorot", fan=(d, 3 * h)),
            WeightSpec("wh", (h, 3 * h), init="glorot", fan=(h, 3 * h)),
            WeightSpec("bias", (3 * h,), init="zero"),
        ]

    def forward(self, params, xs, *, training=False, rng=None):
        x = xs[0]
        b, h = x.shape[0], self.hidden_size
        wx, wh, bias = params["wx"], params["wh"], params["bias"]
        xg = jnp.einsum("bsd,dk->bsk", x, wx) + bias

        def cell(h_prev, xg_t):
            xr, xz, xn = jnp.split(xg_t, 3, axis=-1)
            hr, hz, hn = jnp.split(h_prev @ wh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1 - z) * n + z * h_prev
            return h_new, h_new

        h0 = jnp.zeros((b, h), x.dtype)
        _, hs = lax.scan(cell, h0, xg.transpose(1, 0, 2))
        out = hs.transpose(1, 0, 2)
        return [out if self.return_sequences else out[:, -1]]

    def partitionable_output_dims(self):
        return [0]

    def flops(self):
        b, s = self.inputs[0].dims[0], self.inputs[0].dims[1]
        return 2 * b * s * 3 * self.hidden_size * (self.in_dim + self.hidden_size)
