"""Op base class and weight specs."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.ffconst import DataType, OperatorType, ParameterSyncType
from flexflow_tpu.tensor import Parameter, Tensor


@dataclasses.dataclass
class WeightSpec:
    """Metadata for one trainable weight of an op (analog of the reference's
    create_weights + Initializer attachment, e.g. linear.cu:74-122)."""

    name: str
    shape: Tuple[int, ...]
    dtype: DataType = DataType.DT_FLOAT
    init: str = "glorot"  # glorot | zero | one | uniform | normal | constant
    init_args: Tuple = ()  # e.g. (low, high) for uniform
    # fan dims for glorot: (fan_in, fan_out) computed from shape by default
    fan: Optional[Tuple[int, int]] = None
    sync_type: ParameterSyncType = ParameterSyncType.NCCL


class Op:
    """Graph-node base.

    Subclasses set `op_type`, implement `output_shapes`, `forward`, and
    optionally `weights`, `weight_partition`, `partitionable_output_dims`,
    `flops`.
    """

    op_type: OperatorType = OperatorType.OP_NOOP
    stateful: bool = False  # True => implements forward_stateful (BatchNorm)
    needs_rng: bool = False  # True => forward uses rng (Dropout, MHA dropout)

    def __init__(self, model, name: str, inputs: Sequence[Tensor], **attrs):
        self.model = model
        self.name = name
        self.inputs: List[Tensor] = list(inputs)
        self.attrs: Dict[str, Any] = attrs
        self.outputs: List[Tensor] = []
        self._weight_specs: Optional[List[WeightSpec]] = None

    # -- graph construction --------------------------------------------------

    def finalize(self) -> None:
        """Infer outputs and register with the model graph."""
        shapes, dtypes = self.output_shapes()
        self.outputs = [
            Tensor(dims=tuple(s), dtype=dt, owner_op=self, owner_idx=i,
                   name=f"{self.name}:out{i}")
            for i, (s, dt) in enumerate(zip(shapes, dtypes))
        ]

    def output_shapes(self) -> Tuple[List[Tuple[int, ...]], List[DataType]]:
        raise NotImplementedError

    def weights(self) -> List[WeightSpec]:
        return []

    def weight_specs(self) -> List[WeightSpec]:
        if self._weight_specs is None:
            self._weight_specs = self.weights()
        return self._weight_specs

    # -- execution -----------------------------------------------------------

    def forward(self, params: Dict[str, Any], xs: List[Any], *,
                training: bool = False, rng=None) -> List[Any]:
        raise NotImplementedError

    def forward_stateful(self, params, state, xs, *, training=False, rng=None):
        raise NotImplementedError

    def init_state(self) -> Dict[str, Any]:
        return {}

    def init_state_for_shapes(self, in_shapes) -> Dict[str, Any]:
        """State sized for PER-SHARD input shapes (the measurement harness
        runs one shard standalone; channel-sharded BatchNorm needs its
        running stats sliced to the shard's channel count). Default: the
        full-size state."""
        return self.init_state()

    # -- parallelization metadata ---------------------------------------------

    def partitionable_output_dims(self) -> List[int]:
        """Logical output dims the search may partition. Default: sample dim
        only (the reference's conservative default for most ops)."""
        return [0]

    def single_axis_dims(self) -> List[int]:
        """Output dims the executor can shard over at most ONE mesh axis
        (the search must not propose multi-axis products for them). Default
        none; MultiHeadAttention's seq dim is the known case — the
        ring/Ulysses lowering needs a single named 'seq' axis."""
        return []

    def contract_size(self) -> Optional[int]:
        """Size of the op's weight-contraction dim, if the op supports
        CONTRACT (row-parallel) sharding: weight sharded on its input-feature
        dim, input sharded on its last dim, output psum-replicated. None =
        not contractable. Analog of the reference Linear's replica-dim
        machinery (linear.cu:171-192,774-835)."""
        return None

    def expert_parallel_size(self) -> Optional[int]:
        """Number of independently-shardable experts, if the op supports
        EXPERT (MoE expert-parallel) sharding: expert-indexed weights shard
        on their expert dim, tokens all-to-all to their experts and back,
        output replicated over the axis. None = not expert-parallelizable.
        The search proposes {axis: EXPERT} when the axis size divides it."""
        return None

    def pipeline_stages(self) -> int:
        """Number of identical stacked layers this op can split into pipeline
        stages (STAGE axis_map proposals): 0 = not pipelineable. Ops with a
        stacked-layer weight layout (TransformerPipelineStack) return their
        layer count; the search proposes {axis: STAGE} when the axis size
        divides it."""
        return 0

    def output_axis_map(self, axis_map: Dict[str, Optional[int]]
                        ) -> Dict[str, Optional[int]]:
        """The sharding the op's OUTPUT actually has under `axis_map`:
        CONTRACT and STAGE axes produce a psum-replicated output, so
        consumers see them as replicated."""
        return {ax: (d if d is not None and d >= 0 else None)
                for ax, d in (axis_map or {}).items()}

    def weight_partition(self, axis_map: Dict[str, Optional[int]]):
        """Given the op's output axis_map (mesh axis -> output dim), return
        {weight_name: PartitionSpec}. Default: fully replicated weights
        (reference: weights replicated under data parallelism,
        model.cc:948-1074 PS/NCCL layouts)."""
        from jax.sharding import PartitionSpec as P

        return {w.name: P(*([None] * len(w.shape))) for w in self.weight_specs()}

    @staticmethod
    def axes_for_dim(axis_map: Dict[str, Optional[int]], dim: int):
        """Mesh axes mapped to output dim `dim`, as a PartitionSpec entry:
        None, a single axis name, or a tuple."""
        axes = [ax for ax, d in (axis_map or {}).items() if d == dim]
        if not axes:
            return None
        return axes[0] if len(axes) == 1 else tuple(axes)

    # output dims whose sharding does NOT propagate to inputs (e.g. an
    # out-channel dim produced by a weight contraction: the input must stay
    # replicated over axes sharding it). Subclasses with weight-produced dims
    # override this.
    _contracted_output_dims: Tuple[int, ...] = ()

    def contract_input_dim(self, input_idx: int) -> Optional[int]:
        """The input dim a CONTRACT axis shards for `input_idx` (e.g. the
        last dim for Linear, the channel dim for Conv2D). None = CONTRACT
        axes leave this input replicated. Only meaningful for ops whose
        contract_size() is not None."""
        return None

    def input_axis_map(self, axis_map: Dict[str, Optional[int]], input_idx: int
                       ) -> Dict[str, Optional[int]]:
        """Propagate the op's output axis_map to the sharding it implies for
        input `input_idx` (analog of get_input_sub_tensor shard-shape rules,
        reference model.cc:128-205). Default: same map truncated to input
        rank, with weight-contracted dims dropped (their axes need the input
        replicated — e.g. a column-parallel Linear all-gathers its input over
        the 'model' axis; the cost model must see that) and CONTRACT axes
        mapped to contract_input_dim()."""
        from flexflow_tpu.parallel.pconfig import CONTRACT

        ndims = self.inputs[input_idx].num_dims
        nd_out = self.outputs[0].num_dims
        contracted = {(d % nd_out) for d in self._contracted_output_dims}
        cdim = self.contract_input_dim(input_idx)
        out = {}
        for ax, d in axis_map.items():
            if d == CONTRACT and cdim is not None:
                out[ax] = cdim
            else:
                out[ax] = (d if d is not None and 0 <= d < ndims
                           and d not in contracted else None)
        return out

    # -- cost model ------------------------------------------------------------

    def flops(self) -> int:
        """Per-sample-batch forward FLOPs estimate for the analytic cost model
        (fallback when real measurement is unavailable)."""
        return 2 * sum(t.volume() for t in self.outputs)

    def output_bytes(self) -> int:
        import numpy as np

        return sum(t.volume() * 4 for t in self.outputs)

    def weight_bytes(self) -> int:
        total = 0
        for w in self.weight_specs():
            n = 1
            for d in w.shape:
                n *= d
            total += n * 4
        return total

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name!r})"


class InputOp(Op):
    """Placeholder op owning a graph input tensor (reference: tensors created
    by FFModel::create_tensor, model.cc:762, have owner_op == NULL)."""

    op_type = OperatorType.OP_INPUT

    def __init__(self, model, name: str, dims: Tuple[int, ...], dtype: DataType):
        super().__init__(model, name, [])
        self._dims = tuple(dims)
        self._dtype = dtype

    def output_shapes(self):
        return [self._dims], [self._dtype]

    def forward(self, params, xs, *, training=False, rng=None):
        raise RuntimeError("InputOp is fed by the executor, never executed")
