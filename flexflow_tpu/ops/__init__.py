"""Operator library.

Each op is a pure-functional compute rule plus shape/weight/partition metadata.
The analog of the reference's src/ops/*.cu files — but where the reference op
owns Legion regions, launchers, and hand-written CUDA kernels
(e.g. src/ops/linear.cu:41-1115), a TPU op here is only:

  * shape inference (`output_shapes`)
  * weight specs (`weights`) with initializer + sync metadata
  * a traceable `forward` built from jax/lax/pallas primitives
  * partition metadata for the strategy search (`partitionable_output_dims`,
    `weight_partition`) — the analog of create_output_and_partition
  * an analytic cost hook (`flops`) feeding the C++ simulator

Backward is sharded autodiff (jax.grad under GSPMD) — the reference's
per-op backward_kernel + replica-reduction machinery (linear.cu:774-835)
collapses into psum insertions by XLA.
"""

from flexflow_tpu.ops.base import Op, WeightSpec, InputOp  # noqa: F401
