"""flexflow_tpu — a TPU-native auto-parallelizing deep-learning framework.

A ground-up rebuild of the capabilities of FlexFlow (the Legion/CUDA
auto-parallelizing DNN framework, see /root/reference) designed for TPU:
the operator graph lowers to a single GSPMD-sharded XLA program over a
`jax.sharding.Mesh`; parallelization strategies are per-op `ParallelConfig`s
(SOAP dimensions) lowered to `PartitionSpec`s; an MCMC search over a C++
event-driven simulator with a TPU machine model (ICI/DCN/HBM) discovers
hybrid strategies; hot kernels (ring attention, embedding bag, top-k) are
Pallas.

Public API mirrors the reference's FFModel surface
(reference: include/model.h:250-483, python/flexflow/core/flexflow_cbinding.py).
"""

import os as _os

# sharding-invariant RNG: without it, old-jax GSPMD generates different
# random bits for dim-0-sharded weight inits (see _env docstring) — a
# CONTRACT/FSDP model then trains from DIFFERENT initial weights than its
# replicated twin. Must precede any traced jax.random use in the package.
from flexflow_tpu._env import \
    enable_sharding_invariant_rng as _enable_invariant_rng

_enable_invariant_rng()

if _os.environ.get("FLEXFLOW_FORCE_CPU_DEVICES"):
    # FLEXFLOW_FORCE_CPU_DEVICES=N provisions an N-device virtual CPU
    # platform, provided flexflow_tpu is imported before any jax use (the
    # test/example sweep scripts rely on this). No-op if the embedding
    # application already initialized a backend.
    from flexflow_tpu._env import force_cpu_devices_from_env as _force_cpu

    _force_cpu(_os.environ["FLEXFLOW_FORCE_CPU_DEVICES"])

from flexflow_tpu.ffconst import (  # noqa: F401
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OperatorType,
    ParameterSyncType,
    PoolType,
)
from flexflow_tpu.config import FFConfig  # noqa: F401
from flexflow_tpu.tensor import Tensor, Parameter  # noqa: F401
from flexflow_tpu.model import FFModel  # noqa: F401
from flexflow_tpu.runtime.optimizer import SGDOptimizer, AdamOptimizer  # noqa: F401
from flexflow_tpu.runtime.schedule import (  # noqa: F401
    ConstantSchedule, ExponentialDecay, StepDecay, WarmupCosine,
    WarmupLinear)
from flexflow_tpu.runtime.initializer import (  # noqa: F401
    GlorotUniformInitializer,
    ZeroInitializer,
    UniformInitializer,
    NormInitializer,
    ConstantInitializer,
)
from flexflow_tpu.runtime.dataloader import SingleDataLoader  # noqa: F401
from flexflow_tpu.runtime.resilience import TrainSupervisor  # noqa: F401
from flexflow_tpu.runtime.elastic import TopologyChangedError  # noqa: F401
from flexflow_tpu.runtime.checkpoint import (  # noqa: F401
    CheckpointCorruptError,
)
from flexflow_tpu.parallel.pconfig import ParallelConfig  # noqa: F401

__version__ = "0.1.0"
