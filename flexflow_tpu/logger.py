"""Env-var-driven logger (reference: python/flexflow/flexflow_logger.py —
`fflogger` configured from FF_LOGGING_LEVEL / FF_LOGGING_FILE; C++ side uses
LegionRuntime::Logger categories, model.cc:23).

Usage:
    from flexflow_tpu.logger import fflogger
    fflogger.info("compile done")

FLEXFLOW_LOG_LEVEL: debug|info|warning|error (default warning)
FLEXFLOW_LOG_FILE:  path (default stderr)
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warning": logging.WARNING, "error": logging.ERROR}


def _make_logger() -> logging.Logger:
    logger = logging.getLogger("flexflow_tpu")
    if logger.handlers:
        return logger
    level = _LEVELS.get(
        os.environ.get("FLEXFLOW_LOG_LEVEL", "warning").lower(),
        logging.WARNING)
    logger.setLevel(level)
    path = os.environ.get("FLEXFLOW_LOG_FILE", "")
    handler = (logging.FileHandler(path) if path
               else logging.StreamHandler(sys.stderr))
    handler.setFormatter(logging.Formatter(
        "[%(levelname)s %(asctime)s flexflow_tpu] %(message)s"))
    logger.addHandler(handler)
    logger.propagate = False
    return logger


fflogger = _make_logger()
