"""Env-var-driven logger (reference: python/flexflow/flexflow_logger.py —
`fflogger` configured from FF_LOGGING_LEVEL / FF_LOGGING_FILE; C++ side uses
LegionRuntime::Logger categories, model.cc:23).

Usage:
    from flexflow_tpu.logger import fflogger
    fflogger.info("compile done")

Env knobs — each accepts BOTH the reference's ``FF_LOGGING_*`` name and
this package's ``FLEXFLOW_LOG_*`` name; when both are set the
``FLEXFLOW_*`` (new) name wins:

FLEXFLOW_LOG_LEVEL  / FF_LOGGING_LEVEL:  debug|info|warning|error
                                         (default warning)
FLEXFLOW_LOG_FILE   / FF_LOGGING_FILE:   path (default stderr)
FLEXFLOW_LOG_FORMAT / FF_LOGGING_FORMAT: "text" (default) | "json" —
    JSON-lines output, one object per line with ``ts``, ``level``,
    ``logger``, ``msg`` and (when a telemetry span is active on the
    logging thread) ``trace_id``, so log lines join against the trace
    ring / exported Chrome trace by request id
    (runtime/telemetry.py, docs/observability.md).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warning": logging.WARNING, "error": logging.ERROR}


def _env(new: str, old: str, default: str = "") -> str:
    """Read a knob under both its names; the new name wins when both are
    set (the docstring's contract — the reference's names keep working)."""
    v = os.environ.get(new, "")
    return v if v else os.environ.get(old, default)


class _JsonFormatter(logging.Formatter):
    """JSON-lines log format carrying the active telemetry trace id so
    log lines can be joined against per-request traces."""

    def format(self, record: logging.LogRecord) -> str:
        row = {
            "ts": round(record.created, 6),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S",
                                  time.localtime(record.created)),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        try:    # deferred import: telemetry imports this module at top
            from flexflow_tpu.runtime.telemetry import current_trace_id

            tid = current_trace_id()
            if tid is not None:
                row["trace_id"] = tid
        except Exception:
            pass
        if record.exc_info:
            row["exc"] = self.formatException(record.exc_info)
        return json.dumps(row, ensure_ascii=False)


def _make_logger() -> logging.Logger:
    logger = logging.getLogger("flexflow_tpu")
    if logger.handlers:
        return logger
    level = _LEVELS.get(
        _env("FLEXFLOW_LOG_LEVEL", "FF_LOGGING_LEVEL", "warning").lower(),
        logging.WARNING)
    logger.setLevel(level)
    path = _env("FLEXFLOW_LOG_FILE", "FF_LOGGING_FILE")
    handler = (logging.FileHandler(path) if path
               else logging.StreamHandler(sys.stderr))
    fmt = _env("FLEXFLOW_LOG_FORMAT", "FF_LOGGING_FORMAT", "text").lower()
    handler.setFormatter(
        _JsonFormatter() if fmt == "json" else logging.Formatter(
            "[%(levelname)s %(asctime)s flexflow_tpu] %(message)s"))
    logger.addHandler(handler)
    logger.propagate = False
    return logger


fflogger = _make_logger()
