"""Model zoo — the reference's example applications rebuilt on the native API
(reference: examples/cpp/{AlexNet,ResNet,InceptionV3,Transformer,DLRM},
examples/python)."""
