"""Llama-family decoder: RMSNorm pre-norm, RoPE, grouped-query attention,
SwiGLU MLP, no biases.

Net-new vs the reference (its newest workload is the cuDNN-MHA encoder,
src/ops/attention.cu) — this is the modern decoder architecture the TPU
rebuild targets (BASELINE.json north star names "Llama-3-8B-class" configs)
and it is deliberately head_dim-128-friendly: the round-3 on-chip probe
sweep showed QK^T/AV contract over head_dim, so d=128 fills the MXU where
d=64 runs it half-empty.

GQA/RoPE live in the attention op itself (ops/attention.py) and compose
with every attention lowering (dense flash kernel, ring/Ulysses sequence
parallel, head-sharded TP).
"""

from __future__ import annotations

from flexflow_tpu.ffconst import DataType
from flexflow_tpu.model import FFModel


def swiglu(ff: FFModel, x, hidden: int, ffn_hidden: int, i: int):
    """SwiGLU MLP: (silu(x W_gate) * x W_up) W_down, silu = x * sigmoid(x)."""
    g = ff.dense(x, ffn_hidden, use_bias=False, name=f"ffn_gate_{i}")
    s = ff.multiply(g, ff.sigmoid(g, name=f"ffn_sig_{i}"),
                    name=f"ffn_silu_{i}")
    u = ff.dense(x, ffn_hidden, use_bias=False, name=f"ffn_up_{i}")
    h = ff.multiply(s, u, name=f"ffn_gated_{i}")
    return ff.dense(h, hidden, use_bias=False, name=f"ffn_down_{i}")


def llama_lm(ff: FFModel, batch_size: int, seq_len: int = 256,
             hidden: int = 512, layers: int = 4, heads: int = 4,
             kv_heads: int = 0, ffn_hidden: int = 0,
             vocab_size: int = 32_000, rope_theta: float = 10000.0,
             tie_embeddings: bool = False):
    """Decoder-only causal LM in the Llama shape. kv_heads=0 -> MHA;
    kv_heads < heads -> grouped-query attention. ffn_hidden defaults to
    the Llama-style ~8/3 * hidden rounded to a multiple of 128.
    tie_embeddings shares the lm_head with the token embedding
    (FFModel.tie_weights) — vocab x hidden params stored once."""
    if not ffn_hidden:
        ffn_hidden = max(128, (8 * hidden // 3 + 127) // 128 * 128)
    tokens = ff.create_tensor([batch_size, seq_len], dtype=DataType.DT_INT32,
                              name="input")
    t = ff.embedding(tokens, vocab_size, hidden, name="tok_embed")
    for i in range(layers):
        a = ff.rms_norm(t, name=f"ln1_{i}")
        a = ff.multihead_attention(
            a, a, a, hidden, heads, causal=True, bias=False,
            num_kv_heads=kv_heads, rope=True, rope_theta=rope_theta,
            name=f"attn_{i}")
        t = ff.add(t, a, name=f"res1_{i}")
        f = swiglu(ff, ff.rms_norm(t, name=f"ln2_{i}"), hidden, ffn_hidden, i)
        t = ff.add(t, f, name=f"res2_{i}")
    t = ff.rms_norm(t, name="ln_f")
    logits = ff.dense(t, vocab_size, use_bias=False, name="lm_head")
    if tie_embeddings:
        ff.tie_weights("lm_head", "kernel", "tok_embed", "kernel",
                       "transpose")
    return tokens, logits
