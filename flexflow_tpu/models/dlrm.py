"""DLRM — deep learning recommendation model.

Reference: examples/cpp/DLRM/dlrm.cc:77+ and run_summit.sh (Summit config:
512/GPU batch, up to 24 x 1M-row x 64-dim embedding tables, mlp-bot
64-512-512-64, mlp-top 576-1024-1024-1024-1). The embedding tables are the
parallelization showcase: the reference places them per-GPU via hetero
strategies; here each table's ParallelConfig can shard its output dim over
'model' (vocab-partitioned lookup under GSPMD).
"""

from __future__ import annotations

from typing import List, Sequence

from flexflow_tpu.ffconst import ActiMode, AggrMode, DataType
from flexflow_tpu.model import FFModel


def _mlp(ff, t, sizes: Sequence[int], prefix: str, sigmoid_last=False):
    for i, s in enumerate(sizes):
        last = i == len(sizes) - 1
        act = (ActiMode.AC_MODE_SIGMOID if (last and sigmoid_last)
               else ActiMode.AC_MODE_RELU)
        t = ff.dense(t, s, act, name=f"{prefix}_{i}")
    return t


def dlrm(ff: FFModel, batch_size: int,
         embedding_size: int = 64,
         embedding_entries: int = 100_000,
         num_tables: int = 8,
         indices_per_table: int = 1,
         dense_dim: int = 64,
         mlp_bot: Sequence[int] = (512, 512, 64),
         mlp_top: Sequence[int] = (1024, 1024, 1024, 1)):
    """Returns (dense_input, sparse_inputs, output)."""
    dense_in = ff.create_tensor([batch_size, dense_dim], name="dense_input")
    sparse_ins: List = []
    emb_outs: List = []
    for i in range(num_tables):
        s = ff.create_tensor([batch_size, indices_per_table],
                             dtype=DataType.DT_INT32, name=f"sparse_{i}")
        sparse_ins.append(s)
        e = ff.embedding(s, embedding_entries, embedding_size,
                         AggrMode.AGGR_MODE_SUM, name=f"emb_{i}")
        emb_outs.append(e)
    x = _mlp(ff, dense_in, mlp_bot, "bot")
    # interaction: concat embeddings + bottom-MLP output (reference dlrm.cc
    # interact_features 'cat' mode)
    t = ff.concat([x] + emb_outs, axis=1, name="interact")
    out = _mlp(ff, t, mlp_top, "top", sigmoid_last=True)
    return dense_in, sparse_ins, out
