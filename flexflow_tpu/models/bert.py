"""BERT / GPT-style language models on the native API.

Reference analog: examples/python/native/bert_proxy_native.py (BERT-proxy
encoder stack). Adds the decoder-only GPT/Llama-style variant (RMSNorm +
causal attention + MoE option) — the modern configs the TPU rebuild targets
(BASELINE.json: "GPT-3 / Llama-3-8B ... on v5p pod").
"""

from __future__ import annotations

from flexflow_tpu.ffconst import ActiMode, DataType
from flexflow_tpu.model import FFModel
from flexflow_tpu.models.transformer import encoder_block


def bert_base(ff: FFModel, batch_size: int, seq_len: int = 128,
              hidden: int = 768, layers: int = 12, heads: int = 12,
              vocab_size: int = 30_522, num_classes: int = 2):
    """BERT-base encoder with a classification head (proxy config matches
    bert_proxy_native.py: H768 L12 A12)."""
    tokens = ff.create_tensor([batch_size, seq_len], dtype=DataType.DT_INT32,
                              name="input")
    t = ff.embedding(tokens, vocab_size, hidden, name="tok_embed")
    pos = ff.create_tensor([batch_size, seq_len], dtype=DataType.DT_INT32,
                           name="positions")
    p = ff.embedding(pos, seq_len, hidden, name="pos_embed")
    t = ff.add(t, p, name="embed_add")
    for i in range(layers):
        t = encoder_block(ff, t, hidden, heads, 4, i, causal=False)
    t = ff.layer_norm(t, name="ln_f")
    cls = ff.mean(t, dims=[1], name="pool")  # mean-pool (CLS proxy)
    out = ff.dense(cls, num_classes, name="cls_head")
    return tokens, pos, out


def gpt_lm(ff: FFModel, batch_size: int, seq_len: int = 256,
           hidden: int = 512, layers: int = 8, heads: int = 8,
           vocab_size: int = 32_000, moe_every: int = 0,
           num_experts: int = 8):
    """Decoder-only causal LM; set moe_every=2 for a GShard-style MoE stack."""
    tokens = ff.create_tensor([batch_size, seq_len], dtype=DataType.DT_INT32,
                              name="input")
    t = ff.embedding(tokens, vocab_size, hidden, name="tok_embed")
    for i in range(layers):
        a = ff.rms_norm(t, name=f"ln1_{i}")
        a = ff.multihead_attention(a, a, a, hidden, heads, causal=True,
                                   bias=False, name=f"attn_{i}")
        t = ff.add(t, a, name=f"res1_{i}")
        f = ff.rms_norm(t, name=f"ln2_{i}")
        if moe_every and (i + 1) % moe_every == 0:
            f = ff.moe(f, num_experts=num_experts, hidden_dim=hidden * 4,
                       k=2, name=f"moe_{i}")
        else:
            f = ff.dense(f, hidden * 4, ActiMode.AC_MODE_GELU, name=f"ffn1_{i}")
            f = ff.dense(f, hidden, name=f"ffn2_{i}")
        t = ff.add(t, f, name=f"res2_{i}")
    t = ff.rms_norm(t, name="ln_f")
    logits = ff.dense(t, vocab_size, use_bias=False, name="lm_head")
    return tokens, logits


def gpt_pipelined(ff: FFModel, batch_size: int, seq_len: int = 256,
                  hidden: int = 512, layers: int = 8, heads: int = 8,
                  vocab_size: int = 32_000,
                  num_microbatches=None):
    """Decoder-only causal LM with the layer stack as ONE pipelined op
    (ops/pipelined.py): under a 'pipe' mesh axis the blocks run as a GPipe
    ring; single-device it is a lax.scan over layers. The graph-level PP
    counterpart of the reference's NMT pipeline (nmt/rnn.h:21-63)."""
    tokens = ff.create_tensor([batch_size, seq_len], dtype=DataType.DT_INT32,
                              name="input")
    t = ff.embedding(tokens, vocab_size, hidden, name="tok_embed")
    t = ff.transformer_pipeline_stack(t, layers, heads, causal=True,
                                      num_microbatches=num_microbatches,
                                      name="blocks")
    t = ff.rms_norm(t, name="ln_f")
    logits = ff.dense(t, vocab_size, use_bias=False, name="lm_head")
    return tokens, logits
