"""CNN model zoo: AlexNet, ResNet-50, InceptionV3.

Reference apps: examples/cpp/AlexNet/alexnet.cc:34-130 (canonical train
loop), examples/cpp/ResNet/resnet.cc (BottleneckBlock), examples/cpp/
InceptionV3/inception.cc (branchy graph — the op-parallel search showcase).
All NCHW through the native builder API.
"""

from __future__ import annotations

from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.model import FFModel


def alexnet(ff: FFModel, batch_size: int, num_classes: int = 1000):
    """reference: alexnet.cc:43-72 (229x229 input variant)."""
    x = ff.create_tensor([batch_size, 3, 229, 229], name="input")
    t = ff.conv2d(x, 64, 11, 11, 4, 4, 2, 2, ActiMode.AC_MODE_RELU, name="conv1")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool1")
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU, name="conv2")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool2")
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="conv3")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="conv4")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="conv5")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool5")
    t = ff.flat(t)
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU, name="fc6")
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU, name="fc7")
    t = ff.dense(t, num_classes, name="fc8")
    return x, t


def alexnet_cifar10(ff: FFModel, batch_size: int):
    """bootcamp_demo CIFAR10 AlexNet (32x32), the accuracy-gate config."""
    x = ff.create_tensor([batch_size, 3, 32, 32], name="input")
    t = ff.conv2d(x, 64, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU, name="conv1")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU, name="conv2")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool2")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="conv3")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool3")
    t = ff.flat(t)
    t = ff.dense(t, 512, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 10, name="fc2")
    return x, t


def _bottleneck(ff, t, out_channels, stride, i, downsample):
    """reference: resnet.cc BottleneckBlock — 1x1 reduce, 3x3, 1x1 expand,
    projection shortcut on stride/width change; BN after each conv."""
    shortcut = t
    c = out_channels
    b = ff.conv2d(t, c, 1, 1, 1, 1, 0, 0, name=f"res{i}_br1x1a")
    b = ff.batch_norm(b, relu=True, name=f"res{i}_bn1")
    b = ff.conv2d(b, c, 3, 3, stride, stride, 1, 1, name=f"res{i}_br3x3")
    b = ff.batch_norm(b, relu=True, name=f"res{i}_bn2")
    b = ff.conv2d(b, 4 * c, 1, 1, 1, 1, 0, 0, name=f"res{i}_br1x1b")
    b = ff.batch_norm(b, relu=False, name=f"res{i}_bn3")
    if downsample:
        shortcut = ff.conv2d(t, 4 * c, 1, 1, stride, stride, 0, 0,
                             name=f"res{i}_proj")
        shortcut = ff.batch_norm(shortcut, relu=False, name=f"res{i}_bnp")
    out = ff.add(b, shortcut, name=f"res{i}_add")
    return ff.relu(out, name=f"res{i}_relu")


def resnet50(ff: FFModel, batch_size: int, num_classes: int = 1000,
             image_size: int = 224):
    x = ff.create_tensor([batch_size, 3, image_size, image_size], name="input")
    t = ff.conv2d(x, 64, 7, 7, 2, 2, 3, 3, name="conv1")
    t = ff.batch_norm(t, relu=True, name="bn1")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1, name="pool1")
    i = 0
    for stage, (c, n, s) in enumerate([(64, 3, 1), (128, 4, 2),
                                       (256, 6, 2), (512, 3, 2)]):
        for blk in range(n):
            stride = s if blk == 0 else 1
            t = _bottleneck(ff, t, c, stride, i, downsample=(blk == 0))
            i += 1
    # global average pool
    h = t.dims[2]
    t = ff.pool2d(t, h, h, 1, 1, 0, 0, PoolType.POOL_AVG, name="gap")
    t = ff.flat(t)
    t = ff.dense(t, num_classes, name="fc")
    return x, t


def _inception_a(ff, t, pool_c, i):
    """reference: inception.cc InceptionA — 4 branches concat'd."""
    b1 = ff.conv2d(t, 64, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b1")
    b2 = ff.conv2d(t, 48, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b2a")
    b2 = ff.conv2d(b2, 64, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b2b")
    b3 = ff.conv2d(t, 64, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b3a")
    b3 = ff.conv2d(b3, 96, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b3b")
    b3 = ff.conv2d(b3, 96, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b3c")
    b4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG, name=f"iA{i}_b4a")
    b4 = ff.conv2d(b4, pool_c, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b4b")
    return ff.concat([b1, b2, b3, b4], axis=1, name=f"iA{i}_cat")


def inception_v3_stem(ff: FFModel, batch_size: int, num_classes: int = 1000):
    """InceptionV3 stem + 3x InceptionA + head (abridged but faithfully
    branchy — the op-parallel benefit shows in the A-blocks; reference
    inception.cc builds the full tower the same way)."""
    x = ff.create_tensor([batch_size, 3, 299, 299], name="input")
    t = ff.conv2d(x, 32, 3, 3, 2, 2, 0, 0, ActiMode.AC_MODE_RELU, name="c1")
    t = ff.conv2d(t, 32, 3, 3, 1, 1, 0, 0, ActiMode.AC_MODE_RELU, name="c2")
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="c3")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="p1")
    t = ff.conv2d(t, 80, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_RELU, name="c4")
    t = ff.conv2d(t, 192, 3, 3, 1, 1, 0, 0, ActiMode.AC_MODE_RELU, name="c5")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="p2")
    t = _inception_a(ff, t, 32, 0)
    t = _inception_a(ff, t, 64, 1)
    t = _inception_a(ff, t, 64, 2)
    h = t.dims[2]
    t = ff.pool2d(t, h, h, 1, 1, 0, 0, PoolType.POOL_AVG, name="gap")
    t = ff.flat(t)
    t = ff.dense(t, num_classes, name="fc")
    return x, t
