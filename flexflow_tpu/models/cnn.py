"""CNN model zoo: AlexNet, ResNet-50, InceptionV3.

Reference apps: examples/cpp/AlexNet/alexnet.cc:34-130 (canonical train
loop), examples/cpp/ResNet/resnet.cc (BottleneckBlock), examples/cpp/
InceptionV3/inception.cc (branchy graph — the op-parallel search showcase).
All NCHW through the native builder API.
"""

from __future__ import annotations

from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.model import FFModel


def alexnet(ff: FFModel, batch_size: int, num_classes: int = 1000):
    """reference: alexnet.cc:43-72 (229x229 input variant)."""
    x = ff.create_tensor([batch_size, 3, 229, 229], name="input")
    t = ff.conv2d(x, 64, 11, 11, 4, 4, 2, 2, ActiMode.AC_MODE_RELU, name="conv1")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool1")
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU, name="conv2")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool2")
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="conv3")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="conv4")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="conv5")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool5")
    t = ff.flat(t)
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU, name="fc6")
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU, name="fc7")
    t = ff.dense(t, num_classes, name="fc8")
    return x, t


def alexnet_cifar10(ff: FFModel, batch_size: int):
    """bootcamp_demo CIFAR10 AlexNet (32x32), the accuracy-gate config."""
    x = ff.create_tensor([batch_size, 3, 32, 32], name="input")
    t = ff.conv2d(x, 64, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU, name="conv1")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU, name="conv2")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool2")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="conv3")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool3")
    t = ff.flat(t)
    t = ff.dense(t, 512, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 10, name="fc2")
    return x, t


def _bottleneck(ff, t, out_channels, stride, i, downsample):
    """reference: resnet.cc BottleneckBlock — 1x1 reduce, 3x3, 1x1 expand,
    projection shortcut on stride/width change; BN after each conv."""
    shortcut = t
    c = out_channels
    b = ff.conv2d(t, c, 1, 1, 1, 1, 0, 0, name=f"res{i}_br1x1a")
    b = ff.batch_norm(b, relu=True, name=f"res{i}_bn1")
    b = ff.conv2d(b, c, 3, 3, stride, stride, 1, 1, name=f"res{i}_br3x3")
    b = ff.batch_norm(b, relu=True, name=f"res{i}_bn2")
    b = ff.conv2d(b, 4 * c, 1, 1, 1, 1, 0, 0, name=f"res{i}_br1x1b")
    b = ff.batch_norm(b, relu=False, name=f"res{i}_bn3")
    if downsample:
        shortcut = ff.conv2d(t, 4 * c, 1, 1, stride, stride, 0, 0,
                             name=f"res{i}_proj")
        shortcut = ff.batch_norm(shortcut, relu=False, name=f"res{i}_bnp")
    out = ff.add(b, shortcut, name=f"res{i}_add")
    return ff.relu(out, name=f"res{i}_relu")


def resnet50(ff: FFModel, batch_size: int, num_classes: int = 1000,
             image_size: int = 224):
    x = ff.create_tensor([batch_size, 3, image_size, image_size], name="input")
    t = ff.conv2d(x, 64, 7, 7, 2, 2, 3, 3, name="conv1")
    t = ff.batch_norm(t, relu=True, name="bn1")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1, name="pool1")
    i = 0
    for stage, (c, n, s) in enumerate([(64, 3, 1), (128, 4, 2),
                                       (256, 6, 2), (512, 3, 2)]):
        for blk in range(n):
            stride = s if blk == 0 else 1
            t = _bottleneck(ff, t, c, stride, i, downsample=(blk == 0))
            i += 1
    # global average pool
    h = t.dims[2]
    t = ff.pool2d(t, h, h, 1, 1, 0, 0, PoolType.POOL_AVG, name="gap")
    t = ff.flat(t)
    t = ff.dense(t, num_classes, name="fc")
    return x, t


def _inception_a(ff, t, pool_c, i):
    """reference: inception.cc InceptionA — 4 branches concat'd."""
    b1 = ff.conv2d(t, 64, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b1")
    b2 = ff.conv2d(t, 48, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b2a")
    b2 = ff.conv2d(b2, 64, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b2b")
    b3 = ff.conv2d(t, 64, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b3a")
    b3 = ff.conv2d(b3, 96, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b3b")
    b3 = ff.conv2d(b3, 96, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b3c")
    b4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG, name=f"iA{i}_b4a")
    b4 = ff.conv2d(b4, pool_c, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_RELU,
                   name=f"iA{i}_b4b")
    return ff.concat([b1, b2, b3, b4], axis=1, name=f"iA{i}_cat")


def _inception_b(ff, t, i):
    """Grid reduction 35->17 (reference: inception.cc InceptionB)."""
    r = ActiMode.AC_MODE_RELU
    b1 = ff.conv2d(t, 384, 3, 3, 2, 2, 0, 0, r, name=f"iB{i}_b1")
    b2 = ff.conv2d(t, 64, 1, 1, 1, 1, 0, 0, r, name=f"iB{i}_b2a")
    b2 = ff.conv2d(b2, 96, 3, 3, 1, 1, 1, 1, r, name=f"iB{i}_b2b")
    b2 = ff.conv2d(b2, 96, 3, 3, 2, 2, 0, 0, r, name=f"iB{i}_b2c")
    b3 = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name=f"iB{i}_b3")
    return ff.concat([b1, b2, b3], axis=1, name=f"iB{i}_cat")


def _inception_c(ff, t, c, i):
    """7x7-factorized block (reference: inception.cc InceptionC)."""
    r = ActiMode.AC_MODE_RELU
    b1 = ff.conv2d(t, 192, 1, 1, 1, 1, 0, 0, r, name=f"iC{i}_b1")
    b2 = ff.conv2d(t, c, 1, 1, 1, 1, 0, 0, r, name=f"iC{i}_b2a")
    b2 = ff.conv2d(b2, c, 1, 7, 1, 1, 0, 3, r, name=f"iC{i}_b2b")
    b2 = ff.conv2d(b2, 192, 7, 1, 1, 1, 3, 0, r, name=f"iC{i}_b2c")
    b3 = ff.conv2d(t, c, 1, 1, 1, 1, 0, 0, r, name=f"iC{i}_b3a")
    b3 = ff.conv2d(b3, c, 7, 1, 1, 1, 3, 0, r, name=f"iC{i}_b3b")
    b3 = ff.conv2d(b3, c, 1, 7, 1, 1, 0, 3, r, name=f"iC{i}_b3c")
    b3 = ff.conv2d(b3, c, 7, 1, 1, 1, 3, 0, r, name=f"iC{i}_b3d")
    b3 = ff.conv2d(b3, 192, 1, 7, 1, 1, 0, 3, r, name=f"iC{i}_b3e")
    b4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG, name=f"iC{i}_b4a")
    b4 = ff.conv2d(b4, 192, 1, 1, 1, 1, 0, 0, r, name=f"iC{i}_b4b")
    return ff.concat([b1, b2, b3, b4], axis=1, name=f"iC{i}_cat")


def _inception_d(ff, t, i):
    """Grid reduction 17->8 (reference: inception.cc InceptionD)."""
    r = ActiMode.AC_MODE_RELU
    b1 = ff.conv2d(t, 192, 1, 1, 1, 1, 0, 0, r, name=f"iD{i}_b1a")
    b1 = ff.conv2d(b1, 320, 3, 3, 2, 2, 0, 0, r, name=f"iD{i}_b1b")
    b2 = ff.conv2d(t, 192, 1, 1, 1, 1, 0, 0, r, name=f"iD{i}_b2a")
    b2 = ff.conv2d(b2, 192, 1, 7, 1, 1, 0, 3, r, name=f"iD{i}_b2b")
    b2 = ff.conv2d(b2, 192, 7, 1, 1, 1, 3, 0, r, name=f"iD{i}_b2c")
    b2 = ff.conv2d(b2, 192, 3, 3, 2, 2, 0, 0, r, name=f"iD{i}_b2d")
    b3 = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name=f"iD{i}_b3")
    return ff.concat([b1, b2, b3], axis=1, name=f"iD{i}_cat")


def _inception_e(ff, t, i):
    """Expanded-filter-bank block, 6-way concat (reference: InceptionE)."""
    r = ActiMode.AC_MODE_RELU
    b1 = ff.conv2d(t, 320, 1, 1, 1, 1, 0, 0, r, name=f"iE{i}_b1")
    b2i = ff.conv2d(t, 384, 1, 1, 1, 1, 0, 0, r, name=f"iE{i}_b2i")
    b2 = ff.conv2d(b2i, 384, 1, 3, 1, 1, 0, 1, r, name=f"iE{i}_b2a")
    b3 = ff.conv2d(b2i, 384, 3, 1, 1, 1, 1, 0, r, name=f"iE{i}_b2b")
    b4i = ff.conv2d(t, 448, 1, 1, 1, 1, 0, 0, r, name=f"iE{i}_b4i")
    b4i = ff.conv2d(b4i, 384, 3, 3, 1, 1, 1, 1, r, name=f"iE{i}_b4m")
    b4 = ff.conv2d(b4i, 384, 1, 3, 1, 1, 0, 1, r, name=f"iE{i}_b4a")
    b5 = ff.conv2d(b4i, 384, 3, 1, 1, 1, 1, 0, r, name=f"iE{i}_b4b")
    b6 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG, name=f"iE{i}_b6a")
    b6 = ff.conv2d(b6, 192, 1, 1, 1, 1, 0, 0, r, name=f"iE{i}_b6b")
    return ff.concat([b1, b2, b3, b4, b5, b6], axis=1, name=f"iE{i}_cat")


def inception_v3(ff: FFModel, batch_size: int, num_classes: int = 10,
                 image_size: int = 299):
    """Full InceptionV3 tower (reference: inception.cc:150-174 — stem, 3xA,
    B, 4xC, D, 2xE, 8x8 avg-pool head). The branchy graph is the op-parallel
    search showcase."""
    r = ActiMode.AC_MODE_RELU
    x = ff.create_tensor([batch_size, 3, image_size, image_size], name="input")
    t = ff.conv2d(x, 32, 3, 3, 2, 2, 0, 0, r, name="c1")
    t = ff.conv2d(t, 32, 3, 3, 1, 1, 0, 0, r, name="c2")
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, r, name="c3")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="p1")
    t = ff.conv2d(t, 80, 1, 1, 1, 1, 0, 0, r, name="c4")
    t = ff.conv2d(t, 192, 3, 3, 1, 1, 1, 1, r, name="c5")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="p2")
    t = _inception_a(ff, t, 32, 0)
    t = _inception_a(ff, t, 64, 1)
    t = _inception_a(ff, t, 64, 2)
    t = _inception_b(ff, t, 0)
    t = _inception_c(ff, t, 128, 0)
    t = _inception_c(ff, t, 160, 1)
    t = _inception_c(ff, t, 160, 2)
    t = _inception_c(ff, t, 192, 3)
    t = _inception_d(ff, t, 0)
    t = _inception_e(ff, t, 0)
    t = _inception_e(ff, t, 1)
    h = t.dims[2]
    t = ff.pool2d(t, h, h, 1, 1, 0, 0, PoolType.POOL_AVG, name="gap")
    t = ff.flat(t)
    t = ff.dense(t, num_classes, name="fc")
    return x, t


def candle_uno(ff: FFModel, batch_size: int,
               dense_layers=(1000, 1000, 1000),
               dense_feature_layers=(1000, 1000, 1000)):
    """CANDLE Uno drug-response MLP (reference: candle_uno.cc:29-126):
    7 inputs over 4 feature types, each through its own encoder tower (same
    structure, independent weights — matching the reference, which calls
    build_feature_model per input); encodings concat into a final MLP with
    scalar output. Returns (inputs dict, output tensor)."""
    feature_shapes = {"dose": 1, "cell.rnaseq": 942,
                      "drug.descriptors": 5270, "drug.fingerprints": 2048}
    input_features = {"dose1": "dose", "dose2": "dose",
                      "cell.rnaseq": "cell.rnaseq",
                      "drug1.descriptors": "drug.descriptors",
                      "drug1.fingerprints": "drug.fingerprints",
                      "drug2.descriptors": "drug.descriptors",
                      "drug2.fingerprints": "drug.fingerprints"}
    inputs = {}
    encoded = []
    for input_name, feat in input_features.items():
        safe = input_name.replace(".", "_")
        x = ff.create_tensor([batch_size, feature_shapes[feat]], name=safe)
        inputs[safe] = x
        t = x
        # per-feature-type encoder (towers share structure, not weights —
        # matching the reference, which builds a fresh build_feature_model
        # per input: candle_uno.cc:106-119)
        for li, width in enumerate(dense_feature_layers):
            t = ff.dense(t, width, ActiMode.AC_MODE_RELU,
                         name=f"{safe}_enc{li}")
        encoded.append(t)
    out = ff.concat(encoded, axis=1, name="cat")
    for li, width in enumerate(dense_layers):
        out = ff.dense(out, width, ActiMode.AC_MODE_RELU, name=f"mlp{li}")
    out = ff.dense(out, 1, name="out")
    return inputs, out


def inception_v3_stem(ff: FFModel, batch_size: int, num_classes: int = 1000,
                      image_size: int = 299):
    """InceptionV3 stem + 3x InceptionA + head (abridged but faithfully
    branchy — the op-parallel benefit shows in the A-blocks; reference
    inception.cc builds the full tower the same way)."""
    x = ff.create_tensor([batch_size, 3, image_size, image_size],
                         name="input")
    t = ff.conv2d(x, 32, 3, 3, 2, 2, 0, 0, ActiMode.AC_MODE_RELU, name="c1")
    t = ff.conv2d(t, 32, 3, 3, 1, 1, 0, 0, ActiMode.AC_MODE_RELU, name="c2")
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="c3")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="p1")
    t = ff.conv2d(t, 80, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_RELU, name="c4")
    t = ff.conv2d(t, 192, 3, 3, 1, 1, 0, 0, ActiMode.AC_MODE_RELU, name="c5")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="p2")
    t = _inception_a(ff, t, 32, 0)
    t = _inception_a(ff, t, 64, 1)
    t = _inception_a(ff, t, 64, 2)
    h = t.dims[2]
    t = ff.pool2d(t, h, h, 1, 1, 0, 0, PoolType.POOL_AVG, name="gap")
    t = ff.flat(t)
    t = ff.dense(t, num_classes, name="fc")
    return x, t
