"""NMT: LSTM encoder-decoder seq2seq.

Reference: the standalone nmt/ subsystem (rnn.cu, lstm.cu, nmt.cc:31-99 —
2 layers, seq 20->40, hidden/embed 2048, vocab 20k, hand-scheduled pipeline
over per-(layer,timestep) ParallelConfigs). Here the model is ordinary graph
ops; pipelining comes from the 'pipe' axis utilities instead of the
reference's per-timestep device tables, and the SoftmaxDP data-parallel
softmax is just the softmax op under a data-parallel strategy.
"""

from __future__ import annotations

from flexflow_tpu.ffconst import DataType
from flexflow_tpu.model import FFModel


def nmt_seq2seq(ff: FFModel, batch_size: int,
                src_len: int = 20, tgt_len: int = 20,
                embed_size: int = 2048, hidden_size: int = 2048,
                vocab_size: int = 20_000, num_layers: int = 2):
    """Returns (src_input, tgt_input, logits). Teacher-forced decoder: encoder
    final state feeds the decoder via concat of encoder context (simplified
    vs cuDNN state-passing; the reference also feeds full chunked states)."""
    src = ff.create_tensor([batch_size, src_len], dtype=DataType.DT_INT32,
                           name="src_tokens")
    tgt = ff.create_tensor([batch_size, tgt_len], dtype=DataType.DT_INT32,
                           name="tgt_tokens")
    enc = ff.embedding(src, vocab_size, embed_size, name="src_embed")
    for i in range(num_layers):
        enc = ff.lstm(enc, hidden_size, name=f"enc_lstm_{i}")
    # context = mean over source positions (stand-in for final-state passing)
    ctx = ff.mean(enc, dims=[1], keepdims=True, name="enc_context")

    dec = ff.embedding(tgt, vocab_size, embed_size, name="tgt_embed")
    for i in range(num_layers):
        dec = ff.lstm(dec, hidden_size, name=f"dec_lstm_{i}")
    # broadcast-add context to every decoder position
    dec = ff.add(dec, ctx, name="ctx_add")
    logits = ff.dense(dec, vocab_size, name="vocab_proj")
    return src, tgt, logits
