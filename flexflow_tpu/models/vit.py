"""Vision Transformer (patchify-conv + pre-norm encoder).

Net-new model family vs the reference zoo (its vision workloads are all
CNNs — examples/cpp/{AlexNet,ResNet,InceptionV3}); built entirely from
existing graph ops: Conv2D patch embedding (kernel=stride=patch),
reshape/transpose to (B, N, hidden), pre-norm MHA blocks with RoPE over
the patch sequence (rotary ViT — no learned positional table needed, and
positions stay absolute under sequence sharding), GELU MLP, mean-pool
head. Shapes default head_dim-64; pass heads to hit head_dim 128 on TPU
(see the round-3 MFU probe finding).
"""

from __future__ import annotations

from flexflow_tpu.ffconst import ActiMode
from flexflow_tpu.model import FFModel


def vit(ff: FFModel, batch_size: int, image_size: int = 224,
        patch_size: int = 16, hidden: int = 384, layers: int = 6,
        heads: int = 6, mlp_ratio: int = 4, num_classes: int = 1000,
        channels: int = 3):
    assert image_size % patch_size == 0, \
        f"image {image_size} not divisible by patch {patch_size}"
    grid = image_size // patch_size
    n_patches = grid * grid

    x = ff.create_tensor([batch_size, channels, image_size, image_size],
                         name="input")
    # non-overlapping patch embedding: one conv with kernel == stride
    t = ff.conv2d(x, hidden, patch_size, patch_size, patch_size, patch_size,
                  0, 0, name="patch_embed")
    # (B, hidden, g, g) -> (B, N, hidden)
    t = ff.reshape(t, [batch_size, hidden, n_patches], name="patch_flat")
    t = ff.transpose(t, [0, 2, 1], name="patch_seq")
    for i in range(layers):
        a = ff.layer_norm(t, name=f"ln1_{i}")
        a = ff.multihead_attention(a, a, a, hidden, heads, rope=True,
                                   name=f"attn_{i}")
        t = ff.add(t, a, name=f"res1_{i}")
        m = ff.layer_norm(t, name=f"ln2_{i}")
        m = ff.dense(m, hidden * mlp_ratio, ActiMode.AC_MODE_GELU,
                     name=f"mlp_up_{i}")
        m = ff.dense(m, hidden, name=f"mlp_down_{i}")
        t = ff.add(t, m, name=f"res2_{i}")
    t = ff.layer_norm(t, name="ln_f")
    t = ff.mean(t, [1], name="pool")          # mean over patches
    logits = ff.dense(t, num_classes, name="head")
    return x, logits
