"""Transformer builders.

`build_reference_transformer` reproduces the reference benchmark app
(examples/cpp/Transformer/transformer.cc:30-140: encoder-decoder of
MHA + residual + 2xdense blocks, defaults hidden 512 / 16 heads / 12 layers /
seq 128, MSE regression head, SGD 0.01).

`build_encoder_classifier` is the modern variant (pre-LN, GELU FFN, causal
option) used as the flagship bench model.
"""

from __future__ import annotations

import dataclasses

from flexflow_tpu.ffconst import ActiMode
from flexflow_tpu.model import FFModel


@dataclasses.dataclass
class TransformerConfig:
    hidden_size: int = 512
    embedding_size: int = 512
    num_heads: int = 16
    num_layers: int = 12
    sequence_length: int = 128


def attention_encoder_decoder(ff: FFModel, x1, x2, hidden_dim, num_heads, i):
    """One reference layer (transformer.cc:39-56): self-attn + residual +
    dense(relu)+dense on each stream, plus cross-attention on stream 2."""
    t1 = ff.add(ff.multihead_attention(x1, x1, x1, hidden_dim, num_heads,
                                       name=f"enc_attn_{i}"), x1)
    t1 = ff.dense(ff.dense(t1, hidden_dim, ActiMode.AC_MODE_RELU,
                           name=f"enc_ff1_{i}"),
                  hidden_dim, name=f"enc_ff2_{i}")
    t2 = ff.add(ff.multihead_attention(x2, x2, x2, hidden_dim, num_heads,
                                       name=f"dec_self_attn_{i}"), x2)
    t2 = ff.add(ff.multihead_attention(t2, t1, t1, hidden_dim, num_heads,
                                       name=f"dec_cross_attn_{i}"), t2)
    t2 = ff.dense(ff.dense(t2, hidden_dim, ActiMode.AC_MODE_RELU,
                           name=f"dec_ff1_{i}"),
                  hidden_dim, name=f"dec_ff2_{i}")
    return t1, t2


def build_reference_transformer(ff: FFModel, batch_size: int,
                                cfg: TransformerConfig = None):
    cfg = cfg or TransformerConfig()
    x = ff.create_tensor([batch_size, cfg.sequence_length, cfg.hidden_size],
                         name="input")
    t1 = t2 = x
    for i in range(cfg.num_layers):
        t1, t2 = attention_encoder_decoder(ff, t1, t2, cfg.hidden_size,
                                           cfg.num_heads, i)
    out = ff.dense(t2, 1, name="regression_head")
    return x, out


def build_seq2seq_transformer(ff: FFModel, batch_size: int,
                              src_len: int = 128, tgt_len: int = 64,
                              hidden: int = 512, layers: int = 4,
                              heads: int = 8, ffn_mult: int = 4,
                              vocab_size: int = 0):
    """Modern encoder-decoder transformer with DISTINCT source/target
    lengths: pre-LN encoder; decoder = causal self-attention + (non-causal)
    cross-attention over the encoder states + FFN per layer. The
    sq != sk cross-attention runs on the flash kernel when eligible — the
    workload class the reference's vendor kernel served with distinct
    q/kv lengths (attention.cu:533-570) and its Transformer app built as
    twin streams (transformer.cc:39-56; see build_reference_transformer
    for the faithful twin-stream port).

    Returns (src_input, tgt_input, out): out is per-target-position
    hidden states, projected to vocab_size logits when vocab_size > 0
    (seq2seq LM head) else raw (B, tgt_len, hidden)."""
    src = ff.create_tensor([batch_size, src_len, hidden], name="src")
    tgt = ff.create_tensor([batch_size, tgt_len, hidden], name="tgt")
    e = src
    for i in range(layers):
        e = encoder_block(ff, e, hidden, heads, ffn_mult, f"enc{i}")
    e = ff.layer_norm(e, name="enc_ln_f")
    d = tgt
    for i in range(layers):
        a = ff.layer_norm(d, name=f"dec_ln1_{i}")
        a = ff.multihead_attention(a, a, a, hidden, heads, causal=True,
                                   name=f"dec_self_{i}")
        d = ff.add(d, a, name=f"dec_res1_{i}")
        c = ff.layer_norm(d, name=f"dec_ln2_{i}")
        c = ff.multihead_attention(c, e, e, hidden, heads,
                                   name=f"dec_cross_{i}")
        d = ff.add(d, c, name=f"dec_res2_{i}")
        f = ff.layer_norm(d, name=f"dec_ln3_{i}")
        f = ff.dense(f, hidden * ffn_mult, ActiMode.AC_MODE_GELU,
                     name=f"dec_ffn1_{i}")
        f = ff.dense(f, hidden, name=f"dec_ffn2_{i}")
        d = ff.add(d, f, name=f"dec_res3_{i}")
    d = ff.layer_norm(d, name="dec_ln_f")
    if vocab_size > 0:
        d = ff.dense(d, vocab_size, use_bias=False, name="lm_head")
    return src, tgt, d


def encoder_block(ff: FFModel, x, hidden, heads, ffn_mult, i, causal=False,
                  dropout=0.0):
    """Pre-LN block: x + MHA(LN(x)); x + FFN(LN(x)) with GELU."""
    a = ff.layer_norm(x, name=f"ln1_{i}")
    a = ff.multihead_attention(a, a, a, hidden, heads, dropout=dropout,
                               causal=causal, name=f"attn_{i}")
    x = ff.add(x, a, name=f"res1_{i}")
    f = ff.layer_norm(x, name=f"ln2_{i}")
    f = ff.dense(f, hidden * ffn_mult, ActiMode.AC_MODE_GELU, name=f"ffn1_{i}")
    f = ff.dense(f, hidden, name=f"ffn2_{i}")
    return ff.add(x, f, name=f"res2_{i}")


def build_encoder_classifier(ff: FFModel, batch_size: int, seq_len: int = 128,
                             hidden: int = 512, layers: int = 6, heads: int = 8,
                             ffn_mult: int = 4, num_classes: int = 16,
                             causal: bool = False):
    x = ff.create_tensor([batch_size, seq_len, hidden], name="input")
    t = x
    fused = getattr(ff.config, "use_fused_ln", False)
    # one graph, two lowerings of each residual-add + following layernorm
    # pair: fused (one Pallas pass, FFConfig.use_fused_ln) or separate ops.
    # Same math, same norm-parameter count (2L+1) either way; in the fused
    # form the last add_ln's normed output IS ln_f.
    n = ff.layer_norm(t, name="ln1_0") if fused else None
    for i in range(layers):
        if fused:
            a = ff.multihead_attention(n, n, n, hidden, heads, causal=causal,
                                       name=f"attn_{i}")
            t, n = ff.add_layer_norm(t, a, name=f"res1_ln2_{i}")
            f = ff.dense(n, hidden * ffn_mult, ActiMode.AC_MODE_GELU,
                         name=f"ffn1_{i}")
            f = ff.dense(f, hidden, name=f"ffn2_{i}")
            t, n = ff.add_layer_norm(t, f, name=f"res2_ln1_{i}")
        else:
            t = encoder_block(ff, t, hidden, heads, ffn_mult, i, causal)
    t = n if fused else ff.layer_norm(t, name="ln_f")
    t = ff.mean(t, dims=[1], name="pool")
    out = ff.dense(t, num_classes, name="head")
    return x, out


def seq2seq_lm(ff: FFModel, batch_size: int, src_len: int = 32,
               tgt_len: int = 32, hidden: int = 128, layers: int = 2,
               heads: int = 4, ffn_mult: int = 4,
               vocab_size: int = 1000, rope_theta: float = 10000.0):
    """Token-level encoder-decoder LM, the GENERATION-capable member of
    the seq2seq family (build_seq2seq_transformer is the hidden-state
    twin of the reference's Transformer app). Positions come from RoPE
    inside every SELF-attention (encoder bidirectional, decoder causal);
    cross-attention carries no positional rotation — position info is
    already mixed into both streams by their self-attentions. This is
    the layout Seq2SeqGenerator decodes with a KV cache on decoder
    self-attention and a STATIC projected k/v for cross-attention.

    Returns (src_tokens, tgt_tokens, logits) with logits
    (B, tgt_len, vocab)."""
    from flexflow_tpu.ffconst import DataType

    src = ff.create_tensor([batch_size, src_len], dtype=DataType.DT_INT32,
                           name="src")
    tgt = ff.create_tensor([batch_size, tgt_len], dtype=DataType.DT_INT32,
                           name="tgt")
    e = ff.embedding(src, vocab_size, hidden, name="src_embed")
    for i in range(layers):
        a = ff.layer_norm(e, name=f"s2s_enc_ln1_{i}")
        a = ff.multihead_attention(a, a, a, hidden, heads, rope=True,
                                   rope_theta=rope_theta,
                                   name=f"s2s_enc_attn_{i}")
        e = ff.add(e, a, name=f"s2s_enc_res1_{i}")
        f = ff.layer_norm(e, name=f"s2s_enc_ln2_{i}")
        f = ff.dense(f, hidden * ffn_mult, ActiMode.AC_MODE_GELU,
                     name=f"s2s_enc_ffn1_{i}")
        f = ff.dense(f, hidden, name=f"s2s_enc_ffn2_{i}")
        e = ff.add(e, f, name=f"s2s_enc_res2_{i}")
    e = ff.layer_norm(e, name="s2s_enc_ln_f")

    d = ff.embedding(tgt, vocab_size, hidden, name="tgt_embed")
    for i in range(layers):
        a = ff.layer_norm(d, name=f"s2s_dec_ln1_{i}")
        a = ff.multihead_attention(a, a, a, hidden, heads, causal=True,
                                   rope=True, rope_theta=rope_theta,
                                   name=f"s2s_dec_self_{i}")
        d = ff.add(d, a, name=f"s2s_dec_res1_{i}")
        c = ff.layer_norm(d, name=f"s2s_dec_ln2_{i}")
        c = ff.multihead_attention(c, e, e, hidden, heads,
                                   name=f"s2s_dec_cross_{i}")
        d = ff.add(d, c, name=f"s2s_dec_res2_{i}")
        f = ff.layer_norm(d, name=f"s2s_dec_ln3_{i}")
        f = ff.dense(f, hidden * ffn_mult, ActiMode.AC_MODE_GELU,
                     name=f"s2s_dec_ffn1_{i}")
        f = ff.dense(f, hidden, name=f"s2s_dec_ffn2_{i}")
        d = ff.add(d, f, name=f"s2s_dec_res3_{i}")
    d = ff.layer_norm(d, name="s2s_dec_ln_f")
    logits = ff.dense(d, vocab_size, use_bias=False, name="s2s_lm_head")
    return src, tgt, logits
