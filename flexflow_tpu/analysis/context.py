"""Shared strategy resolution for the analysis passes.

Resolves each op's *effective* ParallelConfig + axis_map the same way the
executor will (`runtime/executor.py resolve_axis_map`, defaults from
`GraphExecutor._resolve_strategies`) — but collects problems as Violations
instead of raising, and NEVER builds a `jax.sharding.Mesh` or traces a
program. Everything downstream (legality block math, perf costing) reads
from this one resolution so the analyzer and the executor cannot disagree
about what a strategy means.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.analysis.report import Violation
from flexflow_tpu.ops.base import InputOp, Op
from flexflow_tpu.parallel.pconfig import (CONTRACT, EXPERT, STAGE,
                                           ParallelConfig)

AxisMap = Dict[str, Optional[int]]


@dataclasses.dataclass
class OpResolution:
    op: Op
    pc: ParallelConfig
    axis_map: AxisMap             # validated entries only (bad axes dropped)
    from_table: bool              # False = default (DP/replicated) applied
    explicit_axis_map: bool       # pc.axis_map was present (vs degree-derived)


class AnalysisContext:
    """Static view of (op graph, strategy table, mesh shape)."""

    def __init__(self, model, strategies: Dict[str, ParallelConfig],
                 mesh_shape: Dict[str, int]):
        self.model = model
        self.strategies = dict(strategies or {})
        self.mesh_shape = dict(mesh_shape or {})
        self.num_devices = 1
        for v in self.mesh_shape.values():
            self.num_devices *= v
        self.ops: List[Op] = [op for op in model.ops
                              if not isinstance(op, InputOp)]
        self.op_names = {op.name for op in model.ops}
        self.resolutions: Dict[str, OpResolution] = {}
        self.violations: List[Violation] = []
        self._resolve_all()

    # ---- resolution --------------------------------------------------------

    def _resolve_all(self) -> None:
        for name in self.strategies:
            if name not in self.op_names:
                self.violations.append(Violation(
                    code="unknown-op", pass_name="legality",
                    severity="warning", op_name=name,
                    message=(f"strategy table names {name!r} but the graph "
                             f"has no such op (graph ops: "
                             f"{sorted(self.op_names)[:8]}...) — the entry "
                             f"is dead and will be ignored")))
        for op in self.ops:
            self.resolutions[op.name] = self._resolve_op(op)

    def _default_pc(self, ndims: int) -> ParallelConfig:
        # mirror GraphExecutor._resolve_strategies defaults
        if "data" in self.mesh_shape:
            return ParallelConfig.data_parallel(
                ndims, self.mesh_shape.get("data", 1))
        return ParallelConfig.replicated(ndims)

    def _resolve_op(self, op: Op) -> OpResolution:
        ndims = op.outputs[0].num_dims
        pc = self.strategies.get(op.name)
        from_table = pc is not None
        if pc is None:
            pc = self._default_pc(ndims)
        if pc.axis_map is not None:
            am = self._validate_axis_map(op, pc, ndims)
            return OpResolution(op, pc, am, from_table, True)
        # degree-only entry (reference-written file): greedy resolution,
        # identical to the executor's
        from flexflow_tpu.runtime.executor import resolve_axis_map

        try:
            # strip the axis_map=None path's validations by construction:
            # resolve_axis_map only raises for unresolvable degrees here
            am = resolve_axis_map(pc, self.mesh_shape, ndims)
        except ValueError as e:
            self.violations.append(Violation(
                code="degree-unresolvable", pass_name="legality",
                severity="error", op_name=op.name, message=str(e)))
            am = {}
        return OpResolution(op, pc, am, from_table, False)

    def _validate_axis_map(self, op: Op, pc: ParallelConfig,
                           ndims: int) -> AxisMap:
        am: AxisMap = {}
        for ax, d in pc.axis_map.items():
            if d is not None and ax not in self.mesh_shape:
                self.violations.append(Violation(
                    code="axis-unknown", pass_name="legality",
                    severity="error", op_name=op.name,
                    message=(f"axis_map references mesh axis {ax!r} absent "
                             f"from this mesh {self.mesh_shape} — the "
                             f"strategy was produced for a different mesh; "
                             f"regenerate it or rename the mesh axes")))
                continue
            if d is not None and d not in (CONTRACT, STAGE, EXPERT) \
                    and not (0 <= d < ndims):
                self.violations.append(Violation(
                    code="dim-out-of-range", pass_name="legality",
                    severity="error", op_name=op.name,
                    message=(f"axis_map maps mesh axis {ax!r} to tensor dim "
                             f"{d}, outside this op's output rank {ndims} "
                             f"(valid: 0..{ndims - 1} or the "
                             f"CONTRACT/STAGE/EXPERT sentinels) — the "
                             f"@axismap record is corrupt or was written "
                             f"for a different operator")))
                continue
            am[ax] = d
        return am

    # ---- derived quantities ------------------------------------------------

    def parts(self, am: AxisMap) -> int:
        """Total partition count (weights included: CONTRACT/STAGE count)."""
        n = 1
        for ax, d in (am or {}).items():
            if d is not None:
                n *= self.mesh_shape.get(ax, 1)
        return n

    def dim_degree(self, am: AxisMap, dim: int) -> int:
        n = 1
        for ax, d in (am or {}).items():
            if d == dim:
                n *= self.mesh_shape.get(ax, 1)
        return n

    def axes_of(self, am: AxisMap, dim: int) -> List[str]:
        return [ax for ax, d in (am or {}).items() if d == dim]

    def op_block(self, res: OpResolution) -> Optional[Tuple[int, int]]:
        """(place, ndev) the placement lowering would give this op, or None
        when the device list itself is illegal (a separate violation already
        covers it). Mirror of parallel/placement.py op_block, minus the
        raise."""
        from flexflow_tpu.search.cost_model import align_place

        D = self.num_devices
        parts = max(1, min(self.parts(res.axis_map), D))
        ndev = parts
        place = 0
        ids = res.pc.device_ids
        if ids:
            if len(ids) < parts:
                return None  # device-block-too-small violation elsewhere
            place = min(ids)
            n = len(ids)
            if 1 <= n <= D and D % n == 0:
                ndev = n
        if ndev >= D or D % ndev != 0:
            return 0, D
        return align_place(place, ndev, D), ndev
