"""ffsan lock-graph extraction — the shared AST substrate both source
passes consume.

One parse of every target file produces a ``LockGraph``:

  * which locks exist (factory calls ``locks.make_*("name")`` on module
    globals and ``self.<attr>`` assignments, resolved to their declared
    hierarchy names) and where raw ``threading`` primitives bypass the
    registry;
  * per function/method: which locks it acquires directly (``with``
    regions and ``.acquire()`` calls), which calls it makes while
    holding them, its blocking calls (jit dispatch,
    ``block_until_ready``, cv ``wait``, thread ``join``, ``sleep``,
    orbax IO), its statement-level ``jnp.*`` dispatches, uncommitted
    ``device_put`` sites, and shape-dependent slices of device arrays;
  * the intra-repo call graph — ``self.method()``, module functions,
    sibling-module functions (``flightrec.trip``), and
    ``self.<attr>.method()`` where the attribute's class is known from
    an ``__init__`` assignment — so acquisition and blocking sets
    propagate transitively and an inversion buried two calls deep still
    names the call site that closes the cycle.

Nested ``def``/``lambda`` bodies are deliberately NOT part of the
enclosing function's held-lock context: they execute later (they are
usually traced-program builders handed to jit), so a ``jnp.*`` call
inside one is the NORMAL pattern, not a hazard.

Waivers: ``# ffsan: allow(code[,code])`` anywhere on the statement's
source lines suppresses that code there — the escape hatch for
documented by-design sites (the pragma should say why).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

_FACTORIES = {"make_lock", "make_rlock", "make_condition"}
_RAW_PRIMITIVES = {"Lock", "RLock", "Condition"}
_PRAGMA_RE = re.compile(r"#\s*ffsan:\s*allow\(([^)]*)\)")


def dotted(node: ast.AST) -> str:
    """'jax.numpy.zeros' for nested Attribute/Name chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class FuncInfo:
    """Everything one function/method contributes to the graph."""

    def __init__(self, module: str, qualname: str, path: str, line: int):
        self.module = module
        self.qualname = qualname
        self.path = path
        self.line = line
        self.key = (module, qualname)
        # lock name -> first acquisition site (path, line)
        self.acquires: Dict[str, Tuple[str, int]] = {}
        # direct nested acquisitions: (outer, inner, path, line)
        self.edges: List[Tuple[str, str, str, int]] = []
        # calls made while holding locks:
        #   (held names tuple, callee key or None, callee text, path, line)
        self.calls_under: List[Tuple[Tuple[str, ...],
                                     Optional[Tuple[str, str]],
                                     str, str, int]] = []
        # every resolvable call (held or not) for transitive propagation
        self.calls: Set[Tuple[str, str]] = set()
        # blocking operations: (marker, waived-lock-name or None, path,
        # line); the waived name is the cv a ``wait`` releases — held
        # locks OTHER than it are still held across the block
        self.blocking: List[Tuple[str, Optional[str], str, int]] = []
        # the subset that happens while THIS function holds locks:
        # (held names, marker, waived, path, line)
        self.held_blocking: List[Tuple[Tuple[str, ...], str,
                                       Optional[str], str, int]] = []
        # statement-level jnp dispatches: (dotted name, path, line)
        self.jnp_calls: List[Tuple[str, str, int]] = []
        # uncommitted device_put sites: (path, line)
        self.uncommitted_puts: List[Tuple[str, int]] = []
        # shape-dependent slices of device arrays: (var, path, line)
        self.device_slices: List[Tuple[str, str, int]] = []

    # filled by the fixpoint
    trans_acquires: Dict[str, Tuple[str, int]]
    trans_blocking: List[Tuple[str, Optional[str], str, int]]


class ModuleInfo:
    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path
        self.global_locks: Dict[str, str] = {}    # var -> lock name
        # class name -> {"attr_locks": {attr: name},
        #                "attr_types": {attr: class name}}
        self.classes: Dict[str, Dict] = {}
        self.functions: Dict[str, FuncInfo] = {}  # qualname -> info
        self.aliases: Set[str] = set()            # sibling-module names
        # raw threading primitive creations: (kind, path, line)
        self.raw_locks: List[Tuple[str, str, int]] = []
        # factory calls with a non-literal / unknown name argument
        self.unknown_factory: List[Tuple[str, str, int]] = []


class LockGraph:
    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[Tuple[str, str], FuncInfo] = {}
        self.class_owner: Dict[str, str] = {}     # class name -> module
        # file -> {line -> set of allowed codes}
        self.pragmas: Dict[str, Dict[int, Set[str]]] = {}

    def allowed(self, code: str, path: str, node: ast.AST) -> bool:
        lines = self.pragmas.get(path)
        if not lines:
            return False
        lo = getattr(node, "lineno", 0)
        hi = getattr(node, "end_lineno", lo) or lo
        return any(code in lines.get(ln, ()) for ln in range(lo, hi + 1))

    def allowed_at(self, code: str, path: str, line: int) -> bool:
        lines = self.pragmas.get(path)
        return bool(lines) and code in lines.get(line, set())


def _scan_pragmas(path: str, source: str) -> Dict[int, Set[str]]:
    """Pragmas apply to their own line; a pragma on a comment-only line
    also covers the following comment lines and the FIRST code line
    after them (the idiomatic justification-block placement)."""
    out: Dict[int, Set[str]] = {}
    lines = source.splitlines()
    for i, text in enumerate(lines, 1):
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        out.setdefault(i, set()).update(codes)
        if text.strip().startswith("#"):
            j = i + 1
            while j <= len(lines) and lines[j - 1].strip().startswith("#"):
                out.setdefault(j, set()).update(codes)
                j += 1
            if j <= len(lines):
                out.setdefault(j, set()).update(codes)
    return out


def _factory_name(call: ast.Call) -> Optional[str]:
    """'engine' for ``locks.make_rlock("engine")``; None otherwise."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _FACTORIES \
            or isinstance(fn, ast.Name) and fn.id in _FACTORIES:
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return call.args[0].value
        return "?"      # non-literal name: flagged separately
    return None


def _raw_primitive(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _RAW_PRIMITIVES \
            and dotted(fn).startswith("threading."):
        return fn.attr
    return None


class _Collector(ast.NodeVisitor):
    """Pass 1 over a module: lock declarations, attribute types, raw
    primitives, imports of sibling runtime modules."""

    def __init__(self, mod: ModuleInfo, known_classes: Set[str]):
        self.mod = mod
        self.known_classes = known_classes
        self._class: Optional[str] = None

    def visit_ImportFrom(self, node: ast.ImportFrom):
        for a in node.names:
            self.mod.aliases.add(a.asname or a.name.split(".")[-1])

    def visit_Import(self, node: ast.Import):
        for a in node.names:
            self.mod.aliases.add(a.asname or a.name.split(".")[0])

    def visit_ClassDef(self, node: ast.ClassDef):
        prev, self._class = self._class, node.name
        self.mod.classes.setdefault(
            node.name, {"attr_locks": {}, "attr_types": {}})
        self.generic_visit(node)
        self._class = prev

    def visit_Assign(self, node: ast.Assign):
        if isinstance(node.value, ast.Call):
            name = _factory_name(node.value)
            kind = _raw_primitive(node.value)
            for tgt in node.targets:
                if name is not None:
                    if name == "?":
                        self.mod.unknown_factory.append(
                            ("non-literal lock name", self.mod.path,
                             node.lineno))
                    elif isinstance(tgt, ast.Name):
                        self.mod.global_locks[tgt.id] = name
                    elif self._is_self_attr(tgt):
                        self.mod.classes[self._class]["attr_locks"][
                            tgt.attr] = name
                elif self._is_self_attr(tgt):
                    cls = dotted(node.value.func).split(".")[-1]
                    if cls in self.known_classes:
                        self.mod.classes[self._class]["attr_types"][
                            tgt.attr] = cls
            if kind is not None:
                self.mod.raw_locks.append(
                    (kind, self.mod.path, node.lineno))
            return      # the Call is consumed; don't double-count
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        kind = _raw_primitive(node)
        if kind is not None:
            self.mod.raw_locks.append((kind, self.mod.path, node.lineno))
        self.generic_visit(node)

    def _is_self_attr(self, tgt) -> bool:
        return (self._class is not None and isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self")


class _FuncWalker(ast.NodeVisitor):
    """Pass 2 over one function body: held-lock regions, calls,
    blocking ops, jnp dispatch, device_put commitment, device slices.
    Does NOT descend into nested def/lambda (deferred execution)."""

    _BLOCKING_TAILS = {"block_until_ready": "block_until_ready"}

    def __init__(self, graph: LockGraph, mod: ModuleInfo,
                 cls: Optional[str], info: FuncInfo):
        self.graph = graph
        self.mod = mod
        self.cls = cls
        self.info = info
        self.held: List[str] = []
        # vars assigned from jax/jnp calls in THIS function (device
        # arrays a Python-level slice would retrace on)
        self.device_vars: Set[str] = set()

    # -- deferred bodies are not part of this function's lock context --
    def visit_FunctionDef(self, node):      # noqa: N802
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    # -- lock resolution --
    def _resolve_lock(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.mod.global_locks.get(node.id)
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" and self.cls:
            return self.mod.classes[self.cls]["attr_locks"].get(node.attr)
        return None

    def visit_With(self, node: ast.With):
        names = []
        for item in node.items:
            name = self._resolve_lock(item.context_expr)
            if name is not None:
                self._note_acquire(name, node)
                names.append(name)
            else:
                self.visit(item.context_expr)
        self.held.extend(names)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(names):]

    def _note_acquire(self, name: str, node: ast.AST):
        self.info.acquires.setdefault(name,
                                      (self.mod.path, node.lineno))
        for outer in self.held:
            self.info.edges.append(
                (outer, name, self.mod.path, node.lineno))

    # -- assignments: device-array provenance --
    def visit_Assign(self, node: ast.Assign):
        self.visit(node.value)
        is_dev = isinstance(node.value, ast.Call) and (
            dotted(node.value.func).startswith(("jnp.", "jax.", "lax."))
            or dotted(node.value.func).endswith("_compiled_call"))
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if is_dev:
                    self.device_vars.add(tgt.id)
                else:
                    self.device_vars.discard(tgt.id)
            else:
                self.visit(tgt)

    # -- subscripts: shape-dependent slicing of device arrays --
    def visit_Subscript(self, node: ast.Subscript):
        if isinstance(node.value, ast.Name) \
                and node.value.id in self.device_vars \
                and isinstance(node.slice, ast.Slice):
            bounds = [b for b in (node.slice.lower, node.slice.upper,
                                  node.slice.step) if b is not None]
            if bounds and not all(isinstance(b, ast.Constant)
                                  for b in bounds):
                self.info.device_slices.append(
                    (node.value.id, self.mod.path, node.lineno))
        self.generic_visit(node)

    # -- calls --
    def visit_Call(self, node: ast.Call):
        text = dotted(node.func)
        callee = self._resolve_callee(node)
        if callee is not None:
            self.info.calls.add(callee)
        if self.held:
            self.info.calls_under.append(
                (tuple(self.held), callee, text or "<dynamic>",
                 self.mod.path, node.lineno))
        self._classify(node, text)
        self.generic_visit(node)

    def _resolve_callee(self, node: ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name):        # module-level function
            if fn.id in self.mod.functions:
                return (self.mod.name, fn.id)
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        base = fn.value
        if isinstance(base, ast.Name):
            if base.id == "self" and self.cls:      # self.method()
                q = f"{self.cls}.{fn.attr}"
                if q in self.mod.functions:
                    return (self.mod.name, q)
                return None
            if base.id in self.mod.aliases:         # flightrec.trip()
                target = self.graph.modules.get(base.id)
                if target and fn.attr in target.functions:
                    return (base.id, fn.attr)
            return None
        # self.<attr>.method() with a known attribute class
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and self.cls:
            cls = self.mod.classes[self.cls]["attr_types"].get(base.attr)
            if cls:
                owner = self.graph.class_owner.get(cls)
                if owner is not None:
                    q = f"{cls}.{fn.attr}"
                    if q in self.graph.modules[owner].functions:
                        return (owner, q)
        return None

    def _note_blocking(self, marker: str, waived: Optional[str],
                       path: str, line: int):
        self.info.blocking.append((marker, waived, path, line))
        if self.held:
            self.info.held_blocking.append(
                (tuple(self.held), marker, waived, path, line))

    def _classify(self, node: ast.Call, text: str):
        path, line = self.mod.path, node.lineno
        fn = node.func
        tail = fn.attr if isinstance(fn, ast.Attribute) else \
            (fn.id if isinstance(fn, ast.Name) else "")
        if tail == "block_until_ready":
            self._note_blocking("block_until_ready", None, path, line)
        elif tail == "wait" and isinstance(fn, ast.Attribute):
            cv = self._resolve_lock(fn.value)
            self._note_blocking("cv-wait", cv, path, line)
        elif tail == "join" and isinstance(fn, ast.Attribute) \
                and not node.args:
            # zero positional args: a thread/timer join, not str.join
            self._note_blocking("thread-join", None, path, line)
        elif text == "time.sleep":
            self._note_blocking("sleep", None, path, line)
        elif text.startswith(("ocp.", "orbax.")):
            self._note_blocking("orbax-io", None, path, line)
        elif tail == "_compiled_call":
            self._note_blocking("jit-dispatch", None, path, line)
        elif tail == "acquire":
            name = self._resolve_lock(fn.value) if \
                isinstance(fn, ast.Attribute) else None
            if name is not None:
                self._note_acquire(name, node)
        if text.startswith(("jnp.", "jax.numpy.")):
            self.info.jnp_calls.append((text, path, line))
        if text in ("jax.device_put", "device_put") and node.args:
            committed = len(node.args) >= 2 or any(
                kw.arg in ("device", "sharding", "src")
                for kw in node.keywords)
            if not committed:
                self.info.uncommitted_puts.append((path, line))


def _walk_functions(graph: LockGraph, mod: ModuleInfo, tree: ast.Module):
    """Register every function/method (top-level and one class deep),
    then walk each body."""
    def register(node, qual):
        info = FuncInfo(mod.name, qual, mod.path, node.lineno)
        mod.functions[qual] = info
        graph.functions[info.key] = info
        return info

    targets = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            targets.append((None, node, register(node, node.name)))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    targets.append(
                        (node.name, sub,
                         register(sub, f"{node.name}.{sub.name}")))
    return targets


def build_lockgraph(files: List[str]) -> LockGraph:
    graph = LockGraph()
    trees: Dict[str, ast.Module] = {}
    known_classes: Set[str] = set()

    for path in files:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        name = os.path.splitext(os.path.basename(path))[0]
        trees[name] = ast.parse(source, filename=path)
        graph.pragmas[path] = _scan_pragmas(path, source)
        graph.modules[name] = ModuleInfo(name, path)
        for node in trees[name].body:
            if isinstance(node, ast.ClassDef):
                known_classes.add(node.name)
                graph.class_owner[node.name] = name

    # pass 1: declarations; register functions (so cross-module call
    # resolution in pass 2 sees every target)
    walk_targets = []
    for name, mod in graph.modules.items():
        _Collector(mod, known_classes).visit(trees[name])
        walk_targets.append((mod, _walk_functions(graph, mod,
                                                  trees[name])))

    # pass 2: function bodies
    for mod, targets in walk_targets:
        for cls, node, info in targets:
            walker = _FuncWalker(graph, mod, cls, info)
            for stmt in node.body:
                walker.visit(stmt)

    # fixpoint: propagate acquisition + blocking sets through the call
    # graph (bounded: sets only grow, the lattice is finite)
    for info in graph.functions.values():
        info.trans_acquires = dict(info.acquires)
        info.trans_blocking = list(info.blocking)
    changed = True
    while changed:
        changed = False
        for info in graph.functions.values():
            for callee_key in info.calls:
                callee = graph.functions.get(callee_key)
                if callee is None:
                    continue
                for lock, site in callee.trans_acquires.items():
                    if lock not in info.trans_acquires:
                        info.trans_acquires[lock] = site
                        changed = True
                have = {(m, w) for m, w, _, _ in info.trans_blocking}
                for m, w, p, ln in callee.trans_blocking:
                    if (m, w) not in have:
                        info.trans_blocking.append((m, w, p, ln))
                        changed = True
    return graph
