"""ffsan — static concurrency & trace-stability analysis (ISSUE 16).

fflint's philosophy (millisecond static rejection instead of a
40-second runtime hang) applied to the two bug classes that have cost
this repo the most debugging time: lock-order deadlocks in the threaded
serving stack and silent jit retraces of warm programs.

Two source-level passes over ``flexflow_tpu/runtime`` (no model, no
strategy file, no jax import — pure ``ast``):

  concurrency     — extracts the lock graph (which locks each function
                    acquires, ``with self._lock``-style attributes
                    resolved through the declared hierarchy in
                    runtime/locks.py, propagated through the intra-repo
                    call graph) and reports acquisition-order
                    inversions, locks held across blocking calls, and
                    raw ``threading.Lock()`` creations that bypass the
                    registry.
  tracestability  — retrace hazards: un-committed ``device_put`` (the
                    PR-3 lesson: an uncommitted array feeding a jitted
                    program silently retraces it), shape-dependent
                    Python slicing of device arrays, and ``jnp.*``
                    dispatch while holding a runtime lock (op-by-op
                    tracing under a lock every tick).

By-design sites are waived with an end-of-line pragma::

    something()   # ffsan: allow(<code>) — why this is safe

and the ONE structural waiver both passes share: the ENGINE lock is
documented (serving.py tick contract) to be held across the whole tick
including the device dispatch, so engine-lock-across-dispatch is not a
finding. The runtime sanitizer (FF_SANITIZE=1, runtime/locks.py) is the
dynamic complement that catches what the AST cannot see.

Entry points:
  analyze_sources(paths, passes) -> Report       (library)
  python -m flexflow_tpu.analysis --passes concurrency,tracestability
                                                 (CLI, see __main__)
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional

from flexflow_tpu.analysis.report import Report, Violation

__all__ = ["SOURCE_PASSES", "analyze_sources", "default_paths"]

SOURCE_PASSES = ("concurrency", "tracestability")


def default_paths() -> List[str]:
    """The default analysis target: every .py file in
    flexflow_tpu/runtime (the threaded, jit-dispatching layer whose
    invariants these passes pin)."""
    here = os.path.dirname(os.path.abspath(__file__))
    runtime = os.path.join(os.path.dirname(os.path.dirname(here)),
                           "runtime")
    return [runtime]


def _py_files(paths: Iterable[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if name.endswith(".py"):
                    out.append(os.path.join(p, name))
        elif p.endswith(".py"):
            out.append(p)
        else:
            raise FileNotFoundError(
                f"ffsan: {p!r} is neither a directory nor a .py file")
    return out


def analyze_sources(paths: Optional[Iterable[str]] = None,
                    passes: Iterable[str] = SOURCE_PASSES) -> Report:
    """Run the requested source passes. Same contract as analyze():
    nothing raises on bad code — everything is a Violation; an internal
    analyzer fault degrades to an ``internal-error`` warning."""
    from flexflow_tpu.analysis.sanitize.concurrency import check_concurrency
    from flexflow_tpu.analysis.sanitize.lockgraph import build_lockgraph
    from flexflow_tpu.analysis.sanitize.tracestability import (
        check_tracestability)

    report = Report()
    files = _py_files(paths if paths is not None else default_paths())
    try:
        graph = build_lockgraph(files)
    except Exception as e:   # never let the analyzer crash the caller
        report.add(Violation(
            code="internal-error", pass_name="concurrency",
            severity="warning",
            message=f"lock-graph extraction crashed: "
                    f"{type(e).__name__}: {e}"))
        return report
    if "concurrency" in passes:
        _guard(report, "concurrency", lambda: check_concurrency(graph))
    if "tracestability" in passes:
        _guard(report, "tracestability",
               lambda: check_tracestability(graph))
    return report


def _guard(report: Report, name: str, fn) -> None:
    try:
        report.extend(fn())
    except Exception as e:
        report.add(Violation(
            code="internal-error", pass_name=name, severity="warning",
            message=f"{name} pass crashed: {type(e).__name__}: {e}"))
