"""ffsan ``concurrency`` pass — lock-order inversions, locks held
across blocking calls, and registry bypasses, from the static lock
graph alone.

Rules (codes):
  lock-order-inversion  (error)   An acquisition edge A -> B whose
        declared ranks (runtime/locks.py LOCK_RANKS) are not strictly
        increasing — the A->B/B->A deadlock shape. Edges are both
        syntactically nested ``with`` regions and calls made under a
        lock to a function whose TRANSITIVE acquisition set contains
        the inner lock. Same-name edges are skipped: an RLock
        re-acquire is legal, and two same-rank objects can't be told
        apart statically (the runtime sanitizer catches those).
  lock-across-blocking  (warning) A blocking operation — jit dispatch,
        ``block_until_ready``, cv ``wait``, thread ``join``,
        ``sleep``, orbax IO — reached while holding a registered lock:
        every other thread needing that lock stalls for the block's
        duration. A ``wait`` does not count against the cv it
        releases. Structural waiver: the ENGINE lock is documented
        (serving.py tick contract) to be held across the whole tick
        including device dispatch, so engine-held dispatch/sync is by
        design.
  raw-lock              (error)   A ``threading.Lock/RLock/Condition``
        created directly instead of through ``locks.make_*`` — the
        lock is invisible to the hierarchy, the sanitizer, and this
        pass.
  unknown-lock-name     (error)   A ``locks.make_*`` call whose name is
        not declared in LOCK_RANKS (or is not a string literal): the
        rank table is the single source of truth, so an undeclared
        name would crash at runtime — rejected here in milliseconds
        instead.
"""

from __future__ import annotations

from typing import List

from flexflow_tpu.analysis.report import Violation
from flexflow_tpu.analysis.sanitize.lockgraph import LockGraph
from flexflow_tpu.runtime.locks import LOCK_RANKS

# markers the documented engine tick contract waives (serving.py: ONE
# engine lock across the whole tick, device dispatch included)
_ENGINE_WAIVED = {"jit-dispatch", "block_until_ready"}


def _v(code, severity, message, path, line, qual=None) -> Violation:
    return Violation(code=code, pass_name="concurrency",
                     severity=severity, message=message, op_name=qual,
                     file=path, line=line)


def check_concurrency(graph: LockGraph) -> List[Violation]:
    out: List[Violation] = []
    seen = set()

    def emit(code, severity, msg, path, line, qual=None):
        key = (code, path, line, msg)
        if key in seen or graph.allowed_at(code, path, line):
            return
        seen.add(key)
        out.append(_v(code, severity, msg, path, line, qual))

    # ---- registry bypasses + undeclared names ----
    for mod in graph.modules.values():
        for kind, path, line in mod.raw_locks:
            emit("raw-lock", "error",
                 f"raw threading.{kind}() bypasses the lock registry — "
                 f"create it with locks.make_{kind.lower()}(<name>) so "
                 f"it carries a declared rank", path, line)
        for why, path, line in mod.unknown_factory:
            emit("unknown-lock-name", "error",
                 f"locks.make_* with a {why}: the hierarchy can only "
                 f"rank string-literal names from LOCK_RANKS",
                 path, line)
        for scope, table in (
                [("module", mod.global_locks)]
                + [(cls, c["attr_locks"])
                   for cls, c in mod.classes.items()]):
            for var, name in table.items():
                if name not in LOCK_RANKS:
                    emit("unknown-lock-name", "error",
                         f"lock {var!r} ({scope}) uses undeclared name "
                         f"{name!r}; declare it in "
                         f"runtime/locks.py LOCK_RANKS",
                         mod.path, 1)

    # ---- acquisition-order inversions ----
    for info in graph.functions.values():
        for outer, inner, path, line in info.edges:
            _check_edge(emit, info.qualname, outer, inner, path, line,
                        via=None)
        for held, callee_key, text, path, line in info.calls_under:
            callee = graph.functions.get(callee_key) \
                if callee_key else None
            if callee is None:
                continue
            for inner, site in callee.trans_acquires.items():
                if graph.allowed_at("lock-order-inversion",
                                    site[0], site[1]):
                    continue
                for outer in held:
                    _check_edge(emit, info.qualname, outer, inner,
                                path, line,
                                via=f"{text} -> {callee.qualname} "
                                    f"({site[0].rsplit('/', 1)[-1]}:"
                                    f"{site[1]})")

    # ---- locks held across blocking calls ----
    for info in graph.functions.values():
        for held, marker, waived, path, line in info.held_blocking:
            _check_blocking(emit, info.qualname, held, marker, waived,
                            path, line, via=None)
        for held, callee_key, text, path, line in info.calls_under:
            callee = graph.functions.get(callee_key) \
                if callee_key else None
            if callee is None:
                continue
            for marker, waived, bpath, bline in callee.trans_blocking:
                if graph.allowed_at("lock-across-blocking",
                                    bpath, bline):
                    continue
                _check_blocking(
                    emit, info.qualname, held, marker, waived, path,
                    line,
                    via=f"{text} -> {bpath.rsplit('/', 1)[-1]}:{bline}")
    return out


def _check_edge(emit, qual, outer, inner, path, line, via):
    if outer == inner:
        return
    ro, ri = LOCK_RANKS.get(outer), LOCK_RANKS.get(inner)
    if ro is None or ri is None or ri > ro:
        return
    chain = f" via {via}" if via else ""
    emit("lock-order-inversion", "error",
         f"acquires {inner!r}(rank {ri}) while holding {outer!r}"
         f"(rank {ro}){chain}: the declared order is strictly "
         f"increasing rank — another thread nesting them the other way "
         f"deadlocks", path, line, qual)


def _check_blocking(emit, qual, held, marker, waived, path, line, via):
    still_held = [h for h in held if h != waived]
    if not still_held:
        return
    if still_held == ["engine"] and marker in _ENGINE_WAIVED:
        return      # documented engine tick contract
    chain = f" via {via}" if via else ""
    emit("lock-across-blocking", "warning",
         f"{marker} while holding {still_held}{chain}: every thread "
         f"needing {'that lock' if len(still_held) == 1 else 'them'} "
         f"stalls for the block's duration — release first, or pragma "
         f"the contract", path, line, qual)
