"""ffsan ``tracestability`` pass — retrace hazards the repo has
relearned at runtime four times (PRs 3/7/10/11), rejected statically.

Rules (codes):
  uncommitted-device-put (warning)  ``jax.device_put(x)`` with no
        device/sharding: the result is UNCOMMITTED, and an uncommitted
        array feeding a jitted program gives it a different argument
        signature than a committed one — the warm program silently
        retraces (minutes on a real TPU) with recompile_count none the
        wiser. Pass the placement explicitly.
  shape-dependent-slice  (warning)  Python-level slicing of a device
        array with non-constant bounds in the serving/migration hot
        path (serving.py, router.py): each distinct bound is a new
        trace shape downstream, and the slice itself forces a transfer.
        Slice on the host (numpy) or inside the program (lax.dynamic_slice
        with a fixed output shape).
  jnp-under-lock         (warning)  A statement-level ``jnp.*`` call
        while holding a registered lock: op-by-op dispatch (tracing,
        potentially compiling) inside a critical section, every tick.
        ``jnp`` inside a nested ``def``/``lambda`` is NOT flagged —
        that's a traced-program builder, executed by jit, which is the
        correct place for jnp.

The runtime complement is the retrace sentinel (runtime/locks.py):
after ``warmup()`` any jit cache miss on a warm program is recorded
with the argument signature that diverged — what these rules catch
statically, it catches dynamically, including hazards that arrive via
data rather than code.
"""

from __future__ import annotations

from typing import List

from flexflow_tpu.analysis.report import Violation
from flexflow_tpu.analysis.sanitize.lockgraph import LockGraph

# rule 2's scope: the serving/migration hot paths named by the issue —
# a shape-dependent slice in offline checkpoint code is not a per-tick
# hazard
_HOT_MODULES = ("serving", "router")


def check_tracestability(graph: LockGraph) -> List[Violation]:
    out: List[Violation] = []
    seen = set()

    def emit(code, msg, path, line, qual):
        key = (code, path, line)
        if key in seen or graph.allowed_at(code, path, line):
            return
        seen.add(key)
        out.append(Violation(code=code, pass_name="tracestability",
                             severity="warning", message=msg,
                             op_name=qual, file=path, line=line))

    for info in graph.functions.values():
        for path, line in info.uncommitted_puts:
            emit("uncommitted-device-put",
                 "jax.device_put without a device/sharding leaves the "
                 "array UNCOMMITTED — feeding it to a warm jitted "
                 "program silently retraces it (the PR-3 bug class); "
                 "pass the placement explicitly",
                 path, line, info.qualname)
        if info.module in _HOT_MODULES:
            for var, path, line in info.device_slices:
                emit("shape-dependent-slice",
                     f"Python-level slice of device array {var!r} with "
                     f"non-constant bounds in a serving hot path: each "
                     f"distinct bound is a new downstream trace shape "
                     f"and the slice forces a device sync — slice on "
                     f"the host or via lax.dynamic_slice",
                     path, line, info.qualname)
        for held, callee_key, text, path, line in info.calls_under:
            if text.startswith(("jnp.", "jax.numpy.")):
                emit("jnp-under-lock",
                     f"{text} dispatched while holding {list(held)}: "
                     f"op-by-op tracing inside a critical section — "
                     f"move it into the jitted program (nested def) or "
                     f"outside the lock",
                     path, line, info.qualname)
    return out
