"""fflint performance pass: legal but pathological strategies.

Costs come from the same machine model the MCMC search ranks with
(`search/machine.py` ICI/DCN ring collectives, `search/cost_model.py`
resharding/memory accounting) so the lint and the search cannot disagree
about what is expensive. Four lints:

  reshard (info; warning above FF_LINT_RESHARD_WARN_BYTES, default 64 MiB)
      producer/consumer PartitionSpec mismatch on a graph edge implies a
      GSPMD collective; each is ranked by estimated bytes moved and priced
      through the ICI/DCN model (the reference's region-intersection comm
      tasks, simulator.cc:252-285).
  replicated-weight-no-fsdp (warning above FF_LINT_WEIGHT_WARN_BYTES,
      default 64 MiB)
      a weight replicated on every chip of a multi-chip mesh with
      FFConfig.fsdp_axis unset: per-chip HBM pays the full weight + grad +
      opt state with no sharding anywhere to claw it back.
  hbm-over-capacity (warning)
      per-chip footprint estimate (cost_model.op_mem_bytes accounting)
      exceeds the machine's HBM capacity — the config would OOM or swap
      into the reference simulator's memory-penalty regime
      (simulator.cc:595-620). The peak estimate is always emitted as an
      info note.
  pipeline-* (info/warning)
      per STAGE op: stage count, microbatches, bubble fraction
      ((n-1)/(m+n-1), GPipe) and per-stage FLOP imbalance when the layer
      count doesn't split evenly.
  dcn-collective (warning; two-tier meshes only)
      a PER-LAYER collective crosses a DCN-spanning axis
      (FFConfig.dcn_mesh_shape / MachineModel.dcn_axes): CONTRACT
      assigned to a DCN axis psums activations across hosts every layer
      (fwd + bwd), and a reshard edge whose implied collective crosses a
      DCN axis pays host bandwidth per layer. Data/STAGE across DCN is
      the intended hierarchical placement (one grad sync / one boundary
      hop per step) and is NOT flagged — the search's hierarchical
      candidates (search/driver.hierarchical_strategy) produce exactly
      that shape.
"""

from __future__ import annotations

import os
from typing import List, Optional

from flexflow_tpu.analysis.context import AnalysisContext
from flexflow_tpu.analysis.report import Violation
from flexflow_tpu.ops.base import InputOp
from flexflow_tpu.parallel.pconfig import STAGE

RESHARD_WARN_BYTES = float(
    os.environ.get("FF_LINT_RESHARD_WARN_BYTES", 64 * 1024 * 1024))
WEIGHT_WARN_BYTES = float(
    os.environ.get("FF_LINT_WEIGHT_WARN_BYTES", 64 * 1024 * 1024))


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GiB"


def check_perf(ctx: AnalysisContext, machine=None) -> List[Violation]:
    from flexflow_tpu.search.cost_model import CostModel

    cost = CostModel(ctx.model, ctx.mesh_shape, machine=machine)
    out: List[Violation] = []
    out.extend(_check_resharding(ctx, cost))
    out.extend(_check_replicated_weights(ctx, cost))
    out.extend(_check_hbm(ctx, cost))
    out.extend(_check_pipeline(ctx))
    out.extend(_check_dcn(ctx, cost))
    out.extend(_check_calibration(ctx))
    return out


def _dcn_axes(ctx: AnalysisContext, cost) -> set:
    """Mesh axes the machine model prices at the DCN tier (host-spanning
    and actually parallel on this mesh)."""
    return {ax for ax, hosts in (cost.machine.dcn_axes or {}).items()
            if int(hosts) > 1 and ctx.mesh_shape.get(ax, 1) > 1}


# ---- DCN-crossing per-layer collectives ------------------------------------

def _check_dcn(ctx: AnalysisContext, cost) -> List[Violation]:
    from flexflow_tpu.parallel.pconfig import CONTRACT

    dcn = _dcn_axes(ctx, cost)
    out: List[Violation] = []
    if not dcn:
        return out
    for op in ctx.ops:
        am = ctx.resolutions[op.name].axis_map or {}
        bad = [ax for ax, d in am.items() if d == CONTRACT and ax in dcn]
        if not bad:
            continue
        out_bytes = op.output_bytes()
        secs = sum(2.0 * cost.machine.all_reduce_time(
            out_bytes, ctx.mesh_shape[ax], ax) for ax in bad)
        out.append(Violation(
            code="dcn-collective", pass_name="perf", severity="warning",
            op_name=op.name, est_bytes=out_bytes, est_seconds=secs,
            message=(f"CONTRACT on DCN-spanning axes {bad}: the output "
                     f"psum ({_fmt_bytes(out_bytes)}, fwd + bwd mirror) "
                     f"crosses hosts EVERY layer, est {secs * 1e3:.3f} ms "
                     f"per step on this machine model — keep contract/TP "
                     f"inside ICI and put data/STAGE parallelism on the "
                     f"DCN axes (the hierarchical search candidate)")))
    return out


# ---- resharding ------------------------------------------------------------

def _check_resharding(ctx: AnalysisContext, cost) -> List[Violation]:
    out: List[Violation] = []
    dcn = _dcn_axes(ctx, cost)
    for op in ctx.ops:
        am = ctx.resolutions[op.name].axis_map
        for input_idx, t in enumerate(op.inputs):
            if t.owner_op is None or isinstance(t.owner_op, InputOp):
                continue
            src = t.owner_op.name
            if src not in ctx.resolutions:
                continue
            pam = t.owner_op.output_axis_map(ctx.resolutions[src].axis_map)
            try:
                want = op.input_axis_map(am, input_idx)
            except Exception:
                want = am
            secs = cost.resharding_time(pam, want, t)
            if secs <= 0.0:
                continue
            changed = [ax for ax in ctx.mesh_shape
                       if pam.get(ax) != want.get(ax)
                       and ctx.mesh_shape[ax] > 1]
            nbytes = t.volume() * cost.dtype_bytes
            sev = "warning" if nbytes >= RESHARD_WARN_BYTES else "info"
            crosses_dcn = sorted(set(changed) & dcn)
            if crosses_dcn:
                # a per-layer collective at DCN bandwidth is a strategy
                # bug regardless of size — always worth a warning
                sev = "warning"
            out.append(Violation(
                code="dcn-collective" if crosses_dcn else "reshard",
                pass_name="perf", severity=sev,
                op_name=op.name, est_bytes=nbytes, est_seconds=secs,
                message=(f"input {input_idx} ({t.name}, "
                         f"{_fmt_bytes(nbytes)}) arrives from {src!r} "
                         f"sharded {_fmt_map(pam)} but this op constrains "
                         f"{_fmt_map(want)} — GSPMD inserts a collective "
                         f"over axes {changed}, est "
                         f"{secs * 1e3:.3f} ms on this machine model"
                         + (f"; axes {crosses_dcn} SPAN HOSTS, so this "
                            f"per-layer collective runs at DCN bandwidth "
                            f"— keep it inside ICI (hierarchical "
                            f"candidate)" if crosses_dcn else ""))))
    # ranked: biggest implied collective first
    out.sort(key=lambda v: -(v.est_bytes or 0))
    return out


def _fmt_map(am) -> str:
    live = {ax: d for ax, d in (am or {}).items() if d is not None}
    return str(live) if live else "{replicated}"


# ---- replicated weights ----------------------------------------------------

def _check_replicated_weights(ctx: AnalysisContext, cost) -> List[Violation]:
    out: List[Violation] = []
    if ctx.num_devices <= 1:
        return out
    fsdp = getattr(getattr(ctx.model, "config", None), "fsdp_axis", "") or ""
    if fsdp and ctx.mesh_shape.get(fsdp, 1) > 1:
        return out  # FSDP will shard everything shardable
    for op in ctx.ops:
        am = ctx.resolutions[op.name].axis_map
        try:
            wp = op.weight_partition(am)
        except Exception:
            continue
        for spec in op.weight_specs():
            wbytes = 1
            for d in spec.shape:
                wbytes *= d
            wbytes *= cost.dtype_bytes
            pspec = wp.get(spec.name)
            sharded = pspec is not None and any(e is not None for e in pspec)
            if not sharded and wbytes >= WEIGHT_WARN_BYTES:
                out.append(Violation(
                    code="replicated-weight-no-fsdp", pass_name="perf",
                    severity="warning", op_name=op.name, est_bytes=wbytes,
                    message=(f"weight {spec.name!r} ({_fmt_bytes(wbytes)}) "
                             f"is replicated on all {ctx.num_devices} chips "
                             f"and FFConfig.fsdp_axis is unset — with grads "
                             f"+ optimizer state this costs "
                             f"~{_fmt_bytes(3 * wbytes)} HBM per chip; "
                             f"shard it (axis_map) or set fsdp_axis")))
    return out


# ---- HBM footprint ---------------------------------------------------------

def _check_hbm(ctx: AnalysisContext, cost) -> List[Violation]:
    """Per-chip footprint under the cost model's per-shard accounting,
    accumulated over the device blocks the placement lowering would use
    (cost_model.iteration_time's memory bookkeeping, minus the schedule).
    Each op is priced under ITS chosen memory-relief mode
    (ParallelConfig.mem_mode, set by the multi-objective search) so the
    lint audits what will actually run. When the footprint exceeds
    capacity but the relief modes COULD have brought it under cap, the
    over-capacity finding escalates to an error: the search had a legal
    under-cap alternative (remat/ZeRO/offload) it wasn't allowed to take
    — run the multi-objective search instead of the time-only one."""
    from flexflow_tpu.search.cost_model import MEM_MODES

    D = ctx.num_devices
    dev_mem = [0.0] * max(D, 1)
    relieved_mem = [0.0] * max(D, 1)  # per-op BEST mode: the relief floor
    for op in ctx.ops:
        res = ctx.resolutions[op.name]
        mode = getattr(res.pc, "mem_mode", "none") or "none"
        m = cost.op_mem_bytes(op, res.axis_map, mem_mode=mode)
        floor = min(cost.op_mem_bytes(op, res.axis_map, mem_mode=mm)
                    for mm in MEM_MODES)
        blk = ctx.op_block(res) or (0, max(D, 1))
        place, ndev = blk
        for d in range(place, min(place + ndev, len(dev_mem))):
            dev_mem[d] += m
            relieved_mem[d] += floor
    peak = max(dev_mem) if dev_mem else 0.0
    cap = cost.machine.hbm_bytes
    out = [Violation(
        code="hbm-footprint", pass_name="perf", severity="info",
        est_bytes=peak,
        message=(f"estimated peak per-chip HBM footprint "
                 f"{_fmt_bytes(peak)} of {_fmt_bytes(cap)} capacity "
                 f"({100 * peak / cap:.1f}%)"))]
    if peak > cap:
        worst = max(range(len(dev_mem)), key=lambda d: dev_mem[d])
        relieved_peak = max(relieved_mem) if relieved_mem else 0.0
        fixable = relieved_peak <= cap
        out.append(Violation(
            code="hbm-over-capacity", pass_name="perf",
            severity="error" if fixable else "warning",
            est_bytes=peak,
            message=(f"estimated per-chip HBM footprint {_fmt_bytes(peak)} "
                     f"exceeds capacity {_fmt_bytes(cap)} (worst chip "
                     f"{worst}) — the strategy would OOM or thrash; "
                     + (f"memory-relief modes (remat/ZeRO/offload) could "
                        f"bring it to {_fmt_bytes(relieved_peak)}, UNDER "
                        f"cap: use the multi-objective search "
                        f"(optimize_strategies_multi)" if fixable else
                        f"shard more weights/activations or grow the "
                        f"mesh"))))
    return out


# ---- simulator calibration -------------------------------------------------

def _check_calibration(ctx: AnalysisContext) -> List[Violation]:
    """Predicted-vs-observed step time (info): when the search stashed a
    predicted step time AND telemetry has observed real steps, report the
    ratio — the same drift signal cost_db.export_calibration publishes as
    the ff_csim_error_ratio gauge, surfaced in the lint report so a stale
    or miscalibrated cost DB is visible at compile time."""
    try:
        from flexflow_tpu.search.cost_db import _observed_step_p50
    except Exception:
        return []
    predicted = getattr(ctx.model, "_predicted_step_time", None)
    if not predicted:
        return []
    observed = _observed_step_p50()
    if not observed:
        return []
    ratio = float(predicted) / float(observed)
    return [Violation(
        code="csim-calibration", pass_name="perf", severity="info",
        est_seconds=float(predicted),
        message=(f"cost-model predicted step time {predicted * 1e3:.3f} ms "
                 f"vs telemetry-observed p50 {observed * 1e3:.3f} ms — "
                 f"ratio {ratio:.2f}x "
                 f"(1.0 = calibrated; persistent drift means the cost DB "
                 f"entries no longer match this machine — wipe or "
                 f"re-measure)"))]


# ---- pipeline --------------------------------------------------------------

def _check_pipeline(ctx: AnalysisContext) -> List[Violation]:
    out: List[Violation] = []
    for op in ctx.ops:
        am = ctx.resolutions[op.name].axis_map
        stage_axes = ctx.axes_of(am, STAGE)
        if not stage_axes:
            continue
        n = 1
        for ax in stage_axes:
            n *= ctx.mesh_shape.get(ax, 1)
        if n <= 1:
            continue
        layers = op.pipeline_stages()
        m = int(getattr(op, "num_microbatches", 0) or 0) or n
        bubble = (n - 1) / (m + n - 1)
        if layers > 0 and layers % n != 0:
            lo, hi = layers // n, -(-layers // n)
            out.append(Violation(
                code="pipeline-flop-imbalance", pass_name="perf",
                severity="warning", op_name=op.name,
                message=(f"{layers} layers over {n} stages splits "
                         f"{hi}/{lo} layers per stage — the {hi}-layer "
                         f"stages gate every tick, wasting "
                         f"~{100 * (1 - lo / hi):.0f}% of the light "
                         f"stages' FLOPs")))
        sev = "warning" if m < n else "info"
        out.append(Violation(
            code="pipeline-bubble", pass_name="perf", severity=sev,
            op_name=op.name,
            message=(f"{n} pipeline stages with {m} microbatches: bubble "
                     f"fraction (n-1)/(m+n-1) = {100 * bubble:.0f}%"
                     + (" — fewer microbatches than stages leaves chips "
                        "idle most of the schedule; raise num_microbatches"
                        if m < n else ""))))
    return out
