"""Model registry for the fflint CLI.

`python -m flexflow_tpu.analysis MODEL FILE` needs an op graph to check
the strategy against. MODEL is either a builtin name below (each builds a
representative graph from the models zoo, sized by --model-arg overrides)
or a `package.module:callable` spec whose callable receives the FFModel
and keyword args and adds ops to it. Graph building is pure Python shape
inference — no mesh, no tracing.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict


def _mlp(ff, batch=64, in_dim=64, hidden=256, out_dim=16, layers=2):
    x = ff.create_tensor([batch, in_dim], name="input")
    t = x
    for i in range(layers):
        t = ff.dense(t, hidden, name=f"fc_{i}")
    ff.dense(t, out_dim, name="head")


def _transformer(ff, batch=32, seq=64, hidden=128, layers=2, heads=4,
                 classes=16):
    from flexflow_tpu.models.transformer import build_encoder_classifier

    build_encoder_classifier(ff, batch, seq, hidden, layers, heads,
                             num_classes=classes)


def _dlrm(ff, batch=64, num_tables=8, embedding_size=64, dense_dim=64):
    from flexflow_tpu.models.dlrm import dlrm

    dlrm(ff, batch, embedding_size=embedding_size, num_tables=num_tables,
         dense_dim=dense_dim)


def _pipeline(ff, batch=32, seq=32, hidden=64, layers=4, heads=4,
              classes=16, num_microbatches=None):
    x = ff.create_tensor([batch, seq, hidden], name="input")
    t = ff.transformer_pipeline_stack(x, layers, heads,
                                      num_microbatches=num_microbatches,
                                      name="stack")
    t = ff.mean(t, dims=[1], name="pool")
    ff.dense(t, classes, name="head")


BUILTIN: Dict[str, Callable] = {
    "mlp": _mlp,
    "transformer": _transformer,
    "dlrm": _dlrm,
    "pipeline": _pipeline,
}


def build_model(spec: str, mesh_shape: Dict[str, int],
                model_args: Dict[str, object]):
    """Build an (uncompiled) FFModel for `spec` over `mesh_shape`."""
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.model import FFModel

    if spec in BUILTIN:
        builder = BUILTIN[spec]
    elif ":" in spec:
        mod_name, _, fn_name = spec.rpartition(":")
        builder = getattr(importlib.import_module(mod_name), fn_name)
    else:
        raise ValueError(
            f"unknown model {spec!r}: expected one of {sorted(BUILTIN)}, "
            f"'none', or a 'package.module:callable' spec")
    batch = int(model_args.get("batch", 0)) or None
    cfg = FFConfig(mesh_shape=dict(mesh_shape),
                   **({"batch_size": batch} if batch else {}))
    ff = FFModel(cfg)
    builder(ff, **model_args)
    return ff
