"""fflint legality pass: is the strategy executable on this mesh at all?

Checks graph properties the paper frames as checkable without execution
("Beyond Data and Model Parallelism": strategy legality is a property of
the op graph + device topology, not of a run): mesh-axis existence,
degree/axis-map agreement, device-block sanity, CONTRACT/STAGE
applicability, and shard divisibility. Every rule mirrors the exact spot
the runtime would otherwise fail (or silently degrade):

  axis-unknown / dim-out-of-range  -> executor.resolve_axis_map raises
  degree-mismatch                  -> resolve_axis_map's drift warning
  degree-unresolvable              -> resolve_axis_map raises
  device-block-too-small           -> placement.op_block raises
  device-block-overlap             -> groups would fight over chips
  contract-on-non-contraction      -> weight_partition produces garbage
  stage-on-non-pipelinable         -> STAGE axis silently ignored
  stage-indivisible                -> [L,...] stacked weights can't shard
  single-axis-dim                  -> ring/Ulysses lowering unbuildable
  shard-indivisible (warning)      -> XLA pads the shard SILENTLY
  device-count-mismatch (warning)  -> strategy.py save rewrites the list
"""

from __future__ import annotations

from typing import List

from flexflow_tpu.analysis.context import AnalysisContext, OpResolution
from flexflow_tpu.analysis.report import Violation
from flexflow_tpu.parallel.pconfig import CONTRACT, STAGE


def _v(code: str, op_name: str, message: str,
       severity: str = "error") -> Violation:
    return Violation(code=code, pass_name="legality", severity=severity,
                     op_name=op_name, message=message)


def check_legality(ctx: AnalysisContext) -> List[Violation]:
    out: List[Violation] = []
    blocks = {}  # op -> (place, ndev) for explicitly placed ops
    for op in ctx.ops:
        res = ctx.resolutions[op.name]
        out.extend(_check_degrees(ctx, res))
        out.extend(_check_device_ids(ctx, res))
        out.extend(_check_sentinels(ctx, res))
        out.extend(_check_divisibility(ctx, res))
        if _explicitly_placed(ctx, res):
            blk = ctx.op_block(res)
            if blk is not None:
                blocks[op.name] = blk
    out.extend(_check_block_overlap(blocks))
    return out


# ---- degrees ---------------------------------------------------------------

def _check_degrees(ctx: AnalysisContext, res: OpResolution) -> List[Violation]:
    """With an explicit axis_map AND a degree list, both must describe the
    same sharding on this mesh (the serializer keeps degrees for the
    reference text schema; pconfig.from_axis_map defines the mapping)."""
    if not (res.explicit_axis_map and res.pc.dims and res.from_table):
        return []
    from flexflow_tpu.parallel.pconfig import ParallelConfig

    ndims = res.op.outputs[0].num_dims
    # only derivable when every axis_map entry survived validation
    if res.axis_map != {k: v for k, v in (res.pc.axis_map or {}).items()}:
        return []
    try:
        expect = ParallelConfig.from_axis_map(
            ndims, ctx.mesh_shape, res.axis_map).dims
    except Exception:
        return []
    if tuple(expect) != tuple(res.pc.dims):
        return [_v("degree-mismatch", res.op.name,
                   f"axis_map {res.axis_map} on mesh {ctx.mesh_shape} gives "
                   f"degrees {tuple(expect)} but the strategy records "
                   f"{tuple(res.pc.dims)} — the mesh axis sizes changed "
                   f"since the strategy was written; the executor would run "
                   f"at the NEW degrees")]
    return []


# ---- device ids ------------------------------------------------------------

def _check_device_ids(ctx: AnalysisContext,
                      res: OpResolution) -> List[Violation]:
    out: List[Violation] = []
    ids = res.pc.device_ids
    if not ids or not res.from_table:
        return out
    D = ctx.num_devices
    bad = [i for i in ids if not (0 <= i < D)]
    if bad:
        out.append(_v("device-id-range", res.op.name,
                      f"device_ids {bad[:6]} outside the mesh's device range "
                      f"[0, {D}) (mesh {ctx.mesh_shape})"))
    if len(set(ids)) != len(ids):
        dups = sorted({i for i in ids if list(ids).count(i) > 1})
        out.append(_v("device-id-duplicate", res.op.name,
                      f"device_ids lists devices {dups[:6]} more than once"))
    parts = ctx.parts(res.axis_map)  # devices occupied, STAGE included
    n = res.pc.num_parts()
    has_stage = bool(ctx.axes_of(res.axis_map, STAGE))
    if 0 < len(ids) < parts:
        # the mesh-aware check: save (which has no mesh) accepts any
        # stage-multiple id count; HERE an undersized list is an error
        out.append(_v("device-block-too-small", res.op.name,
                      f"strategy places a {parts}-way sharded op on only "
                      f"{len(ids)} devices ({tuple(ids)[:4]}...) — the "
                      f"device block must hold the sharding"))
    elif len(ids) != n and not (has_stage and len(ids) % max(n, 1) == 0):
        # same consistency predicate as save_strategies_to_file: a
        # mismatched non-stage list is what save would rewrite
        out.append(_v("device-count-mismatch", res.op.name,
                      f"{len(ids)} device_ids for {n} partitions — "
                      f"strategy save would rewrite the list to "
                      f"range({n}); fix the entry or drop the ids",
                      severity="warning"))
    if ids and not bad and len(ids) > 1:
        lo, hi = min(ids), max(ids)
        if hi - lo + 1 != len(set(ids)):
            out.append(_v("device-block-gap", res.op.name,
                          f"device_ids [{lo}..{hi}] are non-contiguous — "
                          f"placement blocks are contiguous aligned ranges; "
                          f"the lowering would use [{lo}, {lo + len(ids)})",
                          severity="warning"))
    return out


def _explicitly_placed(ctx: AnalysisContext, res: OpResolution) -> bool:
    """Mirror of placement.has_placement's per-op rule."""
    if getattr(res.pc, "device_type", "TPU") == "CPU":
        return True
    ids = res.pc.device_ids
    return bool(ids and min(ids) > 0 and 0 < len(ids) < ctx.num_devices
                and ctx.num_devices % len(ids) == 0)


def _check_block_overlap(blocks) -> List[Violation]:
    """Two placed ops' blocks must nest exactly or be disjoint: a partial
    overlap means two sub-mesh programs contend for some chips while each
    also owns chips the other can't see — the per-group lowering has no
    schedule for that."""
    out: List[Violation] = []
    items = sorted(blocks.items(), key=lambda kv: kv[1])
    for i, (a_name, (a_p, a_n)) in enumerate(items):
        for b_name, (b_p, b_n) in items[i + 1:]:
            a_lo, a_hi = a_p, a_p + a_n
            b_lo, b_hi = b_p, b_p + b_n
            disjoint = a_hi <= b_lo or b_hi <= a_lo
            nested = (a_lo <= b_lo and b_hi <= a_hi) or \
                     (b_lo <= a_lo and a_hi <= b_hi)
            if not disjoint and not nested:
                out.append(_v("device-block-overlap", b_name,
                              f"device block [{b_lo},{b_hi}) partially "
                              f"overlaps {a_name!r}'s block [{a_lo},{a_hi}) "
                              f"— placement blocks must nest or be disjoint"))
    return out


# ---- CONTRACT / STAGE ------------------------------------------------------

def _check_sentinels(ctx: AnalysisContext,
                     res: OpResolution) -> List[Violation]:
    out: List[Violation] = []
    op = res.op
    contract_axes = ctx.axes_of(res.axis_map, CONTRACT)
    stage_axes = ctx.axes_of(res.axis_map, STAGE)
    if contract_axes and op.contract_size() is None:
        out.append(_v("contract-on-non-contraction", op.name,
                      f"axis_map marks {contract_axes} CONTRACT "
                      f"(row-parallel) but {type(op).__name__} has no "
                      f"contraction dim (contract_size() is None) — only "
                      f"weight-contraction ops (Linear, Conv2D) accept it"))
    if stage_axes:
        stages = op.pipeline_stages()
        if stages <= 0:
            out.append(_v("stage-on-non-pipelinable", op.name,
                          f"axis_map marks {stage_axes} STAGE (pipeline) but "
                          f"{type(op).__name__} exposes no pipeline_stages() "
                          f"— only stacked-layer ops "
                          f"(TransformerPipelineStack) accept it"))
        else:
            n = 1
            for ax in stage_axes:
                n *= ctx.mesh_shape.get(ax, 1)
            if n > 0 and stages % n != 0:
                out.append(_v("stage-indivisible", op.name,
                              f"STAGE axes {stage_axes} give {n} pipeline "
                              f"stages but the op stacks {stages} layers — "
                              f"{stages} % {n} != 0, so the [L, ...] stacked "
                              f"weights cannot shard into equal stages"))
    # dims the executor can shard over at most one axis (MHA seq dim)
    for d in op.single_axis_dims():
        axes = ctx.axes_of(res.axis_map, d)
        if len(axes) > 1:
            out.append(_v("single-axis-dim", op.name,
                          f"output dim {d} is sharded over {len(axes)} mesh "
                          f"axes {axes} but this op's lowering supports at "
                          f"most one axis on that dim"))
    return out


# ---- divisibility ----------------------------------------------------------

def _check_divisibility(ctx: AnalysisContext,
                        res: OpResolution) -> List[Violation]:
    """XLA pads non-divisible shards SILENTLY (GSPMD semantics) — correct
    numerics for most ops but wasted compute and, for ops that reduce over
    the padded dim, a latent numerics trap. Flag every tensor dim whose
    size doesn't divide by its shard degree."""
    out: List[Violation] = []
    op = res.op
    dims = op.outputs[0].dims
    for d in range(len(dims)):
        deg = ctx.dim_degree(res.axis_map, d)
        if deg > 1 and dims[d] % deg != 0:
            axes = ctx.axes_of(res.axis_map, d)
            out.append(_v("shard-indivisible", op.name,
                          f"output dim {d} (size {dims[d]}) does not divide "
                          f"by its shard degree {deg} (axes {axes}) — XLA "
                          f"will silently pad each shard to "
                          f"{-(-dims[d] // deg)}", severity="warning"))
    cdeg = ctx.dim_degree(res.axis_map, CONTRACT)
    if cdeg > 1:
        csize = op.contract_size()
        if csize is not None and csize % cdeg != 0:
            out.append(_v("shard-indivisible", op.name,
                          f"contraction dim (size {csize}) does not divide "
                          f"by the CONTRACT degree {cdeg} — XLA will "
                          f"silently pad the weight shards",
                          severity="warning"))
    return out
